"""Admission chain (apiserver pkg/admission + the kube-apiserver plugin
order, pkg/kubeapiserver/options/plugins.go:64).

Writes pass through mutating then validating admission before they touch the
store maps. The in-tree plugins modeled (the scheduling-relevant subset):

- NamespaceLifecycle: reject creates into a terminating/absent namespace
- DefaultPriority (Priority admission): resolve priorityClassName → priority
- ResourceQuota: reject pod creates that would exceed the namespace's quota
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

from ..api import resource as resource_api
from ..api.types import ObjectMeta, Pod, ResourceQuota


class AdmissionError(Exception):
    """403: request denied by an admission plugin."""

    def __init__(self, plugin: str, message: str):
        super().__init__(f"admission denied by {plugin}: {message}")
        self.plugin = plugin


class AdmissionPlugin:
    name = "plugin"

    def admit(self, store, kind: str, obj) -> None:
        """Mutating pass; may modify obj in place."""

    def validate(self, store, kind: str, obj) -> None:
        """Validating pass; raise AdmissionError to reject. Must be free of
        store-state side effects — it runs outside the store lock and before
        the duplicate-key check."""

    def admit_update(self, store, kind: str, old, obj) -> None:
        """Mutating pass for updates (operation=UPDATE attributes)."""

    def validate_update(self, store, kind: str, old, obj) -> None:
        """Validating pass for updates; raise AdmissionError to reject."""

    def charge(self, store, kind: str, obj) -> Optional[Callable[[], None]]:
        """Stateful admission step, run under the store lock immediately
        before the object is inserted (after the duplicate-key check), so a
        failed create never leaves residue. Returns an undo callable (or
        None); raise AdmissionError to reject."""
        return None


class NamespaceLifecycle(AdmissionPlugin):
    """plugin/namespace/lifecycle: no creates into terminating or absent
    namespaces. An absent namespace is tolerated for the default namespace
    only (the reference bootstraps ``default`` at startup; we model that as
    lazy tolerance rather than pre-seeding every test store)."""

    name = "NamespaceLifecycle"

    NAMESPACED_KINDS = ("Pod", "Service", "ReplicaSet", "StatefulSet",
                        "Deployment", "DaemonSet", "Job")

    def validate(self, store, kind: str, obj) -> None:
        if kind not in self.NAMESPACED_KINDS:
            return
        ns = store.namespaces.get(obj.meta.namespace)
        if ns is None:
            if obj.meta.namespace != "default":
                raise AdmissionError(
                    self.name, f"namespace {obj.meta.namespace!r} not found")
            return
        if ns.meta.deletion_timestamp:
            raise AdmissionError(self.name,
                                 f"namespace {obj.meta.namespace} is terminating")


class DefaultPriority(AdmissionPlugin):
    """plugin/pkg/admission/priority: resolve priorityClassName to the
    numeric priority at create time (what the scheduler sorts on)."""

    name = "Priority"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        if pod.spec.priority_class_name and not pod.spec.priority:
            pc = store.priority_classes.get(pod.spec.priority_class_name)
            if pc is None:
                raise AdmissionError(
                    self.name, f"no PriorityClass {pod.spec.priority_class_name!r}")
            pod.spec.priority = pc.value


def pod_quota_usage(pod: Pod) -> dict:
    """The quota dimensions a pod consumes (quota/v1/evaluator/core)."""
    cpu = sum(resource_api.canonical("cpu", c.requests.get("cpu", 0))
              for c in pod.spec.containers)
    mem = sum(resource_api.canonical("memory", c.requests.get("memory", 0))
              for c in pod.spec.containers)
    return {"pods": 1, "requests.cpu": cpu, "requests.memory": mem}


class ResourceQuotaAdmission(AdmissionPlugin):
    """plugin/pkg/admission/resourcequota: a pod create must fit every
    matching quota's remaining headroom. The check+charge runs atomically in
    ``charge()`` under the store lock after the duplicate-key check — usage is
    updated only when the write will succeed, and rolled back if a later step
    fails (mirrors the reference, where usage moves only on successful
    writes; the controller reconciles drift from deletes)."""

    name = "ResourceQuota"

    def _matching(self, store, obj):
        return [rq for rq in store.resource_quotas.values()
                if rq.meta.namespace == obj.meta.namespace]

    def _check(self, rq: ResourceQuota, usage: dict) -> None:
        for dim, amount in usage.items():
            if dim not in rq.hard:
                continue
            if rq.used.get(dim, 0) + amount > rq.hard[dim]:
                raise AdmissionError(
                    self.name,
                    f"exceeded quota {rq.meta.name}: {dim} "
                    f"used {rq.used.get(dim, 0)} + requested {amount} > hard {rq.hard[dim]}",
                )

    def validate(self, store, kind: str, obj) -> None:
        # Advisory read-only fast-fail; the authoritative check is charge().
        if kind != "Pod":
            return
        usage = pod_quota_usage(obj)
        for rq in self._matching(store, obj):
            self._check(rq, usage)

    def charge(self, store, kind: str, obj) -> Optional[Callable[[], None]]:
        if kind != "Pod":
            return None
        usage = pod_quota_usage(obj)
        quotas = self._matching(store, obj)
        # Check ALL matching quotas before charging ANY, so a later quota's
        # rejection never strands charges on an earlier one.
        for rq in quotas:
            self._check(rq, usage)
        for rq in quotas:
            for dim, amount in usage.items():
                if dim in rq.hard:
                    rq.used[dim] = rq.used.get(dim, 0) + amount

        def undo() -> None:
            for rq in quotas:
                for dim, amount in usage.items():
                    if dim in rq.hard:
                        rq.used[dim] = rq.used.get(dim, 0) - amount

        return undo


class LimitRanger(AdmissionPlugin):
    """plugin/pkg/admission/limitranger: apply the namespace's LimitRange
    Container defaults to unset requests/limits, then validate against
    min/max. Runs before quota so defaulted requests are what quota sees
    (plugins.go:64 ordering)."""

    name = "LimitRanger"

    def _ranges(self, store, ns: str):
        return [lr for lr in store.limit_ranges.values()
                if lr.meta.namespace == ns]

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        mutated = False
        for lr in self._ranges(store, pod.meta.namespace):
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in pod.spec.containers:
                    for r, q in item.default_request.items():
                        if r not in c.requests:
                            c.requests[r] = q
                            mutated = True
                    for r, q in item.default.items():
                        c.limits.setdefault(r, q)
        if mutated:
            pod.invalidate_request_cache()

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        for lr in self._ranges(store, pod.meta.namespace):
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in pod.spec.containers:
                    for r, q in item.max.items():
                        req = c.requests.get(r)
                        if req is not None and (
                            resource_api.canonical(r, req) > resource_api.canonical(r, q)
                        ):
                            raise AdmissionError(
                                self.name,
                                f"container {c.name!r} {r} request {req} exceeds max {q}")
                    for r, q in item.min.items():
                        req = c.requests.get(r)
                        if req is not None and (
                            resource_api.canonical(r, req) < resource_api.canonical(r, q)
                        ):
                            raise AdmissionError(
                                self.name,
                                f"container {c.name!r} {r} request {req} below min {q}")


# default NoExecute toleration window (defaulttolerationseconds/admission.go)
DEFAULT_TOLERATION_SECONDS = 300
NOT_READY_TAINT = "node.kubernetes.io/not-ready"
UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"


class DefaultTolerationSeconds(AdmissionPlugin):
    """plugin/pkg/admission/defaulttolerationseconds: every pod gets
    NoExecute tolerations for not-ready/unreachable (bounded eviction delay)
    unless it already tolerates them."""

    name = "DefaultTolerationSeconds"

    def admit(self, store, kind: str, obj) -> None:
        from ..api.types import TOLERATION_OP_EXISTS, Taint, Toleration

        if kind != "Pod":
            return
        pod: Pod = obj
        extra = []
        for key in (NOT_READY_TAINT, UNREACHABLE_TAINT):
            taint = Taint(key=key, effect="NoExecute")
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                extra.append(Toleration(
                    key=key, operator=TOLERATION_OP_EXISTS, effect="NoExecute",
                    toleration_seconds=DEFAULT_TOLERATION_SECONDS))
        if extra:
            pod.spec.tolerations = tuple(pod.spec.tolerations) + tuple(extra)


class PodNodeSelector(AdmissionPlugin):
    """plugin/pkg/admission/podnodeselector: merge the namespace's
    ``scheduler.alpha.kubernetes.io/node-selector`` annotation into the
    pod's nodeSelector; conflicts reject the pod."""

    name = "PodNodeSelector"
    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    @staticmethod
    def _parse(ann: str) -> dict:
        out = {}
        for part in ann.split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
        return out

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        ns = store.namespaces.get(pod.meta.namespace)
        ann = ns.meta.annotations.get(self.ANNOTATION) if ns is not None else None
        if not ann:
            return
        for k, v in self._parse(ann).items():
            cur = pod.spec.node_selector.get(k)
            if cur is not None and cur != v:
                raise AdmissionError(
                    self.name,
                    f"pod node selector {k}={cur} conflicts with namespace selector {k}={v}")
            pod.spec.node_selector[k] = v


class TaintNodesByCondition(AdmissionPlugin):
    """plugin/pkg/admission/nodetaint: a node that registers not-Ready gets
    the ``node.kubernetes.io/not-ready`` NoSchedule taint at create time;
    the node lifecycle controller removes it when the node reports Ready.
    (The reference taints every new node unconditionally and relies on the
    controller to lift it within a heartbeat; we taint exactly the nodes
    whose initial status is not Ready — same steady state without requiring
    a controller tick between create and first scheduling cycle.)"""

    name = "TaintNodesByCondition"

    def admit(self, store, kind: str, obj) -> None:
        from ..api.types import Taint

        if kind != "Node":
            return
        node = obj
        if node.status.ready:
            return
        if any(t.key == NOT_READY_TAINT and t.effect == "NoSchedule"
               for t in node.spec.taints):
            return
        node.spec.taints = tuple(node.spec.taints) + (
            Taint(key=NOT_READY_TAINT, effect="NoSchedule"),)


class ServiceAccountAdmission(AdmissionPlugin):
    """plugin/pkg/admission/serviceaccount: default the pod's
    serviceAccountName to ``default`` and require that it exists. The
    per-namespace ``default`` ServiceAccount is tolerated as absent (the
    serviceaccount controller creates it lazily; requiring it would couple
    every pod create to a controller tick)."""

    name = "ServiceAccount"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        if not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"

    def admit_update(self, store, kind: str, old, obj) -> None:
        if kind != "Pod":
            return
        if not obj.spec.service_account_name:
            # inherit the stored pod's SA (an apply that omits the field must
            # not strip the identity); fall back to the default
            obj.spec.service_account_name = (
                old.spec.service_account_name if old is not None else ""
            ) or "default"

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        sa_name = obj.spec.service_account_name
        if sa_name == "default":
            return
        key = f"{obj.meta.namespace}/{sa_name}"
        if key not in store.service_accounts:
            raise AdmissionError(
                self.name, f"service account {key!r} not found")

    def validate_update(self, store, kind: str, old, obj) -> None:
        # the reference checks SA existence only on CREATE; re-checking an
        # unchanged identity would brick status updates of running pods
        # after their SA is deleted
        if (kind == "Pod" and old is not None
                and obj.spec.service_account_name != old.spec.service_account_name):
            self.validate(store, kind, obj)


# pod-security.kubernetes.io/enforce levels (pod-security-admission/api)
PS_PRIVILEGED = "privileged"
PS_BASELINE = "baseline"
PS_RESTRICTED = "restricted"
PS_ENFORCE_LABEL = "pod-security.kubernetes.io/enforce"


class PodSecurity(AdmissionPlugin):
    """plugin/pkg/admission/podsecurity: enforce the namespace's Pod
    Security Standards level (the ``pod-security.kubernetes.io/enforce``
    namespace label). Modeled checks per level:

    - baseline: no hostNetwork/hostPID/hostIPC, no privileged containers,
      no non-default capability adds beyond the baseline allowlist
    - restricted: baseline + runAsNonRoot required + privilege escalation
      must be explicitly disallowed + capabilities must drop ALL (adding
      back only NET_BIND_SERVICE)
    """

    name = "PodSecurity"

    _BASELINE_CAPS = {"AUDIT_WRITE", "CHOWN", "DAC_OVERRIDE", "FOWNER",
                      "FSETID", "KILL", "MKNOD", "NET_BIND_SERVICE",
                      "SETFCAP", "SETGID", "SETPCAP", "SETUID", "SYS_CHROOT"}

    def _level(self, store, ns_name: str) -> str:
        ns = store.namespaces.get(ns_name)
        if ns is None:
            return PS_PRIVILEGED
        return ns.meta.labels.get(PS_ENFORCE_LABEL, PS_PRIVILEGED)

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        level = self._level(store, obj.meta.namespace)
        if level == PS_PRIVILEGED:
            return
        spec = obj.spec
        if spec.host_network or spec.host_pid or spec.host_ipc:
            raise AdmissionError(
                self.name, f"host namespaces are not allowed at level {level}")
        pod_sc = spec.security_context
        for c in list(spec.containers) + list(spec.init_containers):
            sc = c.security_context
            if sc is not None:
                if sc.privileged:
                    raise AdmissionError(
                        self.name,
                        f"privileged container {c.name!r} not allowed at level {level}")
                extra = set(sc.capabilities_add) - self._BASELINE_CAPS
                if extra:
                    raise AdmissionError(
                        self.name,
                        f"container {c.name!r} adds forbidden capabilities {sorted(extra)}")
            if level == PS_RESTRICTED:
                run_as_non_root = None
                if sc is not None and sc.run_as_non_root is not None:
                    run_as_non_root = sc.run_as_non_root
                elif pod_sc is not None and pod_sc.run_as_non_root is not None:
                    run_as_non_root = pod_sc.run_as_non_root
                if not run_as_non_root:
                    raise AdmissionError(
                        self.name,
                        f"container {c.name!r} must set runAsNonRoot at level restricted")
                if sc is None or sc.allow_privilege_escalation is not False:
                    raise AdmissionError(
                        self.name,
                        f"container {c.name!r} must set allowPrivilegeEscalation: "
                        "false at level restricted")
                if sc.capabilities_add and set(sc.capabilities_add) != {"NET_BIND_SERVICE"}:
                    raise AdmissionError(
                        self.name,
                        f"container {c.name!r} may only add NET_BIND_SERVICE at "
                        "level restricted")
                if "ALL" not in sc.capabilities_drop:
                    raise AdmissionError(
                        self.name,
                        f"container {c.name!r} must drop ALL capabilities at "
                        "level restricted")

    def validate_update(self, store, kind: str, old, obj) -> None:
        # status-subresource exemption (upstream pod-security only gates
        # security-relevant spec changes): a pod whose spec is unchanged must
        # keep updating even after its namespace's enforce level tightens
        if kind == "Pod" and old is not None and obj.spec == old.spec:
            return
        self.validate(store, kind, obj)


class NodeRestriction(AdmissionPlugin):
    """plugin/pkg/admission/noderestriction: a kubelet identity
    (``system:node:<name>``) may only write its own Node object, pods bound
    to itself, and its own Lease. Other users are unrestricted."""

    name = "NodeRestriction"

    @staticmethod
    def _node_of(user: str) -> Optional[str]:
        return user[len("system:node:"):] if user.startswith("system:node:") else None

    def _check(self, store, kind: str, obj, old=None) -> None:
        me = self._node_of(store.request_user())
        if me is None:
            return
        if kind == "Node":
            if obj.meta.name != me:
                raise AdmissionError(
                    self.name, f"node {me!r} may not modify node {obj.meta.name!r}")
        elif kind == "Pod":
            target = obj.spec.node_name or (old.spec.node_name if old is not None else "")
            if target != me:
                raise AdmissionError(
                    self.name, f"node {me!r} may only write pods bound to itself")
        elif kind == "Lease":
            if obj.meta.name != me:
                raise AdmissionError(
                    self.name, f"node {me!r} may not write lease {obj.meta.name!r}")

    def validate(self, store, kind: str, obj) -> None:
        self._check(store, kind, obj)

    def validate_update(self, store, kind: str, old, obj) -> None:
        self._check(store, kind, obj, old)


class DefaultStorageClass(AdmissionPlugin):
    """plugin/pkg/admission/storage/storageclass/setdefault: a PVC created
    without a storage class gets the cluster default (the StorageClass
    carrying the is-default-class annotation)."""

    name = "DefaultStorageClass"

    def admit(self, store, kind: str, obj) -> None:
        from ..api.types import ANNOTATION_DEFAULT_STORAGE_CLASS

        if kind != "PersistentVolumeClaim" or obj.storage_class:
            return
        for sc in store.storage_classes.values():
            if sc.meta.annotations.get(ANNOTATION_DEFAULT_STORAGE_CLASS) == "true":
                obj.storage_class = sc.meta.name
                return


class PersistentVolumeClaimResize(AdmissionPlugin):
    """plugin/pkg/admission/storage/persistentvolume/resize: growing a bound
    PVC requires its StorageClass to allow volume expansion; shrinking is
    never allowed."""

    name = "PersistentVolumeClaimResize"

    def validate_update(self, store, kind: str, old, obj) -> None:
        if kind != "PersistentVolumeClaim" or old is None:
            return
        if obj.requested_bytes < old.requested_bytes:
            raise AdmissionError(self.name, "persistent volume claims cannot shrink")
        if obj.requested_bytes > old.requested_bytes:
            sc = store.storage_classes.get(old.storage_class)
            if sc is None or not sc.allow_volume_expansion:
                raise AdmissionError(
                    self.name,
                    f"storage class {old.storage_class!r} does not allow volume expansion")


class OwnerReferencesPermissionEnforcement(AdmissionPlugin):
    """plugin/pkg/admission/gc: setting blockOwnerDeletion on an owner
    reference requires permission to update the owner's finalizers
    (checked through the store's authorizer when one is configured)."""

    name = "OwnerReferencesPermissionEnforcement"

    def _check(self, store, obj, old=None) -> None:
        if store.authorizer is None:
            return
        refs = getattr(obj.meta, "owner_references", ()) or ()
        old_blocking = set()
        if old is not None:
            old_blocking = {(r.kind, r.name) for r in
                            (getattr(old.meta, "owner_references", ()) or ())
                            if getattr(r, "block_owner_deletion", False)}
        for r in refs:
            if not getattr(r, "block_owner_deletion", False):
                continue
            if (r.kind, r.name) in old_blocking:
                continue  # pre-existing blocks are not re-checked
            user = store.request_user()
            # prefer the group-aware check when the authorizer offers one
            # (RBAC group bindings + system:masters must count here too)
            check = getattr(store.authorizer, "allowed_for", None)
            if check is not None:
                ok = check(user, store.request_groups(), "update", r.kind,
                           r.name, subresource="finalizers")
            else:
                ok = store.authorizer.allowed(user, "update", r.kind, r.name,
                                              subresource="finalizers")
            if not ok:
                raise AdmissionError(
                    self.name,
                    f"user {user!r} may not set blockOwnerDeletion on "
                    f"{r.kind}/{r.name} (cannot update finalizers)")

    def validate(self, store, kind: str, obj) -> None:
        self._check(store, obj)

    def validate_update(self, store, kind: str, old, obj) -> None:
        self._check(store, obj, old)


@dataclasses.dataclass
class WebhookConfiguration:
    """admissionregistration.k8s.io webhook configuration, reduced: a kind
    filter plus either an in-process callable or a localhost URL speaking
    AdmissionReview-shaped JSON (apiserver pkg/admission/plugin/webhook)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    kinds: Tuple[str, ...] = ()          # () = all kinds
    namespaces: Tuple[str, ...] = ()     # () = all namespaces
    handler: Optional[Callable] = None   # (review: dict) -> dict
    url: str = ""                        # http://127.0.0.1:PORT/... alternative
    failure_policy: str = "Fail"         # or "Ignore"

    def matches(self, kind: str, obj) -> bool:
        if self.kinds and kind not in self.kinds:
            return False
        if self.namespaces:
            ns = getattr(obj.meta, "namespace", "")
            if ns not in self.namespaces:
                return False
        return True


def _call_webhook(cfg: WebhookConfiguration, review: dict) -> dict:
    if cfg.handler is not None:
        return cfg.handler(review)
    import json
    import urllib.request

    from ..api.codec import to_wire

    wire = dict(review, object=to_wire(review["object"]))
    req = urllib.request.Request(
        cfg.url, data=json.dumps(wire).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5.0) as resp:
        return json.loads(resp.read().decode())


def _apply_patch(obj, patch: List[dict]) -> None:
    """JSON-patch subset (add/replace/remove on /-separated paths) applied to
    the typed object: {"op": "replace", "path": "/spec/priority", "value": 7}.
    Intermediate segments may cross dicts; a malformed patch rejects the
    request as an AdmissionError rather than escaping as a raw attribute
    error."""
    for p in patch:
        parts = [s for s in p.get("path", "").split("/") if s]
        if not parts:
            continue
        try:
            target = obj
            for attr in parts[:-1]:
                target = target[attr] if isinstance(target, dict) else getattr(target, attr)
            leaf = parts[-1]
            op = p.get("op", "replace")
            if op == "remove":
                if isinstance(target, dict):
                    target.pop(leaf, None)
                else:
                    setattr(target, leaf, None)
            elif op in ("add", "replace"):
                if isinstance(target, dict):
                    target[leaf] = p.get("value")
                else:
                    setattr(target, leaf, p.get("value"))
            else:
                raise ValueError(f"unsupported op {op!r}")
        except AdmissionError:
            raise
        except Exception as exc:  # noqa: BLE001 — malformed webhook patch
            raise AdmissionError(
                "MutatingAdmissionWebhook",
                f"invalid patch {p.get('op', 'replace')} {p.get('path')!r}: {exc}",
            ) from exc


class MutatingAdmissionWebhook(AdmissionPlugin):
    """MutatingAdmissionWebhook: dispatch matching webhook configurations
    registered as MutatingWebhookConfiguration objects; their patches are
    applied to the object before validation."""

    name = "MutatingAdmissionWebhook"
    _configs_attr = "mutating_webhooks"
    _mutating = True

    def _dispatch(self, store, kind: str, obj, operation: str, old=None) -> None:
        for cfg in list(getattr(store, self._configs_attr).values()):
            if not isinstance(cfg, WebhookConfiguration) or not cfg.matches(kind, obj):
                continue
            review = {
                "kind": kind,
                "operation": operation,
                "name": getattr(obj.meta, "name", ""),
                "namespace": getattr(obj.meta, "namespace", ""),
                "object": obj,
            }
            try:
                resp = _call_webhook(cfg, review)
                if not isinstance(resp, dict):
                    raise TypeError(f"webhook returned {type(resp).__name__}, not a dict")
            except Exception as exc:  # noqa: BLE001 — webhook transport failure
                if cfg.failure_policy == "Ignore":
                    continue
                raise AdmissionError(self.name, f"webhook call failed: {exc}") from exc
            if not resp.get("allowed", True):
                raise AdmissionError(
                    self.name, resp.get("message", "denied by webhook"))
            if self._mutating and resp.get("patch"):
                _apply_patch(obj, resp["patch"])
                if hasattr(obj, "invalidate_request_cache"):
                    # the patch may have touched container requests/limits;
                    # a stale cached resource_request would feed the
                    # scheduler and quota silently (ADVICE r3)
                    obj.invalidate_request_cache()

    def admit(self, store, kind: str, obj) -> None:
        self._dispatch(store, kind, obj, "CREATE")

    def admit_update(self, store, kind: str, old, obj) -> None:
        self._dispatch(store, kind, obj, "UPDATE", old)


class ValidatingAdmissionWebhook(MutatingAdmissionWebhook):
    """ValidatingAdmissionWebhook: same dispatch, validating phase, no
    patches applied (runs after every mutating plugin, plugins.go order)."""

    name = "ValidatingAdmissionWebhook"
    _configs_attr = "validating_webhooks"
    _mutating = False

    def admit(self, store, kind: str, obj) -> None:  # move to validate phase
        pass

    def admit_update(self, store, kind: str, old, obj) -> None:
        pass

    def validate(self, store, kind: str, obj) -> None:
        self._dispatch(store, kind, obj, "CREATE")

    def validate_update(self, store, kind: str, old, obj) -> None:
        self._dispatch(store, kind, obj, "UPDATE", old)


class NamespaceAutoProvision(AdmissionPlugin):
    """plugin/pkg/admission/namespace/autoprovision (default-off): create
    the namespace on first use instead of rejecting."""

    name = "NamespaceAutoProvision"

    def admit(self, store, kind: str, obj) -> None:
        ns = getattr(getattr(obj, "meta", None), "namespace", "")
        if not ns or kind in store.CLUSTER_SCOPED_KINDS or kind == "Namespace":
            return
        if ns not in store.namespaces:  # the map is keyed by name
            from ..api.types import Namespace, ObjectMeta

            store.create_namespace(Namespace(meta=ObjectMeta(name=ns)))


class NamespaceExists(AdmissionPlugin):
    """plugin/pkg/admission/namespace/exists (default-off): reject objects
    in namespaces that don't exist (lifecycle covers the terminating case)."""

    name = "NamespaceExists"

    def validate(self, store, kind: str, obj) -> None:
        ns = getattr(getattr(obj, "meta", None), "namespace", "")
        if not ns or kind in store.CLUSTER_SCOPED_KINDS or kind == "Namespace":
            return
        if ns == "default" or ns == "kube-system":
            return  # always-present namespaces
        if ns not in store.namespaces:  # the map is keyed by name
            raise AdmissionError(self.name, f"namespace {ns!r} does not exist")


class SecurityContextDeny(AdmissionPlugin):
    """plugin/pkg/admission/securitycontext/scdeny (default-off): reject
    pods that set privileged/user/group security context fields."""

    name = "SecurityContextDeny"

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        contexts = [obj.spec.security_context] + [
            c.security_context for c in list(obj.spec.containers)
            + list(obj.spec.init_containers)]
        for sc in contexts:
            if sc is None:
                continue
            if getattr(sc, "privileged", False) \
                    or getattr(sc, "run_as_user", None) is not None:
                # `is not None`, NOT truthiness: runAsUser 0 (root) is
                # exactly the request this plugin exists to reject
                raise AdmissionError(
                    self.name, "pod sets a forbidden securityContext field")


class LimitPodHardAntiAffinityTopology(AdmissionPlugin):
    """plugin/pkg/admission/antiaffinity (default-off): required pod
    anti-affinity may only use the hostname topology key."""

    name = "LimitPodHardAntiAffinityTopology"
    _HOSTNAME = "kubernetes.io/hostname"

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod" or obj.spec.affinity is None:
            return
        anti = obj.spec.affinity.pod_anti_affinity
        for term in (anti.required if anti is not None else ()):
            if term.topology_key != self._HOSTNAME:
                raise AdmissionError(
                    self.name,
                    f"required pod anti-affinity topologyKey "
                    f"{term.topology_key!r} must be {self._HOSTNAME}")


class AlwaysPullImages(AdmissionPlugin):
    """plugin/pkg/admission/alwayspullimages (default-off): force
    imagePullPolicy=Always so credentials are re-checked per node."""

    name = "AlwaysPullImages"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            c.image_pull_policy = "Always"


class ExtendedResourceToleration(AdmissionPlugin):
    """plugin/pkg/admission/extendedresourcetoleration (default-off): pods
    requesting extended resources get matching tolerations automatically."""

    name = "ExtendedResourceToleration"
    _STANDARD = {"cpu", "memory", "ephemeral-storage", "pods"}

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        from ..api.types import Toleration

        extended = set()
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            for res in list(c.requests) + list(c.limits):
                if res not in self._STANDARD and "/" in res:
                    extended.add(res)
        have = {t.key for t in obj.spec.tolerations}
        add = tuple(
            Toleration(key=res, operator="Exists", effect="NoSchedule")
            for res in sorted(extended) if res not in have)
        if add:
            obj.spec.tolerations = tuple(obj.spec.tolerations) + add


class StorageObjectInUseProtection(AdmissionPlugin):
    """plugin/pkg/admission/storage/storageobjectinuseprotection: add the
    protection finalizers the pvc/pv-protection controllers manage."""

    name = "StorageObjectInUseProtection"
    PVC_FINALIZER = "kubernetes.io/pvc-protection"
    PV_FINALIZER = "kubernetes.io/pv-protection"

    def admit(self, store, kind: str, obj) -> None:
        if kind == "PersistentVolumeClaim":
            if self.PVC_FINALIZER not in obj.meta.finalizers:
                obj.meta.finalizers = tuple(obj.meta.finalizers) + (self.PVC_FINALIZER,)
        elif kind == "PersistentVolume":
            if self.PV_FINALIZER not in obj.meta.finalizers:
                obj.meta.finalizers = tuple(obj.meta.finalizers) + (self.PV_FINALIZER,)


class RuntimeClassAdmission(AdmissionPlugin):
    """plugin/pkg/admission/runtimeclass: default spec.overhead (and merge
    scheduling constraints) from the pod's RuntimeClass."""

    name = "RuntimeClass"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod" or not obj.spec.runtime_class_name:
            return
        rc = getattr(store, "runtime_classes", {}).get(obj.spec.runtime_class_name)
        if rc is None:
            raise AdmissionError(
                self.name,
                f"RuntimeClass {obj.spec.runtime_class_name!r} not found")
        if rc.overhead and not obj.spec.overhead:
            obj.spec.overhead = dict(rc.overhead)
            obj.invalidate_request_cache()
        if rc.node_selector:
            merged = dict(rc.node_selector)
            merged.update(obj.spec.node_selector)
            obj.spec.node_selector = merged
        if rc.tolerations:
            have = {(t.key, t.effect) for t in obj.spec.tolerations}
            obj.spec.tolerations = tuple(obj.spec.tolerations) + tuple(
                t for t in rc.tolerations if (t.key, t.effect) not in have)

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod" or not obj.spec.runtime_class_name:
            return
        rc = getattr(store, "runtime_classes", {}).get(obj.spec.runtime_class_name)
        if rc is not None and rc.overhead and obj.spec.overhead != rc.overhead:
            # admit() defaulted an EMPTY overhead; anything still different
            # means the client asserted its own value — reject (the
            # reference rejects any pod whose overhead differs)
            raise AdmissionError(self.name, "pod overhead must match RuntimeClass")


def _signer_authorized(store, verb: str, signer: str, subresource: str) -> bool:
    """Authorize a CSR state transition against the store's authorizer with
    the REQUEST's full identity (user + groups — allowed() would drop the
    groups and defeat the system:masters bypass). No authorizer = open."""
    authz = getattr(store, "authorizer", None)
    if authz is None:
        return True
    user = store.request_user()
    groups = store.request_groups()
    if hasattr(authz, "allowed_for"):
        return authz.allowed_for(user, groups, verb,
                                 "CertificateSigningRequest", signer,
                                 subresource=subresource)
    return authz.allowed(user, verb, "CertificateSigningRequest", signer,
                         subresource=subresource)


class CertificateApproval(AdmissionPlugin):
    """plugin/pkg/admission/certificates/approval: flipping a CSR to
    approved/denied requires authorization on the signer (the approve
    subresource verb)."""

    name = "CertificateApproval"

    def validate_update(self, store, kind: str, old, obj) -> None:
        if kind != "CertificateSigningRequest" or old is None:
            return
        if (obj.approved, obj.denied) == (old.approved, old.denied):
            return
        if not _signer_authorized(store, "approve", obj.signer_name, "approval"):
            raise AdmissionError(
                self.name, f"user {store.request_user()!r} may not approve "
                f"CSRs for signer {obj.signer_name!r}")


class CertificateSigning(AdmissionPlugin):
    """plugin/pkg/admission/certificates/signing: populating the issued
    certificate requires authorization on the signer (the sign verb)."""

    name = "CertificateSigning"

    def validate_update(self, store, kind: str, old, obj) -> None:
        if kind != "CertificateSigningRequest" or old is None:
            return
        if obj.certificate == old.certificate:
            return
        if not _signer_authorized(store, "sign", obj.signer_name, "status"):
            raise AdmissionError(
                self.name, f"user {store.request_user()!r} may not sign "
                f"CSRs for signer {obj.signer_name!r}")


class CertificateSubjectRestriction(AdmissionPlugin):
    """plugin/pkg/admission/certificates/subjectrestriction: reject
    kube-apiserver-client CSRs for the system:masters group."""

    name = "CertificateSubjectRestriction"

    def validate(self, store, kind: str, obj) -> None:
        if kind != "CertificateSigningRequest":
            return
        if obj.signer_name == "kubernetes.io/kube-apiserver-client" \
                and "system:masters" in obj.groups:
            raise AdmissionError(
                self.name,
                "CSRs for system:masters are not allowed through this signer")


class DenyServiceExternalIPs(AdmissionPlugin):
    """plugin/pkg/admission/denyserviceexternalips: externalIPs are a
    traffic-interception hazard; new ones are rejected outright."""

    name = "DenyServiceExternalIPs"

    def validate(self, store, kind: str, obj) -> None:
        if kind == "Service" and getattr(obj, "external_ips", ()):
            raise AdmissionError(self.name, "externalIPs are not allowed")

    def validate_update(self, store, kind: str, old, obj) -> None:
        if kind != "Service":
            return
        new_ips = set(getattr(obj, "external_ips", ()))
        old_ips = set(getattr(old, "external_ips", ()) if old is not None else ())
        if new_ips - old_ips:
            raise AdmissionError(self.name, "may not add externalIPs")


class EventRateLimit(AdmissionPlugin):
    """plugin/pkg/admission/eventratelimit (default-off): token-bucket
    limits on Event API writes per namespace, so a crash-looping component
    cannot flood the store (the reference's Namespace-type limit)."""

    name = "EventRateLimit"

    def __init__(self, qps: float = 50.0, burst: int = 100, now_fn=None):
        import time as _time

        self.qps = qps
        self.burst = burst
        self.now_fn = now_fn or _time.monotonic
        self._buckets: Dict[str, Tuple[float, float]] = {}  # ns -> (tokens, last)

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Event":
            return
        ns = obj.meta.namespace
        now = self.now_fn()
        tokens, last = self._buckets.get(ns, (float(self.burst), now))
        tokens = min(float(self.burst), tokens + (now - last) * self.qps)
        if tokens < 1.0:
            raise AdmissionError(
                self.name, f"event rate limit exceeded for namespace {ns!r}")
        self._buckets[ns] = (tokens - 1.0, now)

    def validate_update(self, store, kind: str, old, obj) -> None:
        # series count bumps consume the same budget (the reference limits
        # all Event requests, not just creates)
        self.validate(store, kind, obj)


class DefaultIngressClass(AdmissionPlugin):
    """plugin/pkg/admission/network/defaultingressclass: an Ingress created
    without ingressClassName gets the cluster default (the IngressClass
    carrying the is-default-class annotation); two marked defaults reject."""

    name = "DefaultIngressClass"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Ingress" or obj.ingress_class_name:
            return
        from ..api.types import ANNOTATION_DEFAULT_INGRESS_CLASS

        defaults = [ic for ic in getattr(store, "ingress_classes", {}).values()
                    if ic.meta.annotations.get(ANNOTATION_DEFAULT_INGRESS_CLASS)
                    == "true"]
        if len(defaults) > 1:
            raise AdmissionError(
                self.name, "multiple IngressClasses marked as default")
        if defaults:
            obj.ingress_class_name = defaults[0].meta.name


class AlwaysAdmit(AdmissionPlugin):
    """plugin/pkg/admission/admit (default-off, deprecated no-op)."""

    name = "AlwaysAdmit"


class AlwaysDeny(AdmissionPlugin):
    """plugin/pkg/admission/deny (default-off): reject everything."""

    name = "AlwaysDeny"

    def validate(self, store, kind: str, obj) -> None:
        raise AdmissionError(self.name, "admission denied by AlwaysDeny")


def all_ordered_plugins() -> List[AdmissionPlugin]:
    """The full AllOrderedPlugins roster (plugins.go:64) in reference
    order — incl. the default-OFF plugins a config may enable."""
    return [AlwaysAdmit(), NamespaceAutoProvision(), NamespaceLifecycle(),
            NamespaceExists(), SecurityContextDeny(),
            LimitPodHardAntiAffinityTopology(), LimitRanger(),
            ServiceAccountAdmission(), NodeRestriction(),
            TaintNodesByCondition(), AlwaysPullImages(), PodSecurity(),
            PodNodeSelector(), DefaultPriority(), DefaultTolerationSeconds(),
            EventRateLimit(), ExtendedResourceToleration(), DefaultStorageClass(),
            StorageObjectInUseProtection(),
            OwnerReferencesPermissionEnforcement(),
            PersistentVolumeClaimResize(), RuntimeClassAdmission(),
            CertificateApproval(), CertificateSigning(),
            CertificateSubjectRestriction(), DefaultIngressClass(),
            DenyServiceExternalIPs(),
            MutatingAdmissionWebhook(), ValidatingAdmissionWebhook(),
            ResourceQuotaAdmission(), AlwaysDeny()]


def default_chain() -> List[AdmissionPlugin]:
    """AllOrderedPlugins (plugins.go:64), reduced to the modeled set and kept
    in the reference's relative order: NamespaceLifecycle → LimitRanger →
    ServiceAccount → NodeRestriction → TaintNodesByCondition → PodSecurity →
    PodNodeSelector → Priority → DefaultTolerationSeconds →
    DefaultStorageClass → PersistentVolumeClaimResize →
    OwnerReferencesPermissionEnforcement → MutatingAdmissionWebhook →
    ValidatingAdmissionWebhook → ResourceQuota (always last)."""
    return [NamespaceLifecycle(), LimitRanger(), ServiceAccountAdmission(),
            NodeRestriction(), TaintNodesByCondition(), PodSecurity(),
            PodNodeSelector(), DefaultPriority(), DefaultTolerationSeconds(),
            DefaultStorageClass(), StorageObjectInUseProtection(),
            PersistentVolumeClaimResize(),
            OwnerReferencesPermissionEnforcement(), RuntimeClassAdmission(),
            CertificateApproval(), CertificateSigning(),
            CertificateSubjectRestriction(), DefaultIngressClass(),
            # DenyServiceExternalIPs is default-OFF upstream
            # (DefaultOffAdmissionPlugins) — available via
            # all_ordered_plugins(), not enabled here
            MutatingAdmissionWebhook(), ValidatingAdmissionWebhook(),
            ResourceQuotaAdmission()]


class AdmissionChain:
    def __init__(self, plugins: Optional[List[AdmissionPlugin]] = None):
        self.plugins = plugins if plugins is not None else default_chain()

    def run(self, store, kind: str, obj) -> None:
        for p in self.plugins:
            p.admit(store, kind, obj)
        for p in self.plugins:
            p.validate(store, kind, obj)

    def run_update(self, store, kind: str, old, obj) -> None:
        for p in self.plugins:
            p.admit_update(store, kind, old, obj)
        for p in self.plugins:
            p.validate_update(store, kind, old, obj)

    def charge(self, store, kind: str, obj) -> Callable[[], None]:
        """Run every plugin's stateful charge step (under the store lock);
        returns a combined undo. If any plugin rejects, charges already made
        by earlier plugins are rolled back before the error propagates."""
        undos: List[Callable[[], None]] = []

        def undo_all() -> None:
            for u in reversed(undos):
                u()

        for p in self.plugins:
            try:
                u = p.charge(store, kind, obj)
            except AdmissionError:
                undo_all()
                raise
            if u is not None:
                undos.append(u)
        return undo_all
