"""Admission chain (apiserver pkg/admission + the kube-apiserver plugin
order, pkg/kubeapiserver/options/plugins.go:64).

Writes pass through mutating then validating admission before they touch the
store maps. The in-tree plugins modeled (the scheduling-relevant subset):

- NamespaceLifecycle: reject creates into a terminating/absent namespace
- DefaultPriority (Priority admission): resolve priorityClassName → priority
- ResourceQuota: reject pod creates that would exceed the namespace's quota
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..api import resource as resource_api
from ..api.types import Pod, ResourceQuota


class AdmissionError(Exception):
    """403: request denied by an admission plugin."""

    def __init__(self, plugin: str, message: str):
        super().__init__(f"admission denied by {plugin}: {message}")
        self.plugin = plugin


class AdmissionPlugin:
    name = "plugin"

    def admit(self, store, kind: str, obj) -> None:
        """Mutating pass; may modify obj in place."""

    def validate(self, store, kind: str, obj) -> None:
        """Validating pass; raise AdmissionError to reject. Must be free of
        store-state side effects — it runs outside the store lock and before
        the duplicate-key check."""

    def charge(self, store, kind: str, obj) -> Optional[Callable[[], None]]:
        """Stateful admission step, run under the store lock immediately
        before the object is inserted (after the duplicate-key check), so a
        failed create never leaves residue. Returns an undo callable (or
        None); raise AdmissionError to reject."""
        return None


class NamespaceLifecycle(AdmissionPlugin):
    """plugin/namespace/lifecycle: no creates into terminating or absent
    namespaces. An absent namespace is tolerated for the default namespace
    only (the reference bootstraps ``default`` at startup; we model that as
    lazy tolerance rather than pre-seeding every test store)."""

    name = "NamespaceLifecycle"

    NAMESPACED_KINDS = ("Pod", "Service", "ReplicaSet", "StatefulSet",
                        "Deployment", "DaemonSet", "Job")

    def validate(self, store, kind: str, obj) -> None:
        if kind not in self.NAMESPACED_KINDS:
            return
        ns = store.namespaces.get(obj.meta.namespace)
        if ns is None:
            if obj.meta.namespace != "default":
                raise AdmissionError(
                    self.name, f"namespace {obj.meta.namespace!r} not found")
            return
        if ns.meta.deletion_timestamp:
            raise AdmissionError(self.name,
                                 f"namespace {obj.meta.namespace} is terminating")


class DefaultPriority(AdmissionPlugin):
    """plugin/pkg/admission/priority: resolve priorityClassName to the
    numeric priority at create time (what the scheduler sorts on)."""

    name = "Priority"

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        if pod.spec.priority_class_name and not pod.spec.priority:
            pc = store.priority_classes.get(pod.spec.priority_class_name)
            if pc is None:
                raise AdmissionError(
                    self.name, f"no PriorityClass {pod.spec.priority_class_name!r}")
            pod.spec.priority = pc.value


def pod_quota_usage(pod: Pod) -> dict:
    """The quota dimensions a pod consumes (quota/v1/evaluator/core)."""
    cpu = sum(resource_api.canonical("cpu", c.requests.get("cpu", 0))
              for c in pod.spec.containers)
    mem = sum(resource_api.canonical("memory", c.requests.get("memory", 0))
              for c in pod.spec.containers)
    return {"pods": 1, "requests.cpu": cpu, "requests.memory": mem}


class ResourceQuotaAdmission(AdmissionPlugin):
    """plugin/pkg/admission/resourcequota: a pod create must fit every
    matching quota's remaining headroom. The check+charge runs atomically in
    ``charge()`` under the store lock after the duplicate-key check — usage is
    updated only when the write will succeed, and rolled back if a later step
    fails (mirrors the reference, where usage moves only on successful
    writes; the controller reconciles drift from deletes)."""

    name = "ResourceQuota"

    def _matching(self, store, obj):
        return [rq for rq in store.resource_quotas.values()
                if rq.meta.namespace == obj.meta.namespace]

    def _check(self, rq: ResourceQuota, usage: dict) -> None:
        for dim, amount in usage.items():
            if dim not in rq.hard:
                continue
            if rq.used.get(dim, 0) + amount > rq.hard[dim]:
                raise AdmissionError(
                    self.name,
                    f"exceeded quota {rq.meta.name}: {dim} "
                    f"used {rq.used.get(dim, 0)} + requested {amount} > hard {rq.hard[dim]}",
                )

    def validate(self, store, kind: str, obj) -> None:
        # Advisory read-only fast-fail; the authoritative check is charge().
        if kind != "Pod":
            return
        usage = pod_quota_usage(obj)
        for rq in self._matching(store, obj):
            self._check(rq, usage)

    def charge(self, store, kind: str, obj) -> Optional[Callable[[], None]]:
        if kind != "Pod":
            return None
        usage = pod_quota_usage(obj)
        quotas = self._matching(store, obj)
        # Check ALL matching quotas before charging ANY, so a later quota's
        # rejection never strands charges on an earlier one.
        for rq in quotas:
            self._check(rq, usage)
        for rq in quotas:
            for dim, amount in usage.items():
                if dim in rq.hard:
                    rq.used[dim] = rq.used.get(dim, 0) + amount

        def undo() -> None:
            for rq in quotas:
                for dim, amount in usage.items():
                    if dim in rq.hard:
                        rq.used[dim] = rq.used.get(dim, 0) - amount

        return undo


class LimitRanger(AdmissionPlugin):
    """plugin/pkg/admission/limitranger: apply the namespace's LimitRange
    Container defaults to unset requests/limits, then validate against
    min/max. Runs before quota so defaulted requests are what quota sees
    (plugins.go:64 ordering)."""

    name = "LimitRanger"

    def _ranges(self, store, ns: str):
        return [lr for lr in store.limit_ranges.values()
                if lr.meta.namespace == ns]

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        for lr in self._ranges(store, pod.meta.namespace):
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in pod.spec.containers:
                    for r, q in item.default_request.items():
                        c.requests.setdefault(r, q)
                    for r, q in item.default.items():
                        c.limits.setdefault(r, q)

    def validate(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        for lr in self._ranges(store, pod.meta.namespace):
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in pod.spec.containers:
                    for r, q in item.max.items():
                        req = c.requests.get(r)
                        if req is not None and (
                            resource_api.canonical(r, req) > resource_api.canonical(r, q)
                        ):
                            raise AdmissionError(
                                self.name,
                                f"container {c.name!r} {r} request {req} exceeds max {q}")
                    for r, q in item.min.items():
                        req = c.requests.get(r)
                        if req is not None and (
                            resource_api.canonical(r, req) < resource_api.canonical(r, q)
                        ):
                            raise AdmissionError(
                                self.name,
                                f"container {c.name!r} {r} request {req} below min {q}")


# default NoExecute toleration window (defaulttolerationseconds/admission.go)
DEFAULT_TOLERATION_SECONDS = 300
NOT_READY_TAINT = "node.kubernetes.io/not-ready"
UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"


class DefaultTolerationSeconds(AdmissionPlugin):
    """plugin/pkg/admission/defaulttolerationseconds: every pod gets
    NoExecute tolerations for not-ready/unreachable (bounded eviction delay)
    unless it already tolerates them."""

    name = "DefaultTolerationSeconds"

    def admit(self, store, kind: str, obj) -> None:
        from ..api.types import TOLERATION_OP_EXISTS, Taint, Toleration

        if kind != "Pod":
            return
        pod: Pod = obj
        extra = []
        for key in (NOT_READY_TAINT, UNREACHABLE_TAINT):
            taint = Taint(key=key, effect="NoExecute")
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                extra.append(Toleration(
                    key=key, operator=TOLERATION_OP_EXISTS, effect="NoExecute",
                    toleration_seconds=DEFAULT_TOLERATION_SECONDS))
        if extra:
            pod.spec.tolerations = tuple(pod.spec.tolerations) + tuple(extra)


class PodNodeSelector(AdmissionPlugin):
    """plugin/pkg/admission/podnodeselector: merge the namespace's
    ``scheduler.alpha.kubernetes.io/node-selector`` annotation into the
    pod's nodeSelector; conflicts reject the pod."""

    name = "PodNodeSelector"
    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    @staticmethod
    def _parse(ann: str) -> dict:
        out = {}
        for part in ann.split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
        return out

    def admit(self, store, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod: Pod = obj
        ns = store.namespaces.get(pod.meta.namespace)
        ann = ns.meta.annotations.get(self.ANNOTATION) if ns is not None else None
        if not ann:
            return
        for k, v in self._parse(ann).items():
            cur = pod.spec.node_selector.get(k)
            if cur is not None and cur != v:
                raise AdmissionError(
                    self.name,
                    f"pod node selector {k}={cur} conflicts with namespace selector {k}={v}")
            pod.spec.node_selector[k] = v


def default_chain() -> List[AdmissionPlugin]:
    """AllOrderedPlugins, reduced to the modeled set (plugins.go:64 order:
    lifecycle → node selector → priority → tolerations → limits →
    ... → quota last)."""
    return [NamespaceLifecycle(), PodNodeSelector(), DefaultPriority(),
            DefaultTolerationSeconds(), LimitRanger(), ResourceQuotaAdmission()]


class AdmissionChain:
    def __init__(self, plugins: Optional[List[AdmissionPlugin]] = None):
        self.plugins = plugins if plugins is not None else default_chain()

    def run(self, store, kind: str, obj) -> None:
        for p in self.plugins:
            p.admit(store, kind, obj)
        for p in self.plugins:
            p.validate(store, kind, obj)

    def charge(self, store, kind: str, obj) -> Callable[[], None]:
        """Run every plugin's stateful charge step (under the store lock);
        returns a combined undo. If any plugin rejects, charges already made
        by earlier plugins are rolled back before the error propagates."""
        undos: List[Callable[[], None]] = []

        def undo_all() -> None:
            for u in reversed(undos):
                u()

        for p in self.plugins:
            try:
                u = p.charge(store, kind, obj)
            except AdmissionError:
                undo_all()
                raise
            if u is not None:
                undos.append(u)
        return undo_all
