"""In-process cluster state store: the apiserver + informer seam.

Collapses the reference's L2 (apiserver REST + watch cache) and L3 (client-go
reflector/informer) into one in-process component: typed object maps with
synchronous watch-handler fan-out.  The scheduler wires handlers exactly like
eventhandlers.go:249 addAllEventHandlers; tests and the perf harness drive
mutations exactly like the integration suite drives a real apiserver.

The binding subresource (``bind``) mirrors BindingREST.Create
(pkg/registry/core/pod/storage/storage.go:169): it transactionally sets
``pod.spec.node_name`` and fails if the pod is already bound or gone.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..api.types import (
    Binding,
    CSINode,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PriorityClass,
    ReplicaSet,
    ReplicationController,
    Service,
    StatefulSet,
    StorageClass,
)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Handler = Callable[[str, Optional[object], Optional[object]], None]


class Conflict(Exception):
    """409: binding/update conflict (optimistic concurrency failure)."""


class NotFound(Exception):
    """404."""


class ClusterStore:
    def __init__(self):
        self._lock = threading.RLock()
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.namespaces: Dict[str, Namespace] = {}
        self.pdbs: Dict[str, PodDisruptionBudget] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.pvs: Dict[str, PersistentVolume] = {}
        self.pvcs: Dict[str, PersistentVolumeClaim] = {}
        self.storage_classes: Dict[str, StorageClass] = {}
        self.csinodes: Dict[str, CSINode] = {}
        self.services: Dict[str, Service] = {}
        self.replication_controllers: Dict[str, ReplicationController] = {}
        self.replica_sets: Dict[str, ReplicaSet] = {}
        self.stateful_sets: Dict[str, StatefulSet] = {}
        self._handlers: Dict[str, List[Handler]] = {}
        self._rv = 0

    def add_event_handler(self, kind: str, handler: Handler) -> None:
        self._handlers.setdefault(kind, []).append(handler)

    def _notify(self, kind: str, event: str, old, new) -> None:
        for h in self._handlers.get(kind, []):
            h(event, old, new)

    def _bump(self, obj) -> None:
        self._rv += 1
        obj.meta.resource_version = self._rv

    # ------------------------------------------------------------- nodes

    def create_node(self, node: Node) -> None:
        with self._lock:
            self._bump(node)
            self.nodes[node.meta.name] = node
        self._notify("Node", ADDED, None, node)

    def update_node(self, node: Node) -> None:
        with self._lock:
            old = self.nodes.get(node.meta.name)
            if old is None:
                raise NotFound(node.meta.name)
            self._bump(node)
            self.nodes[node.meta.name] = node
        self._notify("Node", MODIFIED, old, node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            old = self.nodes.pop(name, None)
        if old is not None:
            self._notify("Node", DELETED, old, None)

    # ------------------------------------------------------------- pods

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            self._bump(pod)
            self.pods[pod.key()] = pod
        self._notify("Pod", ADDED, None, pod)

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            old = self.pods.get(pod.key())
            if old is None:
                raise NotFound(pod.key())
            self._bump(pod)
            self.pods[pod.key()] = pod
        self._notify("Pod", MODIFIED, old, pod)

    def delete_pod(self, key: str) -> None:
        with self._lock:
            old = self.pods.pop(key, None)
        if old is not None:
            self._notify("Pod", DELETED, old, None)

    def get_pod(self, key: str) -> Optional[Pod]:
        with self._lock:
            return self.pods.get(key)

    def bind(self, binding: Binding) -> None:
        """POST pods/{name}/binding (storage.go:169)."""
        with self._lock:
            pod = self.pods.get(binding.pod_key)
            if pod is None:
                raise NotFound(binding.pod_key)
            if pod.spec.node_name:
                raise Conflict(f"pod {binding.pod_key} is already bound to {pod.spec.node_name}")
            old = pod
            new = pod.clone()
            new.spec.node_name = binding.node_name
            new.status.phase = "Running"
            self._bump(new)
            self.pods[binding.pod_key] = new
        self._notify("Pod", MODIFIED, old, new)

    def update_pod_nominated_node(self, key: str, node_name: str) -> None:
        """pod.Status.NominatedNodeName persist (schedule_one.go:846)."""
        with self._lock:
            pod = self.pods.get(key)
            if pod is None:
                raise NotFound(key)
            old = pod
            new = pod.clone()
            new.status.nominated_node_name = node_name
            self._bump(new)
            self.pods[key] = new
        self._notify("Pod", MODIFIED, old, new)

    # ------------------------------------------------------------- misc kinds

    def create_namespace(self, ns: Namespace) -> None:
        with self._lock:
            self.namespaces[ns.meta.name] = ns
        self._notify("Namespace", ADDED, None, ns)

    def ns_labels(self, name: str) -> Dict[str, str]:
        with self._lock:
            ns = self.namespaces.get(name)
            return dict(ns.meta.labels) if ns else {}

    def create_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._lock:
            self.pdbs[pdb.meta.key()] = pdb
        self._notify("PodDisruptionBudget", ADDED, None, pdb)

    def list_pdbs(self) -> List[PodDisruptionBudget]:
        with self._lock:
            return list(self.pdbs.values())

    def create_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self.priority_classes[pc.meta.name] = pc
        self._notify("PriorityClass", ADDED, None, pc)

    # ------------------------------------------------------------- workload kinds
    # (SelectorSpread's owner lookup, helper/spread.go DefaultSelector)

    def create_service(self, svc: Service) -> None:
        with self._lock:
            self._bump(svc)
            self.services[svc.meta.key()] = svc
        self._notify("Service", ADDED, None, svc)

    def list_services(self, namespace: str) -> List[Service]:
        with self._lock:
            return [s for s in self.services.values() if s.meta.namespace == namespace]

    def create_replication_controller(self, rc: ReplicationController) -> None:
        with self._lock:
            self._bump(rc)
            self.replication_controllers[rc.meta.key()] = rc
        self._notify("ReplicationController", ADDED, None, rc)

    def get_replication_controller(self, key: str) -> Optional[ReplicationController]:
        with self._lock:
            return self.replication_controllers.get(key)

    def create_replica_set(self, rs: ReplicaSet) -> None:
        with self._lock:
            self._bump(rs)
            self.replica_sets[rs.meta.key()] = rs
        self._notify("ReplicaSet", ADDED, None, rs)

    def get_replica_set(self, key: str) -> Optional[ReplicaSet]:
        with self._lock:
            return self.replica_sets.get(key)

    def create_stateful_set(self, ss: StatefulSet) -> None:
        with self._lock:
            self._bump(ss)
            self.stateful_sets[ss.meta.key()] = ss
        self._notify("StatefulSet", ADDED, None, ss)

    def get_stateful_set(self, key: str) -> Optional[StatefulSet]:
        with self._lock:
            return self.stateful_sets.get(key)

    # ------------------------------------------------------------- storage kinds

    def create_pv(self, pv: PersistentVolume) -> None:
        with self._lock:
            self._bump(pv)
            self.pvs[pv.meta.name] = pv
        self._notify("PersistentVolume", ADDED, None, pv)

    def create_pvc(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            self._bump(pvc)
            self.pvcs[pvc.meta.key()] = pvc
        self._notify("PersistentVolumeClaim", ADDED, None, pvc)

    def create_storage_class(self, sc: StorageClass) -> None:
        with self._lock:
            self.storage_classes[sc.meta.name] = sc
        self._notify("StorageClass", ADDED, None, sc)

    def create_csinode(self, cn: CSINode) -> None:
        with self._lock:
            self.csinodes[cn.meta.name] = cn
        self._notify("CSINode", ADDED, None, cn)

    def get_pvc(self, key: str) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            return self.pvcs.get(key)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        with self._lock:
            return self.pvs.get(name)

    def list_pvs(self) -> List[PersistentVolume]:
        with self._lock:
            return list(self.pvs.values())

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        with self._lock:
            return self.storage_classes.get(name)

    def get_csinode(self, name: str) -> Optional[CSINode]:
        with self._lock:
            return self.csinodes.get(name)

    def bind_pv(self, pv_name: str, pvc_key: str) -> None:
        """PV controller's bind write: set claimRef + PVC.volumeName
        transactionally (the PreBind path of volumebinding writes these)."""
        with self._lock:
            pv = self.pvs.get(pv_name)
            pvc = self.pvcs.get(pvc_key)
            if pv is None or pvc is None:
                raise NotFound(f"{pv_name} / {pvc_key}")
            if pv.bound_pvc and pv.bound_pvc != pvc_key:
                raise Conflict(f"pv {pv_name} already bound to {pv.bound_pvc}")
            old_pv, old_pvc = pv, pvc
            import dataclasses as _dc

            new_pv = _dc.replace(pv, bound_pvc=pvc_key)
            new_pvc = _dc.replace(pvc, bound_pv=pv_name)
            self._bump(new_pv)
            self._bump(new_pvc)
            self.pvs[pv_name] = new_pv
            self.pvcs[pvc_key] = new_pvc
        self._notify("PersistentVolume", MODIFIED, old_pv, new_pv)
        self._notify("PersistentVolumeClaim", MODIFIED, old_pvc, new_pvc)
