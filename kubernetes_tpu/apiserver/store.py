"""In-process cluster state store: the apiserver + informer seam.

Collapses the reference's L2 (apiserver REST + watch cache) and L3 (client-go
reflector/informer) into one in-process component: typed object maps with
synchronous watch-handler fan-out.  The scheduler wires handlers exactly like
eventhandlers.go:249 addAllEventHandlers; tests and the perf harness drive
mutations exactly like the integration suite drives a real apiserver.

The binding subresource (``bind``) mirrors BindingREST.Create
(pkg/registry/core/pod/storage/storage.go:169): it transactionally sets
``pod.spec.node_name`` and fails if the pod is already bound or gone.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..api.types import (
    Binding,
    CSINode,
    Lease,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PriorityClass,
    ReplicaSet,
    ReplicationController,
    Service,
    StatefulSet,
    StorageClass,
)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Handler = Callable[[str, Optional[object], Optional[object]], None]


class Conflict(Exception):
    """409: binding/update conflict (optimistic concurrency failure)."""


class NotFound(Exception):
    """404."""


class Expired(Exception):
    """410 Gone: requested watch resourceVersion fell off the journal —
    the client must relist (etcd compaction / watch-cache overflow analog)."""


@dataclass
class WatchEvent:
    """One event on a Watch stream (apimachinery pkg/watch/watch.go:29)."""

    seq: int
    type: str  # ADDED | MODIFIED | DELETED
    object: object
    old: Optional[object] = None


class Watch:
    """A watch channel: thread-safe event queue + stop
    (watch.Interface; events pushed by the store's fan-out)."""

    def __init__(self, kind: str, store: "ClusterStore"):
        self.kind = kind
        self._store = store
        self._events: Deque[WatchEvent] = deque()
        self._cond = threading.Condition()
        self.stopped = False

    def _push(self, ev: WatchEvent) -> None:
        with self._cond:
            if self.stopped:
                return
            self._events.append(ev)
            self._cond.notify_all()

    def next(self, timeout: float = 0.0) -> Optional[WatchEvent]:
        """Next event or None (after timeout, or immediately when 0)."""
        with self._cond:
            if not self._events and timeout > 0:
                self._cond.wait(timeout)
            return self._events.popleft() if self._events else None

    def drain(self) -> List[WatchEvent]:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            return out

    def stop(self) -> None:
        with self._cond:
            self.stopped = True
            self._cond.notify_all()
        self._store._stop_watch(self)


class ClusterStore:
    def __init__(self):
        from ..testing import locktrace

        self._lock = locktrace.make_rlock("ClusterStore")
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.namespaces: Dict[str, Namespace] = {}
        self.pdbs: Dict[str, PodDisruptionBudget] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.pvs: Dict[str, PersistentVolume] = {}
        self.pvcs: Dict[str, PersistentVolumeClaim] = {}
        self.storage_classes: Dict[str, StorageClass] = {}
        self.csinodes: Dict[str, CSINode] = {}
        self.services: Dict[str, Service] = {}
        self.replication_controllers: Dict[str, ReplicationController] = {}
        self.replica_sets: Dict[str, ReplicaSet] = {}
        self.stateful_sets: Dict[str, StatefulSet] = {}
        self.leases: Dict[str, "Lease"] = {}
        self.resource_quotas: Dict[str, object] = {}
        self.limit_ranges: Dict[str, object] = {}
        self.cron_jobs: Dict[str, object] = {}
        self.endpoint_slices: Dict[str, object] = {}
        self.volume_attachments: Dict[str, object] = {}
        self.deployments: Dict[str, object] = {}
        self.daemon_sets: Dict[str, object] = {}
        self.jobs: Dict[str, object] = {}
        self.endpoints: Dict[str, object] = {}
        self.service_accounts: Dict[str, object] = {}
        self.mutating_webhooks: Dict[str, object] = {}
        self.validating_webhooks: Dict[str, object] = {}
        self.config_maps: Dict[str, object] = {}
        self.secrets: Dict[str, object] = {}
        self.csrs: Dict[str, object] = {}
        self.runtime_classes: Dict[str, object] = {}
        self.ingresses: Dict[str, object] = {}
        self.ingress_classes: Dict[str, object] = {}
        self.events: Dict[str, object] = {}
        self.hpas: Dict[str, object] = {}
        self.cluster_roles: Dict[str, object] = {}
        self.cluster_role_bindings: Dict[str, object] = {}
        # resource.k8s.io (Dynamic Resource Allocation): class catalog,
        # claims (allocation status written by the scheduler's Reserve/
        # PostBind), templates the resourceclaim controller stamps out, and
        # the scheduler⇄driver negotiation objects
        self.resource_classes: Dict[str, object] = {}
        self.resource_claims: Dict[str, object] = {}
        self.resource_claim_templates: Dict[str, object] = {}
        self.pod_scheduling_contexts: Dict[str, object] = {}
        # scheduling.x-k8s.io: gang contracts the Coscheduling plugin gates
        # on, plus the per-namespace scheduler-admission quota contracts the
        # QuotaAdmission plugin + fair-share dequeuer read
        self.pod_groups: Dict[str, object] = {}
        self.scheduling_quotas: Dict[str, object] = {}
        # apiextensions (VERDICT r4 item 10): registered CRDs + one dynamic
        # kind map per served kind — plugin-requested GVKs get real objects,
        # journaled watches and informers through the same generic machinery
        self.crds: Dict[str, object] = {}
        # kube-aggregator registrations: (group, version) -> APIService
        self.api_services: Dict[str, object] = {}
        self._custom_kinds: Dict[str, Dict[str, object]] = {}
        self._custom_scope: Dict[str, bool] = {}  # kind -> namespaced
        # metrics-API stand-in (metrics.k8s.io): pod key -> milli-cpu usage,
        # fed by the hollow kubelet / tests, read by the HPA controller
        self.pod_metrics: Dict[str, int] = {}
        # per-thread request identity (the authn layer's user info, set by
        # the HTTP front from the authenticated request; NodeRestriction and
        # OwnerReferencesPermissionEnforcement read it)
        self._request_user = threading.local()
        # authorizer hook (authz.Authorizer-shaped: allowed(user, verb,
        # kind, name) -> bool); None = authorization disabled
        self.authorizer = None
        self._handlers: Dict[str, List[Handler]] = {}
        self._rv = 0
        # watch journal (the watch cache, cacher.go:227): bounded event log +
        # live watcher fan-out; seq is the LIST/WATCH resourceVersion.
        self._event_seq = 0
        self._journal: List[Tuple[int, str, str, object, object]] = []
        self._journal_capacity = 4096
        self._watchers: Dict[str, List[Watch]] = {}
        # admission chain on the write path (config.go:806 handler chain's
        # admission stage); None disables
        from .admission import AdmissionChain

        self.admission: Optional[AdmissionChain] = AdmissionChain()
        # durable-store seam (apiserver/wal.py attach_wal): when set, every
        # journaled mutation also lands in the write-ahead log — the etcd
        # WAL role (etcd3/store.go:72); None = memory-only (the default)
        self._wal = None
        # group-commit buffer: while a batched mutator (bind_batch) holds
        # the store lock, _journal_event parks WAL records here instead of
        # appending one line each; the batch flushes them as ONE crc-framed
        # append before releasing the lock (ordering contract preserved)
        self._wal_group = None
        # field validation on the write path (api/validation.py, the
        # strategy.Validate position); False disables for raw-object tests
        self.validation_enabled = True

    def add_event_handler(self, kind: str, handler: Handler) -> None:
        self._handlers.setdefault(kind, []).append(handler)

    def _journal_event(self, kind: str, event: str, old, new) -> None:  # ktpu: locked
        """Append to the watch journal + push to live watchers. MUST be
        called inside the mutator's critical section so the journal order
        matches the map mutation order (else concurrent writers could
        journal ADDED/DELETED inverted and desync informer caches)."""
        self._event_seq += 1
        seq = self._event_seq
        self._journal.append((seq, kind, event, old, new))
        if len(self._journal) > self._journal_capacity:
            del self._journal[: len(self._journal) - self._journal_capacity]
        if self._wal is not None:
            obj = new if new is not None else None
            key = self._key_of(kind, new if new is not None else old)
            if self._wal_group is not None:
                self._wal_group.append((seq, kind, event, key, obj))
            else:
                self._wal.append(seq, kind, event, key, obj)
        for w in self._watchers.get(kind, []):
            w._push(WatchEvent(seq=seq, type=event, old=old, object=new if new is not None else old))

    def _notify(self, kind: str, event: str, old, new) -> None:
        """Direct-handler fan-out, outside the lock (handlers may re-enter
        the store); informers get their events from _journal_event."""
        for h in self._handlers.get(kind, []):
            h(event, old, new)

    def _admit(self, kind: str, obj) -> None:
        if self.admission is not None:
            self.admission.run(self, kind, obj)
        if self.validation_enabled:
            # strategy.Validate position: field validation AFTER admission
            # defaulting (registry strategies, pkg/registry/core/pod/strategy.go)
            from ..api import validation

            validation.validate(kind, obj)

    def _admit_update(self, kind: str, old, obj) -> None:
        if self.admission is not None:
            self.admission.run_update(self, kind, old, obj)
        if self.validation_enabled:
            from ..api import validation

            validation.validate_update(kind, old, obj)

    def _guarded_update(self, kind: str, obj, lookup, commit):
        """Admission-checked update with optimistic concurrency against the
        admission snapshot: validate_update runs OUTSIDE the lock (webhooks
        may do IO), then the locked commit only lands if the stored object is
        still the one admission validated against — otherwise re-validate
        against the new truth and retry (GuaranteedUpdate's retry loop,
        etcd3/store.go:328; closes the validate-then-write race on e.g. the
        PVC shrink check). Returns the replaced object."""
        for _ in range(16):
            with self._lock:
                old = lookup()
            self._admit_update(kind, old, obj)
            with self._lock:
                if lookup() is old:
                    commit(old)
                    return old
        raise Conflict(f"{kind} {self._key_of(kind, obj)}: too many concurrent updates")

    # -------------------------------------------------------------- request user
    # (the authn seam: the HTTP front authenticates and pins the user for the
    # duration of the request; in-process callers are "system:admin")

    def request_user(self) -> str:
        return getattr(self._request_user, "name", "") or "system:admin"

    def request_groups(self) -> tuple:
        return getattr(self._request_user, "groups", ())

    def set_request_user(self, name: str, groups: tuple = ()) -> None:
        self._request_user.name = name
        self._request_user.groups = tuple(groups)

    def as_user(self, name: str, groups: tuple = ()):
        """Context manager: run store writes as ``name`` (+ groups) on this
        thread; the previous identity INCLUDING groups is restored on exit
        (a stale group set must never leak into an impersonated context)."""
        store = self

        class _Ctx:
            def __enter__(self):
                self._prev = (getattr(store._request_user, "name", ""),
                              getattr(store._request_user, "groups", ()))
                store._request_user.name = name
                store._request_user.groups = tuple(groups)

            def __exit__(self, *exc):
                store._request_user.name, store._request_user.groups = self._prev
                return False

        return _Ctx()

    def _bump(self, obj) -> None:  # ktpu: locked
        self._rv += 1
        obj.meta.resource_version = self._rv
        if not obj.meta.creation_timestamp:
            import time as _time

            obj.meta.creation_timestamp = _time.time()

    # ------------------------------------------------------------- list+watch
    # (the L2 watch-cache seam: storage/cacher/cacher.go:227 fan-out plus the
    # LIST-with-resourceVersion the reflector resumes from, reflector.go:254)

    def list_objects(self, kind: str) -> Tuple[List[object], int]:
        """LIST: (objects, resourceVersion) — the reflector's initial sync."""
        with self._lock:
            m = self._kind_map(kind)
            return list(m.values()), self._event_seq

    def watch(self, kind: str, since: int) -> "Watch":
        """WATCH from ``since`` (a seq returned by list_objects/WatchEvent).
        Raises Expired when the journal no longer covers ``since`` — the
        client must relist (reflector.go relist-on-410 path)."""
        with self._lock:
            oldest_covered = self._journal[0][0] - 1 if self._journal else self._event_seq
            if since < oldest_covered:
                raise Expired(f"resourceVersion {since} is too old (oldest {oldest_covered})")
            backlog = [e for e in self._journal if e[0] > since and e[1] == kind]
            w = Watch(kind=kind, store=self)
            for seq, _k, event, old, new in backlog:
                w._push(WatchEvent(seq=seq, type=event, old=old, object=new if new is not None else old))
            self._watchers.setdefault(kind, []).append(w)
            return w

    def _stop_watch(self, w: "Watch") -> None:
        with self._lock:
            lst = self._watchers.get(w.kind, [])
            if w in lst:
                lst.remove(w)

    @property
    def KINDS(self):
        """Every kind the store persists (the WAL snapshot's catalog)."""
        return tuple(self._kind_maps())

    def _kind_maps(self) -> Dict[str, Dict[str, object]]:  # ktpu: locked
        return {
                "Pod": self.pods,
                "Node": self.nodes,
                "Namespace": self.namespaces,
                "PodDisruptionBudget": self.pdbs,
                "PriorityClass": self.priority_classes,
                "PersistentVolume": self.pvs,
                "PersistentVolumeClaim": self.pvcs,
                "StorageClass": self.storage_classes,
                "CSINode": self.csinodes,
                "Service": self.services,
                "ReplicationController": self.replication_controllers,
                "ReplicaSet": self.replica_sets,
                "StatefulSet": self.stateful_sets,
                "Lease": self.leases,
                "Deployment": self.deployments,
                "DaemonSet": self.daemon_sets,
                "Job": self.jobs,
                "Endpoints": self.endpoints,
                "ResourceQuota": self.resource_quotas,
                "LimitRange": self.limit_ranges,
                "CronJob": self.cron_jobs,
                "EndpointSlice": self.endpoint_slices,
                "VolumeAttachment": self.volume_attachments,
                "ServiceAccount": self.service_accounts,
                "MutatingWebhookConfiguration": self.mutating_webhooks,
                "ValidatingWebhookConfiguration": self.validating_webhooks,
                "ConfigMap": self.config_maps,
                "Secret": self.secrets,
                "CertificateSigningRequest": self.csrs,
                "RuntimeClass": self.runtime_classes,
                "Ingress": self.ingresses,
                "IngressClass": self.ingress_classes,
                "Event": self.events,
                "HorizontalPodAutoscaler": self.hpas,
                "ClusterRole": self.cluster_roles,
                "ClusterRoleBinding": self.cluster_role_bindings,
                "ResourceClass": self.resource_classes,
                "ResourceClaim": self.resource_claims,
                "ResourceClaimTemplate": self.resource_claim_templates,
                "PodSchedulingContext": self.pod_scheduling_contexts,
                "PodGroup": self.pod_groups,
                "SchedulingQuota": self.scheduling_quotas,
                "CustomResourceDefinition": self.crds,
                "APIService": self.api_services,
                **self._custom_kinds,
            }

    def _kind_map(self, kind: str) -> Dict[str, object]:
        try:
            return self._kind_maps()[kind]
        except KeyError:
            raise NotFound(f"unknown kind {kind!r}") from None

    # ------------------------------------------------------------- nodes

    def create_node(self, node: Node) -> None:
        self._admit("Node", node)
        with self._lock:
            if node.meta.name in self.nodes:
                raise Conflict(f"node {node.meta.name} exists")
            self._bump(node)
            self.nodes[node.meta.name] = node
            self._journal_event("Node", ADDED, None, node)
        self._notify("Node", ADDED, None, node)

    def update_node(self, node: Node) -> None:
        def commit(old):  # ktpu: locked
            if old is None:
                raise NotFound(node.meta.name)
            self._bump(node)
            self.nodes[node.meta.name] = node
            self._journal_event("Node", MODIFIED, old, node)

        old = self._guarded_update("Node", node,
                                   lambda: self.nodes.get(node.meta.name), commit)  # ktpu: unguarded-ok(the lookup closure runs under the lock inside _guarded_update)
        self._notify("Node", MODIFIED, old, node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            old = self.nodes.pop(name, None)
            if old is not None:
                self._journal_event("Node", DELETED, old, None)
        if old is not None:
            self._notify("Node", DELETED, old, None)

    # ------------------------------------------------------------- pods

    def create_pod(self, pod: Pod) -> None:
        self._admit("Pod", pod)
        with self._lock:
            if pod.key() in self.pods:
                raise Conflict(f"pod {pod.key()} exists")
            # Stateful admission (quota charge) runs atomically with the
            # insert, after the duplicate-key check — a failed create can
            # never strand usage (ADVICE r1: check-then-charge race).
            undo_charge = (self.admission.charge(self, "Pod", pod)
                           if self.admission is not None else None)
            try:
                self._bump(pod)
                self.pods[pod.key()] = pod
                self._journal_event("Pod", ADDED, None, pod)
            except BaseException:
                if undo_charge is not None:
                    undo_charge()
                raise
        self._notify("Pod", ADDED, None, pod)

    def update_pod(self, pod: Pod) -> None:
        def commit(old):  # ktpu: locked
            if old is None:
                raise NotFound(pod.key())
            self._bump(pod)
            self.pods[pod.key()] = pod
            self._journal_event("Pod", MODIFIED, old, pod)

        old = self._guarded_update("Pod", pod, lambda: self.pods.get(pod.key()),  # ktpu: unguarded-ok(the lookup closure runs under the lock inside _guarded_update)
                                   commit)
        self._notify("Pod", MODIFIED, old, pod)

    def delete_pod(self, key: str) -> None:
        with self._lock:
            old = self.pods.pop(key, None)
            if old is not None:
                self._journal_event("Pod", DELETED, old, None)
        if old is not None:
            self._notify("Pod", DELETED, old, None)

    def get_pod(self, key: str) -> Optional[Pod]:
        with self._lock:
            return self.pods.get(key)

    def _bind_one_locked(self, binding: Binding):
        """The bind mutation proper — ONE implementation shared by the
        per-pod verb and the batched transaction, so their semantics can
        never drift. Raises NotFound/Conflict; returns (old, new) for the
        caller's notify fan-out (which runs outside the lock)."""
        pod = self.pods.get(binding.pod_key)
        if pod is None:
            raise NotFound(binding.pod_key)
        if pod.spec.node_name:
            raise Conflict(f"pod {binding.pod_key} is already bound to {pod.spec.node_name}")
        old = pod
        new = pod.clone()
        new.spec.node_name = binding.node_name
        new.status.phase = "Running"
        self._bump(new)
        self.pods[binding.pod_key] = new
        self._journal_event("Pod", MODIFIED, old, new)
        return old, new

    def bind(self, binding: Binding) -> None:
        """POST pods/{name}/binding (storage.go:169)."""
        with self._lock:
            old, new = self._bind_one_locked(binding)
        self._notify("Pod", MODIFIED, old, new)

    def bind_batch(self, bindings) -> list:
        """Batched POST pods/binding — the store half of the commit data
        plane: ONE lock acquisition, one journal pass, and one group-commit
        WAL append cover a whole scheduler batch (per-pod bind held a lock
        round trip plus a WAL write+flush each on the measured host.commit
        bottleneck). Per-pod semantics are unchanged: each binding is
        validated independently and a NotFound/Conflict fails only ITS pod —
        the returned list carries None for success or the exception (not
        raised) per binding, in input order. Notify fan-out runs after the
        lock, once per bound pod (handlers may re-enter the store)."""
        outcomes = [None] * len(bindings)
        notifies = []
        with self._lock:
            group_owner = self._wal_group is None
            if group_owner:
                self._wal_group = []
            try:
                for i, binding in enumerate(bindings):
                    try:
                        notifies.append(self._bind_one_locked(binding))
                    except (NotFound, Conflict) as err:
                        outcomes[i] = err
            finally:
                if group_owner:
                    group, self._wal_group = self._wal_group, None
                    if self._wal is not None and group:
                        self._wal.append_batch(group)
        for old, new in notifies:
            self._notify("Pod", MODIFIED, old, new)
        return outcomes

    def update_pod_nominated_node(self, key: str, node_name: str) -> None:
        """pod.Status.NominatedNodeName persist (schedule_one.go:846)."""
        with self._lock:
            pod = self.pods.get(key)
            if pod is None:
                raise NotFound(key)
            old = pod
            new = pod.clone()
            new.status.nominated_node_name = node_name
            self._bump(new)
            self.pods[key] = new
            self._journal_event("Pod", MODIFIED, old, new)
        self._notify("Pod", MODIFIED, old, new)

    # ------------------------------------------------------------- misc kinds

    def create_namespace(self, ns: Namespace) -> None:
        with self._lock:
            self.namespaces[ns.meta.name] = ns
            self._journal_event("Namespace", ADDED, None, ns)
        self._notify("Namespace", ADDED, None, ns)

    def ns_labels(self, name: str) -> Dict[str, str]:
        with self._lock:
            ns = self.namespaces.get(name)
            return dict(ns.meta.labels) if ns else {}

    def create_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._lock:
            self.pdbs[pdb.meta.key()] = pdb
            self._journal_event("PodDisruptionBudget", ADDED, None, pdb)
        self._notify("PodDisruptionBudget", ADDED, None, pdb)

    def list_pdbs(self) -> List[PodDisruptionBudget]:
        with self._lock:
            return list(self.pdbs.values())

    def create_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self.priority_classes[pc.meta.name] = pc
            self._journal_event("PriorityClass", ADDED, None, pc)
        self._notify("PriorityClass", ADDED, None, pc)

    # ------------------------------------------------------------- generic CRUD
    # (the registry's per-resource REST strategies, collapsed: pkg/registry)

    CLUSTER_SCOPED_KINDS = {
        "Node", "Namespace", "PersistentVolume", "StorageClass", "CSINode",
        "PriorityClass", "VolumeAttachment",
        "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
        "ClusterRole", "ClusterRoleBinding", "CertificateSigningRequest",
        "RuntimeClass", "IngressClass", "ResourceClass",
    }

    def is_cluster_scoped(self, kind: str) -> bool:
        """The one scope rule (consumed by _key_of and the HTTP front)."""
        if kind in self.CLUSTER_SCOPED_KINDS or kind in (
                "CustomResourceDefinition", "APIService"):
            return True
        return kind in self._custom_scope and not self._custom_scope[kind]  # ktpu: unguarded-ok(grow-only registration dict; read from both locked and HTTP-front contexts)

    def _key_of(self, kind: str, obj) -> str:
        return obj.meta.name if self.is_cluster_scoped(kind) else obj.meta.key()

    # -------------------------------------------------------- dynamic kinds

    def _register_crd_kind(self, crd) -> None:  # ktpu: locked
        """Kind-map registration half of create_crd — also used by WAL
        restore, where CRD objects re-enter through the raw kind map and
        must re-register their served kinds before any custom object."""
        self._custom_kinds.setdefault(crd.kind, {})
        self._custom_scope[crd.kind] = bool(crd.namespaced)

    def create_crd(self, crd) -> None:
        """Register a dynamic kind (apiextensions customresource_handler.go's
        discovery/registration step, minus schema validation): after this,
        the generic create/update/delete/list/watch machinery — and thus
        reflectors, informers and the scheduler's dynamic event handlers —
        serve the new kind exactly like a built-in."""
        with self._lock:
            name = crd.meta.name or f"{crd.plural}.{crd.group}"
            crd.meta.name = name
            if crd.kind in self._kind_maps():
                raise Conflict(f"kind {crd.kind!r} already served")
            self._bump(crd)
            self.crds[name] = crd
            self._register_crd_kind(crd)
            self._journal_event("CustomResourceDefinition", ADDED, None, crd)
        self._notify("CustomResourceDefinition", ADDED, None, crd)

    def api_service_for(self, group: str, version: str):
        """The aggregation lookup: a non-local APIService claiming this
        group/version (kube-aggregator handler.go ServeHTTP)."""
        with self._lock:
            for svc in self.api_services.values():
                if (svc.group == group and svc.version == version
                        and svc.service_endpoint):
                    return svc
        return None

    def crd_for_plural(self, group: str, plural: str):
        with self._lock:
            for crd in self.crds.values():
                if crd.group == group and crd.plural == plural:
                    return crd
        return None

    def create_object(self, kind: str, obj) -> None:
        if kind == "CustomResourceDefinition":
            # full registration (kind map + scope), not a bare map insert —
            # a half-registered CRD would 404/crash custom-kind requests
            return self.create_crd(obj)
        if kind == "Pod":
            # Pods must take the full admission path (atomic quota charge
            # under the lock); two create paths with divergent semantics was
            # ADVICE r2 low #3
            return self.create_pod(obj)
        self._admit(kind, obj)
        m = self._kind_map(kind)
        with self._lock:
            key = self._key_of(kind, obj)
            if key in m:
                raise Conflict(f"{kind} {key} exists")
            self._bump(obj)
            m[key] = obj
            self._journal_event(kind, ADDED, None, obj)
        self._notify(kind, ADDED, None, obj)

    def update_object(self, kind: str, obj) -> None:
        m = self._kind_map(kind)
        key = self._key_of(kind, obj)

        def commit(old):
            if old is None:
                raise NotFound(f"{kind} {key}")
            # deletionTimestamp is SERVER-owned (metav1 semantics): an update
            # can neither delete a live object nor resurrect a terminating
            # one — only delete_object sets the marker. Exception: kinds our
            # controllers mark terminating in-process (Namespace) keep the
            # client value.
            if kind != "Namespace":
                obj.meta.deletion_timestamp = old.meta.deletion_timestamp
            if obj.meta.deletion_timestamp and not obj.meta.finalizers:
                # last finalizer cleared on a terminating object: the update
                # completes the delete (registry deleteCollection semantics)
                m.pop(key, None)
                self._journal_event(kind, DELETED, old, None)
                commit.deleted = True
                return
            self._bump(obj)
            m[key] = obj
            self._journal_event(kind, MODIFIED, old, obj)

        commit.deleted = False
        old = self._guarded_update(kind, obj, lambda: m.get(key), commit)
        if commit.deleted:
            self._notify(kind, DELETED, old, None)
        else:
            self._notify(kind, MODIFIED, old, obj)

    def delete_object(self, kind: str, key: str) -> None:
        m = self._kind_map(kind)
        with self._lock:
            cur = m.get(key)
            if cur is not None and getattr(cur.meta, "finalizers", ()):
                # finalizer gate (apiserver registry BeforeDelete): mark
                # terminating; actual removal happens when the last
                # finalizer is cleared via update_object
                if not cur.meta.deletion_timestamp:
                    import dataclasses as _dc
                    import time as _time

                    marked = _dc.replace(cur)
                    marked.meta = _dc.replace(
                        cur.meta, deletion_timestamp=_time.time())
                    self._bump(marked)
                    m[key] = marked
                    self._journal_event(kind, MODIFIED, cur, marked)
                else:
                    marked = None
                old = None
            else:
                marked = None
                old = m.pop(key, None)
                if old is not None:
                    self._journal_event(kind, DELETED, old, None)
        if marked is not None:
            self._notify(kind, MODIFIED, cur, marked)
        if old is not None:
            self._notify(kind, DELETED, old, None)

    def get_object(self, kind: str, key: str):
        with self._lock:
            return self._kind_map(kind).get(key)

    def snapshot_map(self, kind: str) -> Dict[str, object]:
        """Copy of a kind's map under the lock — safe to iterate while other
        threads mutate (controllers' level-scan reads)."""
        with self._lock:
            return dict(self._kind_map(kind))

    # ------------------------------------------------------------- workload kinds
    # (SelectorSpread's owner lookup, helper/spread.go DefaultSelector)

    def create_service(self, svc: Service) -> None:
        self._admit("Service", svc)
        with self._lock:
            self._bump(svc)
            self.services[svc.meta.key()] = svc
            self._journal_event("Service", ADDED, None, svc)
        self._notify("Service", ADDED, None, svc)

    def list_services(self, namespace: str) -> List[Service]:
        with self._lock:
            return [s for s in self.services.values() if s.meta.namespace == namespace]

    def create_replication_controller(self, rc: ReplicationController) -> None:
        with self._lock:
            self._bump(rc)
            self.replication_controllers[rc.meta.key()] = rc
            self._journal_event("ReplicationController", ADDED, None, rc)
        self._notify("ReplicationController", ADDED, None, rc)

    def get_replication_controller(self, key: str) -> Optional[ReplicationController]:
        with self._lock:
            return self.replication_controllers.get(key)

    def create_replica_set(self, rs: ReplicaSet) -> None:
        with self._lock:
            self._bump(rs)
            self.replica_sets[rs.meta.key()] = rs
            self._journal_event("ReplicaSet", ADDED, None, rs)
        self._notify("ReplicaSet", ADDED, None, rs)

    def get_replica_set(self, key: str) -> Optional[ReplicaSet]:
        with self._lock:
            return self.replica_sets.get(key)

    def create_stateful_set(self, ss: StatefulSet) -> None:
        with self._lock:
            self._bump(ss)
            self.stateful_sets[ss.meta.key()] = ss
            self._journal_event("StatefulSet", ADDED, None, ss)
        self._notify("StatefulSet", ADDED, None, ss)

    def get_stateful_set(self, key: str) -> Optional[StatefulSet]:
        with self._lock:
            return self.stateful_sets.get(key)

    # ------------------------------------------------------------- leases
    # (coordination.k8s.io; optimistic-concurrency update is the leader lock)

    def get_lease(self, key: str) -> Optional["Lease"]:
        with self._lock:
            return self.leases.get(key)

    def create_lease(self, lease: "Lease") -> None:
        self._admit("Lease", lease)
        with self._lock:
            if lease.meta.key() in self.leases:
                raise Conflict(f"lease {lease.meta.key()} exists")
            self._bump(lease)
            self.leases[lease.meta.key()] = lease
            self._journal_event("Lease", ADDED, None, lease)
        self._notify("Lease", ADDED, None, lease)

    def update_lease(self, lease: "Lease", expect_rv: int) -> None:
        """Guarded update: fails unless the stored lease still has
        ``expect_rv`` (GuaranteedUpdate's optimistic concurrency,
        etcd3/store.go:328 — what makes leader election safe)."""
        self._admit_update("Lease", self.leases.get(lease.meta.key()), lease)  # ktpu: unguarded-ok(optimistic-concurrency read; the locked section re-checks resourceVersion)
        with self._lock:
            old = self.leases.get(lease.meta.key())
            if old is None:
                raise NotFound(lease.meta.key())
            if old.meta.resource_version != expect_rv:
                raise Conflict(
                    f"lease {lease.meta.key()}: rv {expect_rv} != {old.meta.resource_version}"
                )
            self._bump(lease)
            self.leases[lease.meta.key()] = lease
            self._journal_event("Lease", MODIFIED, old, lease)
        self._notify("Lease", MODIFIED, old, lease)

    # ------------------------------------------------------------- storage kinds

    def create_pv(self, pv: PersistentVolume) -> None:
        self._admit("PersistentVolume", pv)
        with self._lock:
            self._bump(pv)
            self.pvs[pv.meta.name] = pv
            self._journal_event("PersistentVolume", ADDED, None, pv)
        self._notify("PersistentVolume", ADDED, None, pv)

    def create_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self._admit("PersistentVolumeClaim", pvc)
        with self._lock:
            self._bump(pvc)
            self.pvcs[pvc.meta.key()] = pvc
            self._journal_event("PersistentVolumeClaim", ADDED, None, pvc)
        self._notify("PersistentVolumeClaim", ADDED, None, pvc)

    def create_storage_class(self, sc: StorageClass) -> None:
        with self._lock:
            self.storage_classes[sc.meta.name] = sc
            self._journal_event("StorageClass", ADDED, None, sc)
        self._notify("StorageClass", ADDED, None, sc)

    def create_csinode(self, cn: CSINode) -> None:
        with self._lock:
            self.csinodes[cn.meta.name] = cn
            self._journal_event("CSINode", ADDED, None, cn)
        self._notify("CSINode", ADDED, None, cn)

    def get_pvc(self, key: str) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            return self.pvcs.get(key)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        with self._lock:
            return self.pvs.get(name)

    def list_pvs(self) -> List[PersistentVolume]:
        with self._lock:
            return list(self.pvs.values())

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        with self._lock:
            return self.storage_classes.get(name)

    def get_csinode(self, name: str) -> Optional[CSINode]:
        with self._lock:
            return self.csinodes.get(name)

    def bind_pv(self, pv_name: str, pvc_key: str) -> None:
        """PV controller's bind write: set claimRef + PVC.volumeName
        transactionally (the PreBind path of volumebinding writes these)."""
        with self._lock:
            pv = self.pvs.get(pv_name)
            pvc = self.pvcs.get(pvc_key)
            if pv is None or pvc is None:
                raise NotFound(f"{pv_name} / {pvc_key}")
            if pv.bound_pvc and pv.bound_pvc != pvc_key:
                raise Conflict(f"pv {pv_name} already bound to {pv.bound_pvc}")
            old_pv, old_pvc = pv, pvc
            import dataclasses as _dc

            new_pv = _dc.replace(pv, bound_pvc=pvc_key)
            new_pvc = _dc.replace(pvc, bound_pv=pv_name)
            self._bump(new_pv)
            self._bump(new_pvc)
            self.pvs[pv_name] = new_pv
            self.pvcs[pvc_key] = new_pvc
            self._journal_event("PersistentVolume", MODIFIED, old_pv, new_pv)
            self._journal_event("PersistentVolumeClaim", MODIFIED, old_pvc, new_pvc)
        self._notify("PersistentVolume", MODIFIED, old_pv, new_pv)
        self._notify("PersistentVolumeClaim", MODIFIED, old_pvc, new_pvc)

    # ------------------------------------------------------------- resource.k8s.io

    def allocate_claim(self, claim_key: str, node_name: str, pod_key: str) -> None:
        """Allocate a ResourceClaim to a node and reserve it for a pod,
        transactionally (the scheduler's Reserve write; claim_controller.go
        allocation + reservedFor semantics). A claim already allocated to a
        DIFFERENT node raises Conflict — the caller unreserves and retries."""
        with self._lock:
            claim = self.resource_claims.get(claim_key)
            if claim is None:
                raise NotFound(claim_key)
            if claim.allocated_node and claim.allocated_node != node_name:
                raise Conflict(
                    f"claim {claim_key} already allocated to {claim.allocated_node}")
            old = claim
            import dataclasses as _dc

            reserved = old.reserved_for
            if pod_key not in reserved:
                reserved = reserved + (pod_key,)
            new = _dc.replace(old, allocated_node=node_name, reserved_for=reserved)
            self._bump(new)
            self.resource_claims[claim_key] = new
            self._journal_event("ResourceClaim", MODIFIED, old, new)
        self._notify("ResourceClaim", MODIFIED, old, new)

    def release_claim(self, claim_key: str, pod_key: str) -> None:
        """Drop one pod's reservation; the last reservation leaving also
        deallocates (the in-process stand-in for the driver's deallocate —
        node-level allocations have nothing else to free)."""
        with self._lock:
            claim = self.resource_claims.get(claim_key)
            if claim is None or pod_key not in claim.reserved_for:
                return
            old = claim
            import dataclasses as _dc

            reserved = tuple(k for k in old.reserved_for if k != pod_key)
            new = _dc.replace(
                old, reserved_for=reserved,
                allocated_node=old.allocated_node if reserved else "")
            self._bump(new)
            self.resource_claims[claim_key] = new
            self._journal_event("ResourceClaim", MODIFIED, old, new)
        self._notify("ResourceClaim", MODIFIED, old, new)
