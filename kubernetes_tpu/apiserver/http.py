"""HTTP REST + watch front for the ClusterStore (the L2 seam).

The reference's apiserver serves typed REST over HTTPS with LIST/WATCH
streaming (staging/src/k8s.io/apiserver pkg/endpoints; watch cache
cacher.go:227). This module is that surface for the in-process store:
reference-shaped paths, JSON bodies through the reflection codec
(api/codec.py), resourceVersion LIST/WATCH semantics with 410 Gone, and the
pods/{name}/binding subresource the scheduler writes through
(registry/core/pod/storage/storage.go:169).

  GET    /api/v1/nodes                       LIST (cluster-scoped)
  GET    /api/v1/namespaces/{ns}/pods        LIST (namespaced)
  GET    .../pods?watch=1&resourceVersion=N  WATCH (JSON-lines stream)
  GET    .../pods/{name}                     GET
  POST   .../pods                            CREATE (admission chain runs)
  PUT    .../pods/{name}                     UPDATE
  DELETE .../pods/{name}                     DELETE
  POST   .../pods/{name}/binding             BIND

The handler chain (config.go:806 DefaultBuildHandlerChain) runs
authentication → flow control (APF) → authorization when serve_api is given
an AuthConfig (apiserver/auth.py): bearer tokens / proxy headers resolve the
user (401 on bad credentials), the FlowController bounds per-priority-level
in-flight requests (429 when a level's queue is full), and the RBAC
authorizer gates verb×kind (403). All three stages are optional — a bare
serve_api() is the previous open server. The resolved user is pinned on the
store for the request (NodeRestriction admission reads it).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api import types as api_types
from ..api.codec import from_wire, to_wire
from ..api.types import Binding
from ..api.validation import ValidationError
from .admission import AdmissionError

# protobuf content negotiation (api/protobuf.py; the reference's
# application/vnd.kubernetes.protobuf serializer seam)
_PROTO_CT = "application/vnd.kubernetes.protobuf"
_PROTO_BODY_KEY = "__ktpu_protobuf_body__"
from .store import ClusterStore, Conflict, Expired, NotFound

# (group-path-prefix, plural) -> kind; plural -> python type via api.types
RESOURCES = {
    ("api/v1", "pods"): "Pod",
    ("api/v1", "nodes"): "Node",
    ("api/v1", "namespaces"): "Namespace",
    ("api/v1", "services"): "Service",
    ("api/v1", "endpoints"): "Endpoints",
    ("api/v1", "replicationcontrollers"): "ReplicationController",
    ("api/v1", "persistentvolumes"): "PersistentVolume",
    ("api/v1", "persistentvolumeclaims"): "PersistentVolumeClaim",
    ("api/v1", "resourcequotas"): "ResourceQuota",
    ("api/v1", "limitranges"): "LimitRange",
    ("api/v1", "configmaps"): "ConfigMap",
    ("api/v1", "secrets"): "Secret",
    ("api/v1", "serviceaccounts"): "ServiceAccount",
    ("apis/apps/v1", "deployments"): "Deployment",
    ("apis/apps/v1", "replicasets"): "ReplicaSet",
    ("apis/apps/v1", "statefulsets"): "StatefulSet",
    ("apis/apps/v1", "daemonsets"): "DaemonSet",
    ("apis/batch/v1", "jobs"): "Job",
    ("apis/batch/v1", "cronjobs"): "CronJob",
    ("apis/discovery.k8s.io/v1", "endpointslices"): "EndpointSlice",
    ("apis/storage.k8s.io/v1", "volumeattachments"): "VolumeAttachment",
    ("apis/policy/v1", "poddisruptionbudgets"): "PodDisruptionBudget",
    ("apis/scheduling.k8s.io/v1", "priorityclasses"): "PriorityClass",
    ("apis/storage.k8s.io/v1", "storageclasses"): "StorageClass",
    ("apis/storage.k8s.io/v1", "csinodes"): "CSINode",
    ("apis/coordination.k8s.io/v1", "leases"): "Lease",
    ("apis/certificates.k8s.io/v1", "certificatesigningrequests"):
        "CertificateSigningRequest",
    ("apis/node.k8s.io/v1", "runtimeclasses"): "RuntimeClass",
    ("apis/networking.k8s.io/v1", "ingresses"): "Ingress",
    ("apis/networking.k8s.io/v1", "ingressclasses"): "IngressClass",
    ("apis/resource.k8s.io/v1alpha2", "resourceclasses"): "ResourceClass",
    ("apis/resource.k8s.io/v1alpha2", "resourceclaims"): "ResourceClaim",
    ("apis/resource.k8s.io/v1alpha2", "resourceclaimtemplates"):
        "ResourceClaimTemplate",
    ("apis/resource.k8s.io/v1alpha2", "podschedulingcontexts"):
        "PodSchedulingContext",
    ("apis/scheduling.x-k8s.io/v1alpha1", "podgroups"): "PodGroup",
    ("apis/scheduling.x-k8s.io/v1alpha1", "schedulingquotas"): "SchedulingQuota",
    ("apis/apiextensions.k8s.io/v1", "customresourcedefinitions"):
        "CustomResourceDefinition",
    ("apis/apiregistration.k8s.io/v1", "apiservices"): "APIService",
    ("api/v1", "events"): "Event",
}

_KIND_TYPES = {kind: getattr(api_types, kind) for (_g, _p), kind in RESOURCES.items()}


def _route(path: str) -> Optional[Tuple[str, str, Optional[str], Optional[str], Optional[str]]]:
    """path -> (group, kind, namespace, name, subresource) or None."""
    parts = [p for p in path.split("/") if p]
    for (group, plural), kind in RESOURCES.items():
        gparts = group.split("/")
        if parts[:len(gparts)] != gparts:
            continue
        rest = parts[len(gparts):]
        ns = None
        # "namespaces/{ns}/{plural}/..." is a namespaced-resource path;
        # "namespaces" / "namespaces/{name}" address Namespace objects
        if len(rest) >= 3 and rest[0] == "namespaces":
            ns = rest[1]
            rest = rest[2:]
        if not rest or rest[0] != plural:
            continue
        name = rest[1] if len(rest) > 1 else None
        sub = rest[2] if len(rest) > 2 else None
        return group, kind, ns, name, sub
    return None


class _Handler(BaseHTTPRequestHandler):
    store: ClusterStore = None  # bound by serve_api()
    auth = None                 # Optional[AuthConfig], bound by serve_api()
    protocol_version = "HTTP/1.1"

    def _maybe_aggregate(self, path: str, body_doc=None) -> bool:
        """kube-aggregator arm: when no built-in or CRD route claims an
        /apis/{group}/{version} path but a non-local APIService does, proxy
        the request verbatim to its backend and relay the response
        (kube-aggregator pkg/apiserver/handler_proxy.go, minus TLS/auth
        forwarding). Returns True when the request was proxied."""
        parts = [p for p in path.split("/") if p]
        if len(parts) < 3 or parts[0] != "apis":
            return False
        svc = self.store.api_service_for(parts[1], parts[2])
        if svc is None:
            return False
        import urllib.error
        import urllib.request

        endpoint = svc.service_endpoint
        if "://" not in endpoint:
            endpoint = f"http://{endpoint}"
        target = endpoint.rstrip("/") + self.path
        body = None
        if body_doc is not None:
            body = json.dumps(body_doc).encode()
        else:
            length = int(self.headers.get("Content-Length", 0) or 0)
            if length:
                body = self.rfile.read(length)
        req = urllib.request.Request(
            target, data=body, method=self.command,
            headers={k: v for k, v in self.headers.items()
                     if k.lower() in ("content-type", "accept")})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = resp.read()
                self.send_response(resp.status)
                self.send_header("Content-Type",
                                 resp.headers.get("Content-Type",
                                                  "application/json"))
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
        except urllib.error.HTTPError as e:
            payload = e.read()
            self.send_response(e.code)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except OSError as e:
            self._error(503, "ServiceUnavailable",
                        f"aggregated apiserver {svc.meta.name}: {e}")
        return True

    def _resolve(self, path: str):
        """Static route table first, then registered CRDs (the
        apiextensions customresource_handler.go dynamic path)."""
        r = _route(path)
        if r is not None:
            return r
        parts = [p for p in path.split("/") if p]
        if len(parts) >= 4 and parts[0] == "apis":
            group, version = parts[1], parts[2]
            rest = parts[3:]
            ns = None
            if len(rest) >= 3 and rest[0] == "namespaces":
                ns = rest[1]
                rest = rest[2:]
            if rest:
                crd = self.store.crd_for_plural(group, rest[0])
                if crd is not None and crd.version == version:
                    name = rest[1] if len(rest) > 1 else None
                    sub = rest[2] if len(rest) > 2 else None
                    return (f"apis/{group}/{version}", crd.kind, ns, name, sub)
        return None

    def log_message(self, *args):
        pass

    # ------------------------------------------------- handler-chain middleware

    _VERB_BY_METHOD = {"POST": "create", "PUT": "update", "DELETE": "delete"}

    def _request_verb(self) -> str:
        if self.command == "GET":
            url = urlparse(self.path)
            q = parse_qs(url.query)
            if q.get("watch", ["0"])[0] in ("1", "true"):
                return "watch"
            r = self._resolve(url.path)
            return "get" if (r is not None and r[3] is not None) else "list"
        return self._VERB_BY_METHOD.get(self.command, "get")

    def _gate(self):
        """authn → flow control → authz. Returns a release callable to run
        when the request finishes, or None if a response was already sent.
        Gate failures close the connection: the request body may be undrained
        on the socket, which would corrupt keep-alive reuse."""
        from .auth import AuthenticationError

        verb = self._request_verb()
        user_name, groups = "system:admin", ()
        cfg = self.auth
        if cfg is not None and cfg.authenticator is not None:
            try:
                user = cfg.authenticator.authenticate(self.headers)
            except AuthenticationError as e:
                self.close_connection = True
                self._error(401, "Unauthorized", str(e))
                return None
            user_name, groups = user.name, user.groups
        elif cfg is not None and cfg.authorizer is not None:
            # authorization without authentication: unauthenticated traffic
            # is ANONYMOUS, never the admin default (everyone-is-admin) and
            # never a spoofable X-Remote-User header — asserting an identity
            # against an active authorizer requires an Authenticator that
            # opted into proxy-header trust.
            from .auth import ANONYMOUS, GROUP_UNAUTHENTICATED

            user_name, groups = ANONYMOUS, (GROUP_UNAUTHENTICATED,)
        elif self.headers.get("X-Remote-User"):
            # no authenticator and no authorizer (open server): trust the
            # proxy header so the NodeRestriction admission seam still sees
            # kubelet identities
            user_name = self.headers["X-Remote-User"]
        self.store.set_request_user(user_name, groups)
        release = lambda: None  # noqa: E731
        if cfg is not None and cfg.flow is not None:
            release = cfg.flow.dispatch(user_name, groups, verb)
            if release is None:
                self.close_connection = True
                self._error(429, "TooManyRequests",
                            "request rejected by priority-and-fairness")
                return None
        if cfg is not None and cfg.authorizer is not None:
            r = self._resolve(urlparse(self.path).path)
            kind = r[1] if r is not None else ""
            name = r[3] or "" if r is not None else ""
            sub = r[4] or "" if r is not None else ""
            if r is not None and name and r[2] is not None \
                    and kind not in self.store.CLUSTER_SCOPED_KINDS:
                # namespaced objects authorize by their store key — a bare
                # name would collapse same-named objects across namespaces
                # (the NodeAuthorizer graph check depends on this)
                name = f"{r[2]}/{name}"
            if not cfg.authorizer.allowed_for(user_name, groups, verb, kind,
                                              name, sub):
                release()
                self.close_connection = True
                self._error(403, "Forbidden",
                            f"user {user_name!r} cannot {verb} {kind}")
                return None
        return release

    # ------------------------------------------------------------- helpers

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, reason: str, message: str) -> None:
        # metav1.Status shape
        self._send_json(code, {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code,
        })

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n)
        # content negotiation (runtime/serializer/protobuf): a protobuf
        # body rides through _decode_body via the raw-bytes marker
        if _PROTO_CT in (self.headers.get("Content-Type") or ""):
            return {_PROTO_BODY_KEY: raw}
        return json.loads(raw or b"{}")

    def _wants_proto(self) -> bool:
        return _PROTO_CT in (self.headers.get("Accept") or "")

    def _send_proto(self, code: int, payload: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", _PROTO_CT)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _obj_wire(self, kind: str, obj) -> dict:
        d = to_wire(obj)
        d["kind"] = kind
        return d

    def _decode_body(self, kind: str, body: dict):
        """Three wire dialects on the write path: protobuf (magic-prefixed
        KObject bytes via content negotiation), a REFERENCE-shaped manifest
        (apiVersion + metadata) through the versioned scheme
        (api/scheme.py), else this framework's snake_case reflection
        format."""
        if _PROTO_BODY_KEY in body:
            from ..api.protobuf import decode_object

            got_kind, obj = decode_object(body[_PROTO_BODY_KEY], kind)
            if got_kind != kind:
                raise ValueError(f"protobuf body is a {got_kind}, not {kind}")
            return obj
        if kind == "CustomResourceDefinition" and "metadata" in body:
            # apiextensions manifest: registration fields live at the top
            # level of the reduced CRD model
            from ..api.corev1 import meta_from
            from ..api.types import CustomResourceDefinition

            spec = body.get("spec") or {}
            names = spec.get("names") or {}
            versions = spec.get("versions") or ()
            version = (versions[0].get("name", "v1") if versions
                       else body.get("version", "v1"))
            return CustomResourceDefinition(
                meta=meta_from(body.get("metadata") or {}),
                group=spec.get("group", body.get("group", "")),
                version=version,
                kind=names.get("kind", body.get("kind_", "")),
                plural=names.get("plural", body.get("plural", "")),
                namespaced=(spec.get("scope", "Namespaced") == "Namespaced"
                            if "scope" in spec
                            else bool(body.get("namespaced", True))),
            )
        if kind not in _KIND_TYPES:
            # dynamic (CRD-served) kind: manifest-shaped body → CustomResource
            from ..api.corev1 import meta_from
            from ..api.types import CustomResource

            return CustomResource(
                meta=meta_from(body.get("metadata") or {}),
                api_version=body.get("apiVersion", ""),
                kind=body.get("kind", kind),
                spec=dict(body.get("spec") or {}),
                status=dict(body.get("status") or {}),
            )
        if "apiVersion" in body and "metadata" in body:
            # a manifest-shaped body MUST decode through the scheme: an
            # unregistered apiVersion is a clear 400, never a silent
            # fall-through to the reflection decoder (which would turn
            # camelCase keys into a default-valued object)
            from ..api.scheme import default_scheme

            obj = default_scheme().decode(dict(body, kind=body.get("kind") or kind))
            if not isinstance(obj, _KIND_TYPES[kind]):
                raise ValueError(
                    f"body kind {type(obj).__name__} does not match "
                    f"path resource {kind}")
            return obj
        return from_wire(_KIND_TYPES[kind], body)

    def _cluster_scoped(self, kind: str) -> bool:
        return self.store.is_cluster_scoped(kind)

    def _match(self, kind: str, ns: Optional[str], obj) -> bool:
        return (ns is None or self._cluster_scoped(kind)
                or obj.meta.namespace == ns)

    # ------------------------------------------------------------- verbs

    def do_GET(self):  # noqa: N802
        release = self._gate()
        if release is None:
            return
        try:
            return self._serve_get()
        finally:
            release()

    def _serve_get(self):
        url = urlparse(self.path)
        r = self._resolve(url.path)
        if r is None:
            if self._maybe_aggregate(url.path):
                return
            return self._error(404, "NotFound", f"unknown path {url.path}")
        _g, kind, ns, name, _sub = r
        q = parse_qs(url.query)
        if name is None and q.get("watch", ["0"])[0] in ("1", "true"):
            rv_raw = q.get("resourceVersion", [None])[0]
            if rv_raw is None:
                # unset = "from current state" (reference semantics): never
                # 410, no backlog replay — long-lived servers trim the
                # journal, and an rv-less watch must still establish
                _objs, since = self.store.list_objects(kind)
            else:
                try:
                    since = int(rv_raw)
                except ValueError:
                    return self._error(400, "BadRequest",
                                       f"invalid resourceVersion {rv_raw!r}")
            return self._watch(kind, ns, since)
        if name is None:
            objs, rv = self.store.list_objects(kind)
            matched = [o for o in objs if self._match(kind, ns, o)]
            if self._wants_proto():
                from ..api.protobuf import encode_list

                return self._send_proto(200, encode_list(kind, matched, rv))
            return self._send_json(200, {
                "kind": f"{kind}List", "apiVersion": "v1",
                "metadata": {"resourceVersion": str(rv)},
                "items": [self._obj_wire(kind, o) for o in matched],
            })
        key = name if self._cluster_scoped(kind) else f"{ns}/{name}"
        obj = self.store.get_object(kind, key)
        if obj is None or not self._match(kind, ns, obj):
            return self._error(404, "NotFound", f"{kind} {key} not found")
        if self._wants_proto():
            from ..api.protobuf import encode_object

            return self._send_proto(200, encode_object(kind, obj))
        return self._send_json(200, self._obj_wire(kind, obj))

    def _watch(self, kind: str, ns: Optional[str], since: int) -> None:
        try:
            w = self.store.watch(kind, since)
        except Expired as e:
            return self._error(410, "Expired", str(e))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while True:
                ev = w.next(timeout=0.5)
                if ev is None:
                    if self.server.__shutdown_request__:
                        break
                    continue
                obj = ev.object
                if not self._match(kind, ns, obj):
                    continue
                line = json.dumps({
                    "type": ev.type,
                    "object": self._obj_wire(kind, obj),
                    "resourceVersion": str(ev.seq),
                }).encode() + b"\n"
                self.wfile.write(hex(len(line))[2:].encode() + b"\r\n" + line + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            w.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def do_POST(self):  # noqa: N802
        release = self._gate()
        if release is None:
            return
        try:
            return self._serve_post()
        finally:
            release()

    def _serve_post(self):
        body = self._body()  # drain FIRST: keep-alive sockets must not carry leftovers
        r = self._resolve(urlparse(self.path).path)
        if r is None:
            if self._maybe_aggregate(urlparse(self.path).path, body_doc=body):
                return
            return self._error(404, "NotFound", "unknown path")
        _g, kind, ns, name, sub = r
        if kind == "Pod" and name is not None and sub == "binding":
            # BindingREST.Create (storage.go:169)
            target = body.get("target", {}).get("name", "")
            if not target:
                return self._error(400, "BadRequest", "binding target.name is required")
            try:
                self.store.bind(Binding(pod_key=f"{ns}/{name}", node_name=target))
            except NotFound as e:
                return self._error(404, "NotFound", str(e))
            except Conflict as e:
                return self._error(409, "Conflict", str(e))
            return self._send_json(201, {"kind": "Status", "status": "Success"})
        if name is not None:
            return self._error(405, "MethodNotAllowed", "POST to a named resource")
        try:
            obj = self._decode_body(kind, body)
        except Exception as e:  # noqa: BLE001 — malformed body is a 400
            return self._error(400, "BadRequest", f"decode: {e}")
        if ns is not None and not self._cluster_scoped(kind):
            obj.meta.namespace = ns
        try:
            self.store.create_object(kind, obj)
        except Conflict as e:
            return self._error(409, "AlreadyExists", str(e))
        except AdmissionError as e:
            return self._error(403, "Forbidden", str(e))
        except ValidationError as e:
            return self._error(422, "Invalid", str(e))
        return self._send_json(201, self._obj_wire(kind, obj))

    def do_PUT(self):  # noqa: N802
        release = self._gate()
        if release is None:
            return
        try:
            return self._serve_put()
        finally:
            release()

    def _serve_put(self):
        body = self._body()  # drain first (keep-alive)
        r = self._resolve(urlparse(self.path).path)
        if r is None or r[3] is None:
            if r is None and self._maybe_aggregate(
                    urlparse(self.path).path, body_doc=body):
                return
            return self._error(404, "NotFound", "unknown path")
        _g, kind, ns, name, _sub = r
        try:
            obj = self._decode_body(kind, body)
        except Exception as e:  # noqa: BLE001
            return self._error(400, "BadRequest", f"decode: {e}")
        if obj.meta.name and obj.meta.name != name:
            return self._error(400, "BadRequest",
                               f"body name {obj.meta.name!r} != URL name {name!r}")
        obj.meta.name = name
        if ns is not None and not self._cluster_scoped(kind):
            obj.meta.namespace = ns
        try:
            self.store.update_object(kind, obj)
        except NotFound as e:
            return self._error(404, "NotFound", str(e))
        except Conflict as e:
            return self._error(409, "Conflict", str(e))
        except AdmissionError as e:
            return self._error(403, "Forbidden", str(e))
        except ValidationError as e:
            return self._error(422, "Invalid", str(e))
        return self._send_json(200, self._obj_wire(kind, obj))

    def do_DELETE(self):  # noqa: N802
        release = self._gate()
        if release is None:
            return
        try:
            return self._serve_delete()
        finally:
            release()

    def _serve_delete(self):
        self._body()  # drain DeleteOptions bodies (keep-alive invariant)
        r = self._resolve(urlparse(self.path).path)
        if r is None or r[3] is None:
            if r is None and self._maybe_aggregate(urlparse(self.path).path):
                return
            return self._error(404, "NotFound", "unknown path")
        _g, kind, ns, name, _sub = r
        key = name if self._cluster_scoped(kind) else f"{ns}/{name}"
        if kind == "Pod":
            try:
                self.store.delete_pod(key)
            except NotFound as e:
                return self._error(404, "NotFound", str(e))
        else:
            if self.store.get_object(kind, key) is None:
                return self._error(404, "NotFound", f"{kind} {key} not found")
            self.store.delete_object(kind, key)
        return self._send_json(200, {"kind": "Status", "status": "Success"})


def serve_api(store: ClusterStore, port: int = 0, auth=None):
    """Serve the REST+watch API on localhost; returns (server, port).
    ``auth`` is an optional apiserver.auth.AuthConfig enabling the
    authn/flow-control/authz handler chain."""
    handler = type("BoundAPIHandler", (_Handler,), {"store": store, "auth": auth})
    authz_member = False
    if auth is not None and auth.authorizer is not None:
        # the admission seam (OwnerReferencesPermissionEnforcement) shares
        # the HTTP layer's authorizer; refcounted ON THE STORE so the LAST
        # authz-enabled server clears it on shutdown (no stale policy, no
        # clearing out from under a still-live sibling server, and no
        # touching an authorizer the caller installed manually — servers
        # only join the refcount when serve_api itself performed or shares
        # the install)
        with _AUTHZ_LOCK:
            count = getattr(store, "_authz_install_count", 0)
            if store.authorizer is None:
                store.authorizer = auth.authorizer
                store._authz_install_count = count + 1
                authz_member = True
            elif count > 0:  # a sibling serve_api installed it: share it
                store._authz_install_count = count + 1
                authz_member = True
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    server.__ktpu_installed_authorizer__ = (store if authz_member else None)
    server.__shutdown_request__ = False
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


_AUTHZ_LOCK = threading.Lock()


def shutdown_api(server) -> None:
    server.__shutdown_request__ = True
    store = getattr(server, "__ktpu_installed_authorizer__", None)
    if store is not None:
        with _AUTHZ_LOCK:
            n = getattr(store, "_authz_install_count", 1) - 1
            store._authz_install_count = max(n, 0)
            if n <= 0:
                store.authorizer = None  # last installer clears the seam
    server.shutdown()
    server.server_close()
