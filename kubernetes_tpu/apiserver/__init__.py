from .store import ClusterStore  # noqa: F401
