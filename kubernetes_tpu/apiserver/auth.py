"""Authn / authz / flow-control middleware for the HTTP apiserver front —
the reference's DefaultBuildHandlerChain stages (apiserver pkg/server/
config.go:806: authentication → authorization, flowcontrol APF in
pkg/util/flowcontrol), reduced to the shapes this framework needs:

- Authenticator: bearer-token map + authenticating-proxy headers
  (X-Remote-User / X-Remote-Group) + optional anonymous.
- RBACAuthorizer: ClusterRole/ClusterRoleBinding objects from the store
  (data-driven, like rbac.authorization.k8s.io), with system:masters bypass.
  Also satisfies the ``store.authorizer`` seam used by admission
  (OwnerReferencesPermissionEnforcement).
- FlowController: API Priority & Fairness analog — priority levels with
  concurrency limits and bounded queues; a full queue rejects (HTTP 429),
  matching APF's reject-when-queue-full behavior.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..api.types import ObjectMeta

ANONYMOUS = "system:anonymous"
GROUP_UNAUTHENTICATED = "system:unauthenticated"
GROUP_AUTHENTICATED = "system:authenticated"
GROUP_MASTERS = "system:masters"


@dataclasses.dataclass(frozen=True)
class UserInfo:
    name: str
    groups: Tuple[str, ...] = ()


class AuthenticationError(Exception):
    """401: credentials presented and rejected."""


class Authenticator:
    """Union authenticator (apiserver pkg/authentication): bearer tokens,
    authenticating-proxy headers, then anonymous."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None,
                 allow_anonymous: bool = True,
                 trust_proxy_headers: Optional[bool] = None):
        self.tokens = tokens or {}
        self.allow_anonymous = allow_anonymous
        # X-Remote-* headers assert an identity the server cannot verify —
        # the reference only trusts them from a cert-verified front proxy.
        # Default: trust them ONLY when no token auth is configured (the
        # trusted-sidecar topology); with tokens present, an explicit opt-in
        # is required, else any client could spoof system:masters.
        if trust_proxy_headers is None:
            trust_proxy_headers = not self.tokens
        self.trust_proxy_headers = trust_proxy_headers

    def authenticate(self, headers) -> UserInfo:
        authz = headers.get("Authorization", "")
        if authz.startswith("Bearer "):
            token = authz[len("Bearer "):].strip()
            user = self.tokens.get(token)
            if user is None:
                raise AuthenticationError("invalid bearer token")
            return UserInfo(user.name, tuple(user.groups) + (GROUP_AUTHENTICATED,))
        if self.trust_proxy_headers:
            name = headers.get("X-Remote-User", "")
            if name:
                groups = tuple(
                    g.strip() for g in headers.get("X-Remote-Group", "").split(",")
                    if g.strip())
                return UserInfo(name, groups + (GROUP_AUTHENTICATED,))
        if self.allow_anonymous:
            return UserInfo(ANONYMOUS, (GROUP_UNAUTHENTICATED,))
        raise AuthenticationError("no credentials")


# --------------------------------------------------------------------- RBAC

@dataclasses.dataclass
class PolicyRule:
    """rbac/v1 PolicyRule (verbs × resources × resourceNames; '*' wildcards)."""

    verbs: Tuple[str, ...] = ("*",)
    resources: Tuple[str, ...] = ("*",)       # kind names, e.g. "Pod"
    resource_names: Tuple[str, ...] = ()      # () = any
    subresources: Tuple[str, ...] = ("*",)    # e.g. "binding", "finalizers"

    def matches(self, verb: str, kind: str, name: str, subresource: str) -> bool:
        if "*" not in self.verbs and verb not in self.verbs:
            return False
        if "*" not in self.resources and kind not in self.resources:
            return False
        if self.resource_names and name not in self.resource_names \
                and name.rsplit("/", 1)[-1] not in self.resource_names:
            # rbac resourceNames are bare object names; callers may pass the
            # namespace-qualified store key (the node-authorizer contract)
            return False
        if subresource and "*" not in self.subresources \
                and subresource not in self.subresources:
            return False
        return True


@dataclasses.dataclass
class ClusterRole:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    rules: Tuple[PolicyRule, ...] = ()
    # rbac/v1 AggregationRule, reduced to label-selector match dicts: when
    # set, the clusterrole-aggregation controller overwrites ``rules`` with
    # the union of every matching ClusterRole's rules
    aggregation_selectors: Tuple[Dict[str, str], ...] = ()


@dataclasses.dataclass
class ClusterRoleBinding:
    """rbac/v1 ClusterRoleBinding: subjects are "user:NAME" or "group:NAME"."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    role: str = ""                    # ClusterRole name
    subjects: Tuple[str, ...] = ()


class RBACAuthorizer:
    """Data-driven RBAC over the store's ClusterRole/ClusterRoleBinding maps
    (plugin/pkg/auth/authorizer/rbac). system:masters always passes."""

    def __init__(self, store):
        self.store = store

    def _user_matches(self, subject: str, user: str, groups: Tuple[str, ...]) -> bool:
        if subject.startswith("user:"):
            return subject[5:] == user
        if subject.startswith("group:"):
            return subject[6:] in groups
        return subject == user  # bare subject = user name

    def allowed_for(self, user: str, groups: Tuple[str, ...], verb: str,
                    kind: str, name: str = "", subresource: str = "") -> bool:
        if GROUP_MASTERS in groups:
            return True
        for b in self.store.cluster_role_bindings.values():
            if not any(self._user_matches(s, user, groups) for s in b.subjects):
                continue
            role = self.store.cluster_roles.get(b.role)
            if role is None:
                continue
            for rule in role.rules:
                if rule.matches(verb, kind, name, subresource):
                    return True
        return False

    def allowed(self, user: str, verb: str, kind: str, name: str = "",
                subresource: str = "") -> bool:
        """store.authorizer seam (admission's blockOwnerDeletion check)."""
        return self.allowed_for(user, (), verb, kind, name, subresource)


class NodeAuthorizer:
    """Graph-based node authorizer (plugin/pkg/auth/authorizer/node
    node_authorizer.go): a kubelet identity (``system:node:<name>``) may
    read a Secret/ConfigMap/PVC only when some pod BOUND TO THAT NODE
    references it, and may touch its own Node/Lease and pods bound to
    itself. Non-node users delegate to the wrapped authorizer (RBAC)."""

    _GRAPH_KINDS = {"Secret", "ConfigMap", "PersistentVolumeClaim"}
    _READ_VERBS = {"get", "list", "watch"}

    def __init__(self, store, delegate=None):
        self.store = store
        self.delegate = delegate

    @staticmethod
    def _node_of(user: str):
        return user[len("system:node:"):] if user.startswith("system:node:") else None

    # kinds a kubelet may READ freely (the informer surfaces a node agent
    # list/watches); everything else is default-deny for node identities
    _OPEN_READ_KINDS = {"Node", "Pod", "Service", "Endpoints", "EndpointSlice",
                        "Namespace", "Lease", "StorageClass", "CSINode",
                        "PersistentVolume", "RuntimeClass"}

    def _referenced_on_node(self, kind: str, name: str, node: str) -> bool:
        # name must be the fully-qualified store key ("ns/name"): a bare
        # name would let a node read the same-named object in ANY namespace
        if "/" not in name:
            return False
        with self.store._lock:  # threaded API server: pods map is shared
            pods = list(self.store.pods.values())
        for pod in pods:
            if pod.spec.node_name != node:
                continue
            ns = pod.meta.namespace
            if kind == "Secret":
                refs = pod.spec.secret_volumes
            elif kind == "ConfigMap":
                refs = pod.spec.config_map_volumes
            else:  # PersistentVolumeClaim
                refs = pod.spec.volumes
            if any(f"{ns}/{r}" == name for r in refs):
                return True
        return False

    def allowed_for(self, user: str, groups: Tuple[str, ...], verb: str,
                    kind: str, name: str = "", subresource: str = "") -> bool:
        node = self._node_of(user)
        if node is None:
            return (self.delegate.allowed_for(user, groups, verb, kind, name,
                                              subresource)
                    if self.delegate is not None else False)
        if kind in self._GRAPH_KINDS:
            return (verb in self._READ_VERBS
                    and bool(name) and self._referenced_on_node(kind, name, node))
        if kind in ("Node", "Lease"):
            # own object only for writes; reads are unrestricted (kubelets
            # watch the node corpus for their own object updates). Lease
            # names arrive namespace-qualified ("kube-node-lease/<node>")
            # from the HTTP gate — compare the bare object name.
            if verb in self._READ_VERBS:
                return True
            return name.rsplit("/", 1)[-1] in ("", node)
        if kind == "Pod":
            if verb in self._READ_VERBS:
                return True
            # writes only against pods already bound to this node (status
            # updates, deletes on eviction) — enforced here as well as by
            # NodeRestriction admission, since the two are configured
            # independently (node_authorizer.go does the same)
            pod = self.store.pods.get(name)
            return pod is not None and pod.spec.node_name == node
        if kind == "Event":
            return verb == "create"
        if verb in self._READ_VERBS and kind in self._OPEN_READ_KINDS:
            return True
        # default-deny: a kubelet identity gets nothing else (in particular
        # no RBAC/webhook/workload writes — node_authorizer.go fails closed)
        return False

    def allowed(self, user: str, verb: str, kind: str, name: str = "",
                subresource: str = "") -> bool:
        return self.allowed_for(user, (), verb, kind, name, subresource)


# ---------------------------------------------------------------------- APF

@dataclasses.dataclass
class PriorityLevel:
    """flowcontrol/v1beta2 PriorityLevelConfiguration, reduced: concurrency
    shares become an absolute in-flight limit; a full queue rejects."""

    name: str
    concurrency: int = 4
    queue_length: int = 16
    exempt: bool = False


@dataclasses.dataclass
class FlowSchema:
    """Maps (user, groups, verb) to a priority level, first match wins
    (flowcontrol FlowSchema matchingPrecedence order)."""

    name: str
    level: str
    users: Tuple[str, ...] = ()      # () = any
    groups: Tuple[str, ...] = ()
    verbs: Tuple[str, ...] = ()

    def matches(self, user: str, groups: Tuple[str, ...], verb: str) -> bool:
        if self.users and user not in self.users:
            return False
        if self.groups and not (set(self.groups) & set(groups)):
            return False
        if self.verbs and verb not in self.verbs:
            return False
        return True


def default_flow_config() -> Tuple[List[PriorityLevel], List[FlowSchema]]:
    """The reference's suggested configuration, reduced
    (apf bootstrap configuration: exempt, system, workload-high,
    global-default, catch-all)."""
    levels = [
        PriorityLevel("exempt", exempt=True),
        PriorityLevel("system", concurrency=16, queue_length=64),
        PriorityLevel("workload-high", concurrency=8, queue_length=32),
        PriorityLevel("global-default", concurrency=4, queue_length=16),
        PriorityLevel("catch-all", concurrency=2, queue_length=0),
    ]
    schemas = [
        FlowSchema("exempt", "exempt", groups=(GROUP_MASTERS,)),
        FlowSchema("system-nodes", "system", groups=("system:nodes",)),
        FlowSchema("system-components", "system",
                   users=("system:kube-scheduler", "system:kube-controller-manager")),
        FlowSchema("watches", "exempt", verbs=("watch",)),  # long-lived streams
        FlowSchema("global-default", "global-default",
                   groups=(GROUP_AUTHENTICATED,)),
        FlowSchema("catch-all", "catch-all"),
    ]
    return levels, schemas


class FlowController:
    """In-flight concurrency control per priority level. ``dispatch`` returns
    a release callable, or None when the level's queue is full (→ 429).
    Waiting requests block up to ``wait_timeout`` for a slot (the queueing
    behavior APF models with fair queuing, collapsed to FIFO)."""

    def __init__(self, levels: Optional[List[PriorityLevel]] = None,
                 schemas: Optional[List[FlowSchema]] = None,
                 wait_timeout: float = 5.0):
        if levels is None or schemas is None:
            levels, schemas = default_flow_config()
        self.levels = {l.name: l for l in levels}
        self.schemas = schemas
        self.wait_timeout = wait_timeout
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._in_flight: Dict[str, int] = {l: 0 for l in self.levels}
        self._queued: Dict[str, int] = {l: 0 for l in self.levels}
        self.rejected_total: Dict[str, int] = {l: 0 for l in self.levels}
        self.dispatched_total: Dict[str, int] = {l: 0 for l in self.levels}

    def classify(self, user: str, groups: Tuple[str, ...], verb: str) -> str:
        for s in self.schemas:
            if s.matches(user, groups, verb) and s.level in self.levels:
                return s.level
        # unmatched traffic takes the LAST non-exempt (lowest-priority,
        # catch-all) level — never fail open into an exempt level, even
        # with a custom level list whose last entry happens to be exempt
        for name in reversed(self.levels):
            if not self.levels[name].exempt:
                return name
        return next(iter(self.levels))  # all-exempt config: nothing to guard

    def dispatch(self, user: str, groups: Tuple[str, ...], verb: str
                 ) -> Optional[Callable[[], None]]:
        level_name = self.classify(user, groups, verb)
        level = self.levels[level_name]
        if level.exempt:
            with self._lock:  # += on a shared counter is read-modify-write
                self.dispatched_total[level_name] += 1
            return lambda: None
        deadline = None
        with self._cond:
            if self._in_flight[level_name] >= level.concurrency:
                if self._queued[level_name] >= level.queue_length:
                    self.rejected_total[level_name] += 1
                    return None
                self._queued[level_name] += 1
                import time as _time

                deadline = _time.monotonic() + self.wait_timeout
                try:
                    while self._in_flight[level_name] >= level.concurrency:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0 or not self._cond.wait(remaining):
                            if self._in_flight[level_name] < level.concurrency:
                                break
                            self.rejected_total[level_name] += 1
                            return None
                finally:
                    self._queued[level_name] -= 1
            self._in_flight[level_name] += 1
            self.dispatched_total[level_name] += 1

        def release() -> None:
            with self._cond:
                self._in_flight[level_name] -= 1
                self._cond.notify_all()

        return release


@dataclasses.dataclass
class AuthConfig:
    """The middleware bundle serve_api accepts; every field optional —
    None disables that stage (matching the previous open server)."""

    authenticator: Optional[Authenticator] = None
    authorizer: Optional[RBACAuthorizer] = None
    flow: Optional[FlowController] = None
