"""Durable store stand-in: write-ahead log + snapshot for ClusterStore.

The reference's store survives restarts because etcd does (raft + WAL,
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:72,328); this
repo's ClusterStore is memory-only, so the crash-only recovery story
("rebuild from the store") bottomed out in a store that itself could not
crash (VERDICT r3 missing #4). This module closes that hole:

  * ``WriteAheadLog`` — append-only JSON-lines journal hooked into the
    store's single mutation funnel (``_journal_event``, which every
    create/update/delete runs inside its critical section), so the log
    order IS the store's linearized mutation order — the property etcd's
    raft log provides.
  * ``snapshot()`` — compaction: dump current state, truncate the log
    (etcd's periodic snapshot + WAL truncation).
  * ``restore()`` — rebuild a ClusterStore from snapshot + log replay;
    informers then relist against the restored store and every component
    resumes (the crash-only contract, SURVEY §5.3/§5.4).

Records carry the object's wire form (api/codec.py) plus its python type
name; type resolution covers api.types and the auth/admission object
families (ClusterRole, WebhookConfiguration) that also live in the store.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Iterator, Optional

from ..api.codec import from_wire, to_wire

_SNAP_SUFFIX = ".snap"

logger = logging.getLogger(__name__)


def _resolve_type(type_name: str):
    from ..api import types as api_types

    cls = getattr(api_types, type_name, None)
    if cls is None:
        from . import auth

        cls = getattr(auth, type_name, None)
    if cls is None:
        from . import admission

        cls = getattr(admission, type_name, None)
    if cls is None:
        raise TypeError(f"WAL cannot resolve type {type_name!r}")
    return cls


class WriteAheadLog:
    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self.records_appended = 0
        self.lines_written = 0  # group commits: lines << records
        # auto-compaction trigger (KTPU_WAL_COMPACT_LINES, default off):
        # once this many lines accumulate past the last snapshot, the next
        # housekeeping ``maybe_compact`` folds them into path + '.snap' —
        # bounding restart replay time under long-lived churn
        self.compact_lines = int(
            os.environ.get("KTPU_WAL_COMPACT_LINES", "0") or 0)
        self._lines_at_compact = 0

    # ------------------------------------------------------------- appending

    @staticmethod
    def _record(seq: int, kind: str, event: str, key: str, obj) -> dict:
        rec = {"seq": seq, "kind": kind, "event": event, "key": key}
        if obj is not None:
            rec["type"] = type(obj).__name__
            rec["obj"] = to_wire(obj)
            rv = getattr(getattr(obj, "meta", None), "resource_version", None)
            if rv is not None:
                rec["rv"] = rv
        return rec

    def _write_line(self, body: str, n_records: int) -> None:
        # per-record guard: an 8-hex crc32 of the JSON body prefixes every
        # line, so replay can tell a torn tail (the process died mid-write,
        # etcd walpb.Record's CRC role) from a clean record
        line = f"{zlib.crc32(body.encode()):08x} {body}\n"
        # deliberate blocking-under-lock: append runs inside the store
        # mutator's critical section BY CONTRACT (journal order must match
        # map mutation order — see ClusterStore._journal_event)
        from ..testing import locktrace

        locktrace.note_blocking(
            "wal_append", self.path,
            allowed="WAL order must match the store journal order")
        with self._lock:
            self._f.write(line)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self.records_appended += n_records
            self.lines_written += 1

    def append(self, seq: int, kind: str, event: str, key: str, obj) -> None:
        self._write_line(json.dumps(self._record(seq, kind, event, key, obj)), 1)

    def append_batch(self, records) -> None:
        """Group commit (the etcd batched-raft-entry analog): ONE crc-framed
        line — one write + flush (+ optional fsync) — carries a whole
        commit's worth of records. ``records`` is a sequence of
        ``(seq, kind, event, key, obj)`` tuples in journal order. Replay
        semantics stay PER-RECORD: ``replay`` unpacks the envelope and
        yields the inner records in order, and the torn-tail rule is
        unchanged — the crc covers the whole line, so a batch record torn
        mid-write drops atomically (none of its records replay; everything
        before the line is the durable prefix). A single-record batch
        writes the legacy per-record form, so the log stays byte-identical
        to the per-pod path when batching degenerates."""
        records = list(records)
        if not records:
            return
        if len(records) == 1:
            self.append(*records[0])
            return
        recs = [self._record(*r) for r in records]
        self._write_line(json.dumps({"batch": recs}), len(recs))

    def close(self) -> None:
        with self._lock:
            self._f.close()

    # ------------------------------------------------------------ compaction

    def snapshot(self, store) -> int:
        """Dump current store state to ``path + '.snap'`` and truncate the
        log (etcd's snapshot + WAL truncation). Returns objects dumped.

        The WHOLE operation — dump AND truncation — holds the store lock:
        WAL appends run inside the store's mutation critical section, so a
        writer that slipped between an unlocked dump and the truncation
        would land its record in the old file and have it wiped while the
        object is also absent from the snapshot (silent loss on restore)."""
        objs = []
        with store._lock:
            rv = store._rv
            seq = store._event_seq
            for kind in store.KINDS:
                for key, obj in store._kind_map(kind).items():
                    objs.append({"kind": kind, "key": key,
                                 "type": type(obj).__name__,
                                 "obj": to_wire(obj)})
            tmp = self.path + _SNAP_SUFFIX + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps({"rv": rv, "seq": seq}) + "\n")
                for rec in objs:
                    f.write(json.dumps(rec) + "\n")
            os.replace(tmp, self.path + _SNAP_SUFFIX)
            with self._lock:
                self._f.close()
                self._f = open(self.path, "w", encoding="utf-8")  # truncate
                self._lines_at_compact = self.lines_written
        return len(objs)

    def maybe_compact(self, store) -> bool:
        """Housekeeping hook: snapshot-compact once the log has grown
        ``compact_lines`` lines past the last compaction. Default off
        (threshold 0) — opt in via KTPU_WAL_COMPACT_LINES."""
        if self.compact_lines <= 0:
            return False
        with self._lock:
            grown = self.lines_written - self._lines_at_compact
        if grown < self.compact_lines:
            return False
        self.snapshot(store)
        return True


def _parse_line(line: str) -> Optional[dict]:
    """One WAL line → record dict, or None when torn/corrupt. Current
    format is ``<crc32hex> <json>``; a bare-JSON line (pre-checksum WAL)
    parses without the crc guard."""
    try:
        if len(line) > 9 and line[8] == " ":
            crc, body = line[:8], line[9:]
            try:
                expect = int(crc, 16)
            except ValueError:
                return json.loads(line)  # legacy bare JSON starting oddly
            if zlib.crc32(body.encode()) != expect:
                return None
            return json.loads(body)
        return json.loads(line)
    except ValueError:
        return None


def replay(path: str) -> Iterator[dict]:
    """Yield WAL records in append order, stopping CLEANLY at a truncated
    or corrupt record instead of raising — the crash left a torn tail (the
    write died mid-line); everything before it is the durable prefix, and
    availability beats the tail (crash-only recovery, SURVEY §5.3). If
    non-empty lines FOLLOW the corrupt one, that is more than a torn tail:
    log what is being dropped, still recover the clean prefix."""
    if not os.path.exists(path):
        return
    with open(path, encoding="utf-8") as f:
        # streamed, not readlines(): an un-compacted WAL can be huge and
        # replay runs at startup; the trailing-record count only walks the
        # remainder in the rare corrupt-record case
        for i, line in enumerate(f):
            line = line.rstrip("\n")
            if not line.strip():
                continue
            rec = _parse_line(line)
            if rec is None:
                trailing = sum(1 for rest in f if rest.strip())
                if trailing:
                    logger.warning(
                        "WAL %s: corrupt record at line %d with %d records "
                        "after it; replaying the clean prefix only",
                        path, i + 1, trailing)
                else:
                    logger.warning(
                        "WAL %s: torn tail at line %d (crash mid-append); "
                        "stopping replay cleanly", path, i + 1)
                return
            batch = rec.get("batch")
            if isinstance(batch, list):
                # group-commit envelope: yield the inner records in journal
                # order — per-record replay semantics preserved. The line's
                # crc already vouched for the WHOLE batch; a torn batch
                # never reaches this branch (it parses as None above).
                for sub in batch:
                    yield sub
                continue
            yield rec


def attach_wal(store, path: str, fsync: bool = False) -> WriteAheadLog:
    """Hook a WAL into a store's mutation funnel; returns the WAL."""
    wal = WriteAheadLog(path, fsync=fsync)
    store._wal = wal
    return wal


def restore(path: str, store_factory=None):
    """Rebuild a ClusterStore from snapshot + WAL replay. Admission and the
    WAL hook are disabled during replay (the records already passed
    admission when first written); the returned store has a FRESH WAL
    attached at the same path, pre-compacted to the restored state."""
    from .store import ClusterStore

    store = (store_factory or ClusterStore)()
    saved_admission, store.admission = store.admission, None
    max_rv = 0
    max_seq = 0
    try:
        snap_path = path + _SNAP_SUFFIX
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                header = json.loads(f.readline())
                max_rv = int(header.get("rv", 0))
                max_seq = int(header.get("seq", 0))
                for line in f:
                    rec = json.loads(line)
                    obj = from_wire(_resolve_type(rec["type"]), rec["obj"])
                    if rec["kind"] == "CustomResourceDefinition":
                        store._register_crd_kind(obj)
                    store._kind_map(rec["kind"])[rec["key"]] = obj
        for rec in replay(path):
            m = store._kind_map(rec["kind"])
            if rec["event"] == "DELETED":
                m.pop(rec["key"], None)
            else:
                obj = from_wire(_resolve_type(rec["type"]), rec["obj"])
                if rec["kind"] == "CustomResourceDefinition":
                    store._register_crd_kind(obj)
                m[rec["key"]] = obj
                max_rv = max(max_rv, int(rec.get("rv", 0) or 0))
            max_seq = max(max_seq, int(rec.get("seq", 0) or 0))
    finally:
        store.admission = saved_admission
    store._rv = max(store._rv, max_rv)
    store._event_seq = max(store._event_seq, max_seq)
    wal = attach_wal(store, path)
    wal.snapshot(store)  # compact: restored state becomes the new baseline
    return store
