"""Scheduler extender — the out-of-process extension protocol.

Analog of pkg/scheduler/extender.go (HTTPExtender :42, Filter :247,
Prioritize :317, Bind :359, ProcessPreemption :135) and the wire types at
staging/src/k8s.io/kube-scheduler/extender/v1/types.go.

The wire format is preserved exactly (ExtenderArgs/ExtenderFilterResult/
HostPriorityList JSON objects) so a real HTTP extender can be bridged; the
default transport is in-process (the config's ``instance`` escape hatch) —
this repo's own TPU backend *replaces* the extender idea with a batched
stateful sidecar, and the per-pod JSON protocol here exists for reference
parity + migration.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.types import Node, Pod
from ..framework.types import NodeInfo


class ExtenderError(Exception):
    pass


def pod_to_wire(pod: Pod) -> dict:
    return {
        "metadata": {"name": pod.meta.name, "namespace": pod.meta.namespace,
                     "labels": dict(pod.meta.labels)},
        "spec": {"priority": pod.spec.priority, "schedulerName": pod.spec.scheduler_name},
    }


class Extender:
    """The framework.Extender contract (framework/extender.go:27)."""

    def name(self) -> str:
        raise NotImplementedError

    def is_ignorable(self) -> bool:
        return False

    def filter(self, pod: Pod, nodes: List[Node]) -> Tuple[List[Node], Dict[str, str], Dict[str, str]]:
        """Returns (feasible nodes, failed node -> reason, failed-and-
        unresolvable node -> reason).  Unresolvable nodes are excluded from
        preemption (schedule_one.go:573-585 gives them precedence)."""
        raise NotImplementedError

    def prioritize(self, pod: Pod, nodes: List[Node]) -> Dict[str, int]:
        """Returns node name -> raw score (to be multiplied by weight)."""
        raise NotImplementedError

    def weight(self) -> int:
        return 1

    def is_binder(self) -> bool:
        return False

    def bind(self, pod: Pod, node_name: str) -> None:
        raise NotImplementedError

    def is_interested(self, pod: Pod) -> bool:
        return True

    def supports_preemption(self) -> bool:
        return False

    def process_preemption(
        self, pod: Pod, victims_by_node: Dict[str, List[Pod]], node_infos
    ) -> Dict[str, List[Pod]]:
        return victims_by_node


class CallableExtender(Extender):
    """In-process extender built from plain callables (the test seam the
    reference covers with fake extenders in extender_test.go)."""

    def __init__(
        self,
        name: str = "callable-extender",
        filter_fn: Optional[Callable[[Pod, List[Node]], Tuple[List[Node], Dict[str, str]]]] = None,
        prioritize_fn: Optional[Callable[[Pod, List[Node]], Dict[str, int]]] = None,
        bind_fn: Optional[Callable[[Pod, str], None]] = None,
        weight: int = 1,
        ignorable: bool = False,
        interested_fn: Optional[Callable[[Pod], bool]] = None,
    ):
        self._name = name
        self._filter = filter_fn
        self._prioritize = prioritize_fn
        self._bind = bind_fn
        self._weight = weight
        self._ignorable = ignorable
        self._interested = interested_fn

    def name(self) -> str:
        return self._name

    def is_ignorable(self) -> bool:
        return self._ignorable

    def weight(self) -> int:
        return self._weight

    def is_binder(self) -> bool:
        return self._bind is not None

    def is_interested(self, pod: Pod) -> bool:
        return self._interested(pod) if self._interested else True

    def filter(self, pod: Pod, nodes: List[Node]) -> Tuple[List[Node], Dict[str, str], Dict[str, str]]:
        if self._filter is None:
            return nodes, {}, {}
        out = self._filter(pod, nodes)
        if len(out) == 2:  # simple callables may omit the unresolvable map
            return out[0], out[1], {}
        return out

    def prioritize(self, pod: Pod, nodes: List[Node]) -> Dict[str, int]:
        if self._prioritize is None:
            return {n.meta.name: 0 for n in nodes}
        return self._prioritize(pod, nodes)

    def bind(self, pod: Pod, node_name: str) -> None:
        if self._bind is None:
            raise ExtenderError(f"extender {self._name} is not a binder")
        self._bind(pod, node_name)


class HTTPExtender(Extender):
    """The reference's JSON-over-HTTP extender (extender.go:42).

    One POST per verb per pod — the stateless per-pod protocol whose overhead
    motivates this framework's batched TPU sidecar (SURVEY.md §5.8)."""

    def __init__(
        self,
        url_prefix: str,
        filter_verb: str = "",
        prioritize_verb: str = "",
        bind_verb: str = "",
        preempt_verb: str = "",
        weight: int = 1,
        node_cache_capable: bool = False,
        ignorable: bool = False,
        timeout: float = 5.0,
    ):
        self.url_prefix = url_prefix.rstrip("/")
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.bind_verb = bind_verb
        self.preempt_verb = preempt_verb
        self._weight = weight
        self.node_cache_capable = node_cache_capable
        self._ignorable = ignorable
        self.timeout = timeout

    def name(self) -> str:
        return self.url_prefix

    def is_ignorable(self) -> bool:
        return self._ignorable

    def weight(self) -> int:
        return self._weight

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.url_prefix}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def filter(self, pod: Pod, nodes: List[Node]) -> Tuple[List[Node], Dict[str, str], Dict[str, str]]:
        if not self.filter_verb:
            return nodes, {}, {}
        args = {"Pod": pod_to_wire(pod)}
        if self.node_cache_capable:
            args["NodeNames"] = [n.meta.name for n in nodes]
        else:
            args["Nodes"] = {"Items": [{"metadata": {"name": n.meta.name}} for n in nodes]}
        result = self._post(self.filter_verb, args)
        if result.get("Error"):
            raise ExtenderError(result["Error"])
        unresolvable = dict(result.get("FailedAndUnresolvableNodes") or {})
        # unresolvable takes precedence over plain failed (schedule_one.go:573)
        failed = {
            k: v for k, v in (result.get("FailedNodes") or {}).items() if k not in unresolvable
        }
        if self.node_cache_capable and result.get("NodeNames") is not None:
            keep = set(result["NodeNames"])
        else:
            keep = {item["metadata"]["name"] for item in (result.get("Nodes") or {}).get("Items", [])}
        return [n for n in nodes if n.meta.name in keep], failed, unresolvable

    def prioritize(self, pod: Pod, nodes: List[Node]) -> Dict[str, int]:
        if not self.prioritize_verb:
            return {n.meta.name: 0 for n in nodes}
        args = {"Pod": pod_to_wire(pod), "NodeNames": [n.meta.name for n in nodes]}
        result = self._post(self.prioritize_verb, args)
        return {hp["Host"]: int(hp["Score"]) for hp in result or []}

    def bind(self, pod: Pod, node_name: str) -> None:
        result = self._post(self.bind_verb, {
            "PodName": pod.meta.name, "PodNamespace": pod.meta.namespace, "Node": node_name,
        })
        if result and result.get("Error"):
            raise ExtenderError(result["Error"])

    def supports_preemption(self) -> bool:
        return bool(self.preempt_verb)

    def process_preemption(self, pod: Pod, victims_by_node, node_infos):
        """(extender.go:135) POST ExtenderPreemptionArgs; returns the trimmed
        NodeNameToMetaVictims mapped back onto our Pod objects."""
        args = {
            "Pod": pod_to_wire(pod),
            "NodeNameToMetaVictims": {
                node: {"Pods": [{"UID": p.meta.uid or p.key()} for p in victims]}
                for node, victims in victims_by_node.items()
            },
        }
        result = self._post(self.preempt_verb, args)
        out = {}
        by_uid = {
            (p.meta.uid or p.key()): p
            for victims in victims_by_node.values()
            for p in victims
        }
        for node, meta in (result.get("NodeNameToMetaVictims") or {}).items():
            pods = [by_uid[v["UID"]] for v in meta.get("Pods", []) if v.get("UID") in by_uid]
            out[node] = pods
        return out


def build_extenders(configs: Sequence) -> List[Extender]:
    """scheduler.go:409 buildExtenders: config entries → Extender objects."""
    out: List[Extender] = []
    for c in configs:
        if getattr(c, "instance", None) is not None:
            out.append(c.instance)
            continue
        out.append(
            HTTPExtender(
                url_prefix=c.url_prefix,
                filter_verb=c.filter_verb,
                prioritize_verb=c.prioritize_verb,
                bind_verb=c.bind_verb,
                preempt_verb=c.preempt_verb,
                weight=c.weight,
                node_cache_capable=c.node_cache_capable,
                ignorable=c.ignorable,
            )
        )
    return out
