"""The Scheduler: wiring + the per-pod scheduling cycle.

Mirrors pkg/scheduler/scheduler.go (object + New wiring), eventhandlers.go
(informer → cache/queue routing, node-diff → ClusterEvent) and
schedule_one.go (the cycle: snapshot → PreFilter → Filter(+nominated 2-pass) →
(adaptive node sampling + rotation) → PreScore/Score → selectHost → assume →
Reserve → Permit → PreBind → Bind).

This is the *sequential oracle path* — semantically the reference scheduler.
The TPU batched path (backend/) replaces schedule_pod's filter+score middle
with one device call over a pod micro-batch; everything around it (queue,
cache, assume, bind, failure handling) is shared.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api.types import Node, Pod
from ..apiserver.store import ADDED, DELETED, MODIFIED, ClusterStore
from ..cache import Cache, Snapshot
from ..framework import interface as fw
from ..framework.interface import CycleState, Status
from ..framework.runtime import Framework
from ..framework.types import (
    ADD,
    Diagnosis,
    FitError,
    NODE,
    QueuedPodInfo,
    UPDATE_NODE_ALLOCATABLE,
    UPDATE_NODE_CONDITION,
    UPDATE_NODE_LABEL,
    UPDATE_NODE_TAINT,
    ClusterEvent,
)
from ..metrics import SchedulerMetrics, latency_ledger
from ..queue import SchedulingQueue
from ..queue import events as qevents
from ..utils.events import EventRecorder, TYPE_NORMAL, TYPE_WARNING
from ..utils.trace import Trace

MIN_FEASIBLE_NODES_TO_FIND = 100           # schedule_one.go:52
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # :56


import dataclasses as _dc


@_dc.dataclass
class WaitingPod:
    """One Permit-parked pod (runtime/waiting_pods_map.go waitingPod):
    binding resumes on allow, unreserve+failure on reject, and the
    housekeeping sweep rejects it once ``deadline`` passes."""

    fwk: "Framework"
    state: CycleState
    pod: Pod
    node_name: str
    pod_cycle: int
    t0: float
    deadline: Optional[float] = None
    plugin: str = ""  # the plugin that voted WAIT


class WaitingPods:
    """The Handle surface Permit plugins use to release or reject parked
    pods (interface.go Handle.IterateOverWaitingPods/GetWaitingPod) —
    Coscheduling drives whole-gang release/teardown through this."""

    def __init__(self, sched: "Scheduler"):
        self._sched = sched

    def iterate(self) -> List[Tuple[str, Pod]]:
        return [(k, wp.pod) for k, wp in self._sched.waiting_pods.items()]

    def allow(self, pod_key: str) -> bool:
        return self._sched.allow_waiting_pod(pod_key)

    def reject(self, pod_key: str, reason: str = "rejected while waiting on permit",
               plugins: Tuple[str, ...] = ()) -> bool:
        return self._sched.reject_waiting_pod(pod_key, reason=reason,
                                              plugins=plugins)


class _SyncCounters(dict):
    """The scheduler's coarse outcome counters (scheduled/attempts/errors),
    with an atomic ``inc``: the commit worker (backend/commit_plane.py)
    lands batch outcomes concurrently with the scheduling thread's precheck
    failures, and a bare ``d[k] += 1`` from two threads can lose updates.
    Plain dict reads everywhere else are unchanged."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._mu = threading.Lock()

    def inc(self, key: str, n: int = 1) -> None:
        with self._mu:
            self[key] = self.get(key, 0) + n


class Scheduler:
    def __init__(
        self,
        store: ClusterStore,
        profiles: Optional[Dict[str, Framework]] = None,
        percentage_of_nodes_to_score: int = 0,
        seed: int = 0,
        pod_initial_backoff: float = 1.0,
        pod_max_backoff: float = 10.0,
        assume_ttl: float = 30.0,
        now_fn=time.monotonic,
        extenders=None,
        metrics=None,
        recorder=None,
        informer_factory=None,
    ):
        self.store = store
        self.informer_factory = informer_factory
        self.extenders = list(extenders or [])
        self.smetrics = metrics if metrics is not None else SchedulerMetrics()
        self.recorder = recorder if recorder is not None else EventRecorder()
        self.trace_threshold_s = 0.1  # LogIfLong(100ms), schedule_one.go:313
        self.now_fn = now_fn
        self.cache = Cache(ttl=assume_ttl, now_fn=now_fn)
        self.snapshot = Snapshot()
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.next_start_node_index = 0
        self.rng = random.Random(seed)
        self.metrics: Dict[str, int] = _SyncCounters(
            schedule_attempts=0, scheduled=0, unschedulable=0, errors=0)
        # external-change sequence for the commit data plane's carry gate:
        # bumped (under _ext_mu — a lost bump would silently keep a stale
        # device carry) on every event that can change NODE-side truth the
        # device mirrors — node add/update/remove, and bound-pod add/update/
        # delete NOT caused by this scheduler's own commits. New PENDING
        # pods don't bump: they enter the queue, not the node tensors.
        self._ext_mu = threading.Lock()
        self._external_events = 0
        self._commit_plane = None  # built lazily (backend import is heavy)
        self.waiting_pods: Dict[str, WaitingPod] = {}
        self._reject_depth = 0  # nested teardown guard (reject_waiting_pod)
        self._last_cleanup = now_fn()
        self._last_unsched_flush = now_fn()
        self._reclaim_drainer = None  # built on first quota reclaim evict

        # Profiles are specs (plugin_config/plugin_args/registry dicts), NOT
        # pre-built Frameworks: the Scheduler owns the handle context, so
        # plugins always get a live snapshot_fn/client (profile.NewMap analog).
        handle_base = {
            "snapshot_fn": lambda: self.snapshot.list(),
            "ns_labels_fn": store.ns_labels,
            "client": store,
            "extenders": self.extenders,
            "metrics": self.smetrics,
            "now_fn": now_fn,
            "waiting_pods": WaitingPods(self),
        }
        specs = profiles or {"default-scheduler": {}}
        self.profiles: Dict[str, Framework] = {}
        for name, spec in specs.items():
            if isinstance(spec, Framework):  # escape hatch for tests
                self.profiles[name] = spec
                continue
            self.profiles[name] = Framework(
                dict(handle_base),
                plugin_config=spec.get("plugin_config"),
                plugin_args=spec.get("plugin_args"),
                registry=spec.get("registry"),
                profile_name=name,
            )

        event_map = {}
        for fwk in self.profiles.values():
            for ev, plugins in fwk.cluster_event_map().items():
                event_map.setdefault(ev, set()).update(plugins)
        first = next(iter(self.profiles.values()))
        from ..framework.plugins.coscheduling import pod_group_key
        from ..framework.plugins.names import QUOTA_ADMISSION

        self.queue = SchedulingQueue(
            less_key=first.queue_sort_key(),
            initial_backoff=pod_initial_backoff,
            max_backoff=pod_max_backoff,
            cluster_event_map=event_map,
            now_fn=now_fn,
            metrics=self.smetrics,
            gang_key_fn=pod_group_key,
            pre_enqueue_fn=self._pre_enqueue_gate,
            ns_weight_fn=self._ns_fair_weight,
        )
        # targeted quota-release moves: a released charge wakes exactly the
        # gated pods the freed headroom admits (shadow-ledger gate), never
        # the whole parked backlog of a still-over-quota namespace. Every
        # profile's QuotaAdmission shares ONE ledger — usage is cluster
        # state, and Reserve charges land in the pod's own profile's
        # instance while release/fair-share read through _quota_plugin().
        shared_quota = None
        for fwk in self.profiles.values():
            plugin = fwk.plugin(QUOTA_ADMISSION)
            if plugin is not None:
                plugin.on_release = self._on_quota_release
                plugin.on_evict = self._quota_evict
                if shared_quota is None:
                    shared_quota = plugin
                else:
                    plugin.share_ledger(shared_quota)
        self._add_all_event_handlers()

    # ------------------------------------------------------- quota admission

    def _quota_plugin(self, pod: Optional[Pod] = None):
        """The pod's profile's QuotaAdmission, else ANY profile's (the
        ledger is shared, so instances are interchangeable — and a custom
        first profile without the plugin must not hide the others')."""
        from ..framework.plugins.names import QUOTA_ADMISSION

        fwk = (self.profiles.get(pod.spec.scheduler_name)
               if pod is not None else None)
        if fwk is not None:
            plugin = fwk.plugin(QUOTA_ADMISSION)
            if plugin is not None:
                return plugin
        for fwk in self.profiles.values():
            plugin = fwk.plugin(QUOTA_ADMISSION)
            if plugin is not None:
                return plugin
        return None

    def _pre_enqueue_gate(self, pod: Pod):
        """SchedulingQueue admission gate: the pod's profile's PreEnqueue
        plugins. None = admit; a non-success Status = park gated."""
        fwk = self.profiles.get(pod.spec.scheduler_name)
        if fwk is None:
            return None
        status = fwk.run_pre_enqueue_plugins(pod)
        return None if status.is_success() else status

    def _ns_fair_weight(self, ns: str) -> Optional[float]:
        """Fair-share weight for the queue's DRR layer (None = the
        namespace is not a tenant and shares the default bucket)."""
        plugin = self._quota_plugin()
        return plugin.weight_for(ns) if plugin is not None else None

    def _on_quota_release(self, ns: str) -> int:
        plugin = self._quota_plugin()
        if plugin is None:
            return 0
        return self.queue.move_gated_pods(
            namespace=ns, plugin=plugin.name(),
            admit_fn=plugin.shadow_admitter(ns))

    def _notify_quota_pod_bound(self, pod: Pod) -> None:
        """A pod observed bound (assumed-confirmation is a no-op; an
        external binder's pod still charges the namespace ledger)."""
        plugin = self._quota_plugin(pod)
        if plugin is not None:
            plugin.pod_observed_bound(pod)

    def _notify_quota_pod_deleted(self, pod: Pod) -> None:
        """Release the pod's quota charge (if any) BEFORE the queue's
        reactivation wave runs, so the wave's gate re-check sees the freed
        headroom."""
        plugin = self._quota_plugin(pod)
        if plugin is not None:
            plugin.pod_deleted(pod)

    def _quota_evict(self, pods: List[Pod], reason: str) -> int:
        """Borrower preemption for the quota reclaim pass: whole-gang
        eviction through the drain orchestrator (delete + recreate unbound
        + targeted EVICTION queue move), built lazily on first reclaim."""
        orch = self._reclaim_drainer
        if orch is None:
            from ..controllers.drain import DrainOrchestrator

            orch = DrainOrchestrator(self.store, metrics=self.smetrics,
                                     queue=self.queue, now_fn=self.now_fn)
            self._reclaim_drainer = orch
        return orch.evict_pods(pods, reason=reason)

    # ----------------------------------------------------------- event wiring

    def _add_all_event_handlers(self) -> None:
        """eventhandlers.go:249 addAllEventHandlers.

        With an informer factory, events arrive through the shared-informer
        bus (reflector → DeltaFIFO → fan-out) and the loop pumps it each
        cycle. Without one, handlers sit directly on the store with the
        initial LIST replayed as ADDs (same ListAndWatch contract,
        reflector.go:254, minus the queueing)."""
        if self.informer_factory is not None:
            evmap = {"add": ADDED, "update": MODIFIED, "delete": DELETED}
            pod_inf = self.informer_factory.informer_for("Pod")
            node_inf = self.informer_factory.informer_for("Node")
            pod_inf.add_event_handler(lambda e, old, new: self._on_pod_event(evmap[e], old, new))
            node_inf.add_event_handler(lambda e, old, new: self._on_node_event(evmap[e], old, new))
            self.informer_factory.wait_for_cache_sync()
            # dynamic plugin-requested kinds (SchedulingQuota, PodGroup …)
            # have no informers — they ride the store's direct handler bus in
            # BOTH topologies. Skipping them here strands gated pods forever
            # on the production server: a quota raise would fire no queue
            # move, and gated pods are exempt from the timeout flush.
            self._add_dynamic_event_handlers()
            return
        for node in list(self.store.nodes.values()):
            self._on_node_event(ADDED, None, node)
        for pod in list(self.store.pods.values()):
            self._on_pod_event(ADDED, None, pod)
        self.store.add_event_handler("Pod", self._on_pod_event)
        self.store.add_event_handler("Node", self._on_node_event)
        self._add_dynamic_event_handlers()

    def _add_dynamic_event_handlers(self) -> None:
        """eventhandlers.go:249's dynamic-informer arm: a plugin that
        registered interest in a GVK the static wiring doesn't cover (e.g.
        a CRD-served kind) gets a handler that re-activates pods it failed —
        the extension story that makes plugin-requested custom kinds
        meaningful."""
        from ..framework.types import ClusterEvent, ALL

        static = {"Pod", "Node"}
        wanted = set()
        for fwk in self.profiles.values():
            for ev in fwk.cluster_event_map():
                kind = str(ev.resource)
                if kind not in static and not ev.is_wildcard():
                    wanted.add((kind, ev.resource))
        for kind, resource in wanted:
            def _handler(event, old, new, _res=resource):
                self.queue.move_all_to_active_or_backoff_queue(
                    ClusterEvent(_res, ALL))
            # registration is unconditional: handlers for kinds not served
            # yet simply never fire until a CRD starts serving the kind
            self.store.add_event_handler(kind, _handler)

    def _on_pod_event(self, event: str, old: Optional[Pod], new: Optional[Pod]) -> None:
        if event == ADDED:
            if new.spec.node_name:
                self._bump_external()  # pre-bound pod: external node truth
                self.cache.add_pod(new)
                self._notify_quota_pod_bound(new)
                self.queue.assigned_pod_updated_or_added(new)
            elif self._responsible_for(new):
                self.queue.add(new)
        elif event == MODIFIED:
            if new.spec.node_name:
                if old is not None and not old.spec.node_name:
                    if not self.cache.is_assumed(new.key()):
                        # an EXTERNAL binder's pod (a peer replica, a test
                        # poking the store) changes node truth; confirming
                        # our own assume does not — the device carry
                        # already holds that placement
                        self._bump_external()
                    self.cache.add_pod(new)  # binding confirmation
                    self._notify_quota_pod_bound(new)
                    self.queue.assigned_pod_updated_or_added(new)
                else:
                    self._bump_external()
                    self.cache.update_pod(old, new)
                    self.queue.assigned_pod_updated_or_added(new)
            elif self._responsible_for(new):
                self.queue.update(old, new)
        elif event == DELETED:
            if old is not None and old.spec.node_name:
                self._bump_external()
            if old is not None:
                self.smetrics.clear_unschedulable(old.key())
                # quota release first: the POD_DELETE reactivation wave
                # below must re-gate against the freed headroom
                self._notify_quota_pod_deleted(old)
            if old is not None and old.spec.node_name:
                self.cache.remove_pod(old)
                self.queue.move_all_to_active_or_backoff_queue(qevents.POD_DELETE)
            elif old is not None:
                self.queue.delete(old)
            if old is not None:
                self._notify_gang_pod_deleted(old)

    def _notify_gang_pod_deleted(self, pod: Pod) -> None:
        """PodGroup lifecycle on member deletion: the Coscheduling plugin's
        bound-count cache must decrement (and GC when the gang empties) or
        a re-created gang is judged against stale quorum."""
        from ..framework.plugins.coscheduling import pod_group_key

        if pod_group_key(pod) is None or not self._responsible_for(pod):
            return
        plugin = self.framework_for_pod(pod).plugin("Coscheduling")
        if plugin is not None:
            plugin.pod_deleted(pod)

    def _on_node_event(self, event: str, old: Optional[Node], new: Optional[Node]) -> None:
        self._bump_external()  # any node event invalidates the device carry
        if event == ADDED:
            self.smetrics.node_events.inc("add")
            self.cache.add_node(new)
            # targeted capacity wake-up: pods parked Unschedulable on
            # resource pressure (NodeResourcesFit registers NODE|ADD)
            # reactivate the moment new capacity joins the cluster
            self.queue.move_all_to_active_or_backoff_queue(qevents.NODE_ADD)
        elif event == MODIFIED:
            self.smetrics.node_events.inc("update")
            self.cache.update_node(new)
            ev = self._node_scheduling_properties_change(old, new)
            if ev is not None:
                self.queue.move_all_to_active_or_backoff_queue(ev)
        elif event == DELETED:
            self.smetrics.node_events.inc("delete")
            self.cache.remove_node(old.meta.name)

    @staticmethod
    def _node_scheduling_properties_change(old: Node, new: Node) -> Optional[ClusterEvent]:
        """eventhandlers.go:423: minimal ClusterEvent from a node diff."""
        if old is None:
            return qevents.NODE_ADD
        if old.status.allocatable != new.status.allocatable:
            return ClusterEvent(NODE, UPDATE_NODE_ALLOCATABLE, "NodeAllocatableChange")
        if old.meta.labels != new.meta.labels:
            return ClusterEvent(NODE, UPDATE_NODE_LABEL, "NodeLabelChange")
        if old.spec.taints != new.spec.taints or old.spec.unschedulable != new.spec.unschedulable:
            return ClusterEvent(NODE, UPDATE_NODE_TAINT, "NodeTaintChange")
        if old.status.ready != new.status.ready:
            return ClusterEvent(NODE, UPDATE_NODE_CONDITION, "NodeConditionChange")
        return None

    def _responsible_for(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name in self.profiles

    def framework_for_pod(self, pod: Pod) -> Framework:
        return self.profiles[pod.spec.scheduler_name]

    # -------------------------------------------------------- commit plane

    @property
    def commit_plane(self):
        """The batched commit engine (backend/commit_plane.py), built on
        first use — plain oracle schedulers never pay the backend import."""
        if self._commit_plane is None:
            from ..backend.commit_plane import CommitPlane

            self._commit_plane = CommitPlane(self)
        return self._commit_plane

    def _bump_external(self) -> None:
        """Record one external node-truth change (see _external_events)."""
        with self._ext_mu:
            self._external_events += 1

    def external_change_seq(self) -> int:
        """Monotonic count of external node-truth changes — the commit data
        plane's carry gate compares snapshots of this across a pipelined
        chain instead of walking cache generations."""
        return self._external_events  # ktpu: unguarded-ok(monotonic int probe; a racing bump reads as a changed seq on the NEXT gate check — conservative chain break, never a missed change)

    # ----------------------------------------------------------- the cycle

    def schedule_one(self) -> bool:
        """One scheduling cycle (schedule_one.go:66). Returns False when the
        active queue is empty."""
        if self.informer_factory is not None:
            self.informer_factory.pump()
        self._periodic_housekeeping()
        qp = self.queue.pop()
        if qp is None:
            return False
        pod = self.store.get_pod(qp.pod.key())
        if pod is None or pod.spec.node_name or not self._responsible_for(pod):
            # skipPodSchedule (:285): deleted/bound meanwhile — close the
            # ledger entry the pop just transitioned (no-op when absent)
            latency_ledger.close_skipped(qp.pod.key(), pod)
            return True
        qp.pod = pod
        self.schedule_one_pod(qp, self.queue.scheduling_cycle)
        return True

    def schedule_one_pod(self, qp: QueuedPodInfo, pod_cycle: int) -> None:
        """Sequential scheduling of one pod: schedule_pod + failure handling +
        assume/bind tail. Shared by schedule_one and the batch fallback path."""
        pod = qp.pod
        fwk = self.framework_for_pod(pod)
        self.metrics.inc("schedule_attempts")
        state = self._new_cycle_state()
        t0 = self.now_fn()
        try:
            node_name = self.schedule_pod(fwk, state, pod, attempts=qp.attempts)
        except FitError as fe:
            self.smetrics.observe_attempt("unschedulable", fwk.profile_name, self.now_fn() - t0)
            self._handle_scheduling_failure(fwk, state, qp, Status.unschedulable(*fe.args), fe.diagnosis, pod_cycle)
            return
        except Exception as e:  # noqa: BLE001 — cycle errors re-enqueue the pod
            self.metrics.inc("errors")
            self.smetrics.observe_attempt("error", fwk.profile_name, self.now_fn() - t0)
            self._handle_scheduling_failure(fwk, state, qp, Status.error(str(e)), Diagnosis(), pod_cycle)
            return
        self.smetrics.scheduling_algorithm_duration.observe(self.now_fn() - t0, fwk.profile_name)
        self.assume_and_bind(fwk, state, qp, pod, node_name, pod_cycle, t0=t0)

    # plugin-metrics sampling period: the reference samples ~10% of attempts;
    # a sampled attempt pays per-(node, plugin) filter observes, which in
    # Python is a bigger relative cost than in Go, so the default is 1-in-20
    PLUGIN_METRICS_SAMPLE_PERIOD = 20

    def _new_cycle_state(self) -> CycleState:
        """CycleState with the plugin-metrics sampling decision made
        (extension-point totals are always recorded; per-plugin durations
        only on sampled cycles). Attempt 1 always samples, so short runs
        still surface per-plugin samples."""
        state = CycleState()
        # (attempts - 1) % period: attempt 1 always samples, and period=1
        # degrades to sample-everything instead of sample-nothing
        state.record_plugin_metrics = (
            (self.metrics["schedule_attempts"] - 1)
            % self.PLUGIN_METRICS_SAMPLE_PERIOD == 0)
        return state

    def assume_and_bind(self, fwk: Framework, state: CycleState, qp: QueuedPodInfo, pod: Pod, node_name: str, pod_cycle: int, t0: Optional[float] = None) -> None:
        """The post-decision tail shared by the sequential and TPU-batched
        paths: assume → Reserve → Permit → binding cycle."""
        if t0 is None:
            t0 = self.now_fn()
        # assume (schedule_one.go:734): next cycle sees this pod immediately;
        # the clone (with node_name set by assume_pod) is what every later
        # extension point receives, like the reference's assumedPod
        assumed = pod.clone()
        self.cache.assume_pod(assumed, node_name)
        fwk.nominator.delete_nominated_pod_if_exists(pod)

        status = fwk.run_reserve_plugins_reserve(state, assumed, node_name)
        if status.is_success():
            status = fwk.run_permit_plugins(state, assumed, node_name)
        if status.code == fw.WAIT:
            # park: stays assumed; binding resumes on allow_waiting_pod
            # (runtime/waiting_pods_map.go; WaitOnPermit schedule_one.go:199).
            # The WAIT plugin's timeout (clock-injected via now_fn) bounds
            # the park: the housekeeping sweep rejects expired waiters.
            from ..framework.runtime import DEFAULT_PERMIT_WAIT_S, PERMIT_TIMEOUT_KEY

            try:
                timeout = float(state.read(PERMIT_TIMEOUT_KEY))
            except KeyError:
                timeout = DEFAULT_PERMIT_WAIT_S
            self.waiting_pods[assumed.key()] = WaitingPod(
                fwk, state, assumed, node_name, pod_cycle, t0,
                deadline=self.now_fn() + timeout, plugin=status.plugin)
            latency_ledger.transition(assumed.key(), "gang.permit_park",
                                      namespace=assumed.meta.namespace,
                                      create=False)
            return
        if not status.is_success():
            fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
            self.cache.forget_pod(assumed)
            self._handle_scheduling_failure(fwk, state, qp, status, Diagnosis(), pod_cycle)
            return

        self._binding_cycle(fwk, state, qp, assumed, node_name, pod_cycle, t0)

    def allow_waiting_pod(self, pod_key: str) -> bool:
        """Approve a Permit-parked pod: continue its binding cycle."""
        wp = self.waiting_pods.pop(pod_key, None)
        if wp is None:
            return False
        latency_ledger.transition(pod_key, "commit.host",
                                  namespace=wp.pod.meta.namespace,
                                  create=False)
        self._binding_cycle(wp.fwk, wp.state, QueuedPodInfo(pod=wp.pod),
                            wp.pod, wp.node_name, wp.pod_cycle, wp.t0)
        return True

    def reject_waiting_pod(self, pod_key: str,
                           reason: str = "pod rejected while waiting on permit",
                           plugins: Tuple[str, ...] = ()) -> bool:
        """Reject a parked pod: unreserve (which may cascade — a gang
        member's rejection tears down its siblings through Coscheduling's
        Unreserve), forget the assume, and requeue with the rejecting
        plugins attributed so event gating can reactivate it."""
        wp = self.waiting_pods.pop(pod_key, None)
        if wp is None:
            return False
        self._reject_depth += 1
        try:
            wp.fwk.run_reserve_plugins_unreserve(wp.state, wp.pod, wp.node_name)
            self.cache.forget_pod(wp.pod)
            diagnosis = Diagnosis(
                unschedulable_plugins=set(p for p in plugins if p))
            self._handle_scheduling_failure(
                wp.fwk, wp.state, QueuedPodInfo(pod=wp.pod),
                Status.unschedulable(reason), diagnosis, wp.pod_cycle)
            self.smetrics.observe_attempt(
                "unschedulable", wp.fwk.profile_name, self.now_fn() - wp.t0)
        finally:
            self._reject_depth -= 1
        # the forget released real capacity: pods parked on resource/port
        # fit can now succeed — the assumed pod's release is the moral
        # equivalent of an assigned-pod delete for queue gating. Fired once
        # per teardown, not per member: a whole-gang cascade (unreserve →
        # Coscheduling.reject_gang → nested rejects) re-enters this method,
        # and only the OUTERMOST frame pays the full-queue move.
        if self._reject_depth == 0:
            self.queue.move_all_to_active_or_backoff_queue(qevents.POD_DELETE)
        return True

    def _sweep_expired_waiting_pods(self, now: float) -> None:
        """WaitOnPermit timeout (waiting_pods_map.go per-pod timer, driven
        inline off the housekeeping tick): a parked pod past its deadline is
        rejected — for a gang member the WHOLE gang is torn down first so no
        partial gang survives the timeout."""
        expired = [(k, wp) for k, wp in self.waiting_pods.items()
                   if wp.deadline is not None and now >= wp.deadline]
        if not expired:
            return
        from ..framework.plugins.coscheduling import pod_group_key

        for key, wp in expired:
            if key not in self.waiting_pods:
                continue  # a gang cascade already rejected it
            gkey = pod_group_key(wp.pod)
            plugin = wp.fwk.plugin("Coscheduling") if gkey else None
            if gkey is not None and plugin is not None:
                plugin.reject_gang(gkey, "timeout")
            if key in self.waiting_pods:  # no cascade (bare framework)
                self.reject_waiting_pod(key, reason="permit wait timeout",
                                        plugins=(wp.plugin,))

    def _periodic_housekeeping(self, now: Optional[float] = None) -> None:
        """The reference's background tickers, driven inline: assume-expiry
        sweep (1s, cache.go:731) and the unschedulable-timeout flush (30s,
        scheduling_queue.go:463). ``now`` lets an override evaluate its own
        pre-sweep gates against the SAME clock read the sweep uses (a
        second read could cross the tick boundary the gate just tested)."""
        if now is None:
            now = self.now_fn()
        if now - self._last_cleanup >= 1.0:
            self._last_cleanup = now
            self._sweep_expired_waiting_pods(now)
            for pod in self.cache.cleanup(now):
                current = self.store.get_pod(pod.key())
                if current is not None and not current.spec.node_name:
                    self.queue.add(current)
            # cache-size + worker gauges ride the 1s sweep (the reference's
            # periodic updateSchedulerCacheSize / binding-goroutine gauges)
            nodes, pods, assumed = self.cache.stats()
            self.smetrics.sync_cache_gauges(nodes, pods, assumed)
            self.smetrics.goroutines.set("binding", value=len(self.waiting_pods))
            # WAL auto-compaction rides the 1s sweep: a durable store whose
            # log outgrew KTPU_WAL_COMPACT_LINES folds it into a snapshot
            # (no-op without an attached WAL or with the default-off gate)
            wal = getattr(self.store, "_wal", None)
            if wal is not None:
                wal.maybe_compact(self.store)
            # cohort quota reclaim-by-preemption rides the same sweep: a
            # lender whose pod parked on "cohort exhausted by loans" evicts
            # borrower pods newest-loan-first (cooldown + SLO breaker
            # paced inside the pass; no-op without recorded demand)
            quota = self._quota_plugin()
            if quota is not None:
                quota.run_reclaim(now)
        if now - self._last_unsched_flush >= 30.0:
            self._last_unsched_flush = now
            self.queue.flush_unschedulable_left_over()

    def _binding_cycle(self, fwk: Framework, state: CycleState, qp: QueuedPodInfo, assumed: Pod, node_name: str, pod_cycle: int, t0: Optional[float] = None) -> None:
        """(schedule_one.go:193) — synchronous here; the perf harness measures
        end-to-end anyway and the in-process store makes binds cheap."""
        latency_ledger.transition(assumed.key(), "bind",
                                  namespace=assumed.meta.namespace,
                                  create=False)
        status = fwk.run_pre_bind_plugins(state, assumed, node_name)
        if status.is_success():
            status = self._extenders_binding(assumed, node_name)
        if status is None:
            status = fwk.run_bind_plugins(state, assumed, node_name)
        if not status.is_success():
            fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
            self.cache.forget_pod(assumed)
            self._handle_scheduling_failure(fwk, state, qp, status, Diagnosis(), pod_cycle)
            return
        self.cache.finish_binding(assumed)
        self.metrics.inc("scheduled")
        self.smetrics.clear_unschedulable(assumed.key())
        latency_ledger.close(assumed.key(), "scheduled")
        self.smetrics.observe_attempt(
            "scheduled", fwk.profile_name,
            self.now_fn() - t0 if t0 is not None else 0.0,
        )
        self.recorder.eventf(
            assumed.key(), TYPE_NORMAL, "Scheduled", "Binding",
            f"Successfully assigned {assumed.key()} to {node_name}",
        )
        fwk.run_post_bind_plugins(state, assumed, node_name)

    def _extenders_binding(self, pod: Pod, node_name: str) -> Optional[Status]:
        """(schedule_one.go:774) first interested binder extender wins; None
        means no extender claimed the bind (fall through to bind plugins)."""
        for ext in self.extenders:
            if ext.is_binder() and ext.is_interested(pod):
                try:
                    ext.bind(pod, node_name)
                    return Status()
                except Exception as e:  # noqa: BLE001 — bind failure fails the cycle
                    return Status.error(f"extender bind: {e}")
        return None

    def schedule_pod(self, fwk: Framework, state: CycleState, pod: Pod,
                     attempts: int = 0) -> str:
        """(schedule_one.go:311) returns the chosen node name or raises FitError."""
        from ..utils import tracing

        with tracing.span("scheduling.cycle", pod=pod.key()):
            return self._schedule_pod_traced(fwk, state, pod, attempts)

    def _schedule_pod_traced(self, fwk: Framework, state: CycleState, pod: Pod,
                             attempts: int = 0) -> str:
        trace = Trace("Scheduling", now_fn=self.now_fn, pod=pod.key())
        self.cache.update_snapshot(self.snapshot)
        trace.step("Snapshotting scheduler cache and node infos done")
        all_nodes = self.snapshot.list()
        if not all_nodes:
            raise FitError(pod, 0, Diagnosis())

        feasible, diagnosis = self.find_nodes_that_fit_pod(fwk, state, pod, all_nodes)
        trace.step("Computing predicates done")
        if not feasible:
            trace.log_if_long(self.trace_threshold_s)
            raise FitError(pod, len(all_nodes), diagnosis)
        if len(feasible) == 1:
            trace.log_if_long(self.trace_threshold_s)
            return feasible[0].node.meta.name

        fwk.run_pre_score_plugins(state, pod, [ni.node for ni in feasible])
        totals = fwk.run_score_plugins(state, pod, feasible)
        trace.step("Prioritizing done")
        trace.log_if_long(self.trace_threshold_s)
        if self.extenders:
            # prioritizeNodes (:662-691): extender scores are raw·weight added
            # onto the plugin totals (extender max is 10, not 100)
            nodes = [ni.node for ni in feasible]
            for ext in self.extenders:
                if not ext.is_interested(pod):
                    continue
                try:
                    prios = ext.prioritize(pod, nodes)
                except Exception:  # noqa: BLE001 — prioritize errors are ignored (:673)
                    continue
                for name, score in prios.items():
                    if name in totals:
                        totals[name] += score * ext.weight()
        return self._select_host(totals, pod=pod, attempts=attempts)

    def find_nodes_that_fit_pod(self, fwk: Framework, state: CycleState, pod: Pod, all_nodes) -> Tuple[List, Diagnosis]:
        """(schedule_one.go:364) PreFilter → (restricted) node list → filters
        with adaptive sampling + round-robin start (:449-:545).

        The "filter" EXTENSION-POINT duration is observed here, once per
        attempt over the node walk only (the reference observes Filter at
        this level, schedule_one.go:373 defer — per-node observation would
        put a histogram write on every node visit). The clock starts AFTER
        PreFilter, which already has its own extension-point histogram;
        timing it into "filter" too would double-count PreFilter-heavy
        plugins and misattribute their latency."""
        diagnosis = Diagnosis()
        result, status = fwk.run_pre_filter_plugins(state, pod)
        if not status.is_success():
            if status.is_unschedulable():
                diagnosis.unschedulable_plugins.add(status.plugin)
                for ni in all_nodes:
                    diagnosis.node_to_status[ni.node.meta.name] = status
                raise FitError(pod, len(all_nodes), diagnosis)
            raise RuntimeError(f"prefilter error: {status}")

        nodes = all_nodes
        if result is not None and not result.all_nodes():
            nodes = [ni for ni in all_nodes if ni.node.meta.name in result.node_names]

        t_filter = time.perf_counter()
        filter_status = "Error"  # overwritten unless an exception escapes
        try:
            # nominated-node fast path (schedule_one.go:394-403): a pod that
            # preempted evaluates its nominated node first and schedules
            # there when feasible — without it, adaptive sampling usually
            # misses the node the victims were evicted from
            if pod.status.nominated_node_name:
                ni = next((n for n in nodes
                           if n.node.meta.name == pod.status.nominated_node_name), None)
                if ni is not None:
                    st = fwk.run_filter_plugins_with_nominated_pods(state, pod, ni)
                    if st.is_success():
                        filter_status = "Success"
                        return [ni], diagnosis

            num_to_find = self.num_feasible_nodes_to_find(len(nodes))
            feasible = []
            checked = 0
            start = self.next_start_node_index % len(nodes) if nodes else 0
            for i in range(len(nodes)):
                ni = nodes[(start + i) % len(nodes)]
                checked += 1
                st = fwk.run_filter_plugins_with_nominated_pods(state, pod, ni)
                if st.is_success():
                    feasible.append(ni)
                    if len(feasible) >= num_to_find:
                        break
                else:
                    diagnosis.node_to_status[ni.node.meta.name] = st
                    diagnosis.unschedulable_plugins.add(st.plugin)
            self.next_start_node_index = (start + checked) % len(nodes) if nodes else 0
            if feasible and self.extenders:
                feasible = self._find_nodes_that_pass_extenders(pod, feasible, diagnosis)
            filter_status = "Success" if feasible else "Unschedulable"
            return feasible, diagnosis
        finally:
            self.smetrics.framework_extension_point_duration.observe(
                time.perf_counter() - t_filter, "filter", filter_status,
                fwk.profile_name)

    def _find_nodes_that_pass_extenders(self, pod: Pod, feasible: List, diagnosis: Diagnosis) -> List:
        """(schedule_one.go:547) run each interested extender's Filter verb;
        ignorable extender failures drop the extender, not the cycle."""
        from .extender import ExtenderError

        by_name = {ni.node.meta.name: ni for ni in feasible}
        nodes = [ni.node for ni in feasible]
        for ext in self.extenders:
            if not nodes:
                break
            if not ext.is_interested(pod):
                continue
            try:
                nodes, failed, unresolvable = ext.filter(pod, nodes)
            except ExtenderError:
                if ext.is_ignorable():
                    continue
                raise
            for name, reason in failed.items():
                diagnosis.node_to_status[name] = Status.unschedulable(reason)
            for name, reason in unresolvable.items():
                # excluded from preemption candidates (preemption.go:363)
                diagnosis.node_to_status[name] = Status.unresolvable(reason)
        return [by_name[n.meta.name] for n in nodes]

    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        return num_feasible_nodes_to_find(num_all_nodes,
                                          self.percentage_of_nodes_to_score)

    def _select_host(self, totals: Dict[str, int], pod: Optional[Pod] = None,
                     attempts: int = 0) -> str:
        """(schedule_one.go:709) argmax + uniform tie-break. The reference's
        reservoir draw is unseeded; here the tie set is broken by the seeded
        per-(pod, attempt, node-name) hash the device batch program also uses
        (ops/tiebreak.py, SURVEY §8) — same uniform choice, but exactly
        replayable. Without a pod (legacy callers), falls back to the
        seeded-RNG reservoir."""
        best_score = None
        winner = None
        if pod is not None:
            from ..ops.tiebreak import name_hash, pod_seed, tie_key

            seed = pod_seed(pod.key(), attempts)
            best_key = -1
            for name, score in totals.items():
                if best_score is None or score > best_score:
                    best_score, winner = score, name
                    best_key = tie_key(seed, name_hash(name))
                elif score == best_score:
                    k = tie_key(seed, name_hash(name))
                    if k > best_key:
                        winner, best_key = name, k
            return winner
        cnt = 0
        for name, score in totals.items():
            if best_score is None or score > best_score:
                best_score, winner, cnt = score, name, 1
            elif score == best_score:
                cnt += 1
                if self.rng.random() < 1.0 / cnt:
                    winner = name
        return winner

    def _handle_scheduling_failure(self, fwk: Framework, state: CycleState, qp: QueuedPodInfo, status: Status, diagnosis: Diagnosis, pod_cycle: int) -> None:
        """(schedule_one.go:812 + scheduler.go:352 MakeDefaultErrorFunc):
        try PostFilter (preemption) on fit errors, then re-enqueue w/ backoff."""
        pod = qp.pod
        nominated_node = ""
        if status.is_unschedulable():
            self.metrics.inc("unschedulable")
            self.smetrics.mark_unschedulable(
                pod.key(), fwk.profile_name, diagnosis.unschedulable_plugins)
            if diagnosis.node_to_status and fwk.points.get("post_filter"):
                self.smetrics.preemption_attempts.inc()
                nominated, pf_status = fwk.run_post_filter_plugins(state, pod, diagnosis.node_to_status)
                if pf_status.is_success() and nominated:
                    nominated_node = nominated
            self.recorder.eventf(
                pod.key(), TYPE_WARNING, "FailedScheduling", "Scheduling",
                "; ".join(status.reasons) or "unschedulable",
            )
        if nominated_node:
            fwk.nominator.add_nominated_pod(pod, nominated_node)
            try:
                self.store.update_pod_nominated_node(pod.key(), nominated_node)
            except Exception:  # noqa: BLE001 — pod vanished; drop nomination
                fwk.nominator.delete_nominated_pod_if_exists(pod)
        # re-check existence/binding before re-queueing (MakeDefaultErrorFunc)
        current = self.store.get_pod(pod.key())
        if current is None or current.spec.node_name:
            self.smetrics.clear_unschedulable(pod.key())  # gone or bound
            # gone (deleted mid-cycle) or bound by an external binder:
            # either way the entry must not linger until the cap evicts it
            latency_ledger.close_skipped(pod.key(), current)
            return
        qp.pod = current
        qp.unschedulable_plugins = set(diagnosis.unschedulable_plugins)
        # error-status pods (device batch failure, bind error) take the
        # rate-limited backoff requeue — no plugin failed, so no ClusterEvent
        # would ever wake them from the unschedulable map
        self.queue.add_unschedulable_if_not_present(
            qp, pod_cycle, error=not status.is_unschedulable())

    # ----------------------------------------------------------- driving

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True) -> int:
        """Drive schedule_one until the active queue drains (test/perf helper;
        the reference's sched.Run loop is wait.Until on scheduleOne)."""
        cycles = 0
        while cycles < max_cycles:
            if not self.schedule_one():
                if flush:
                    self.queue.flush_backoff_completed()
                    if self.queue.pending_pods()["active"] > 0:
                        continue
                break
            cycles += 1
        return cycles

    def run_batched_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                                  idle_wait: float = 0.005,
                                  max_no_progress: int = 200) -> int:
        """Shared settle loop for batched frontends (TPUScheduler,
        WireScheduler): drive ``schedule_batch_cycle`` until the queue
        settles, with a bounded no-progress spin (a pod flapping between
        queues cannot turn this into a hot loop) and ``settle_abandoned``
        surfaced for harness consumers."""
        import time as _time

        cycles = 0
        no_progress = 0
        self.settle_abandoned = False
        while cycles < max_cycles:
            before_sched = self.metrics["scheduled"]
            before_pending = self.queue.pending_pods()
            before_unsched = (before_pending["unschedulable"]
                              + before_pending.get("gated", 0))
            n = self.schedule_batch_cycle()
            if n == 0:
                if flush:
                    self.queue.flush_backoff_completed()
                    if self.queue.pending_pods()["active"] > 0:
                        no_progress += 1
                        if no_progress > max_no_progress:
                            self._abandon_settle()
                            break
                        continue
                break
            cycles += n
            pending = self.queue.pending_pods()
            # Progress = placements OR pods newly parked unschedulable (they
            # stay parked until an external event; failure-draining a batch
            # IS progress toward settling). Only cycles that neither place
            # nor park — a pod flapping straight back into activeQ — pay the
            # wait and count toward the bound.
            if (self.metrics["scheduled"] > before_sched
                    or pending["unschedulable"] + pending.get("gated", 0)
                    > before_unsched):
                no_progress = 0
            else:
                no_progress += 1
                if no_progress > max_no_progress:
                    self._abandon_settle()
                    break
                _time.sleep(idle_wait * min(no_progress, 10))
        return cycles

    def _abandon_settle(self) -> None:
        """Mark and log a no-progress early exit so callers (perf Runner,
        bench) can tell a settled queue from an abandoned one instead of
        silently reporting numbers over a partial workload."""
        import logging

        self.settle_abandoned = True
        self.metrics["settle_abandoned"] = self.metrics.get("settle_abandoned", 0) + 1
        logging.getLogger(__name__).warning(
            "run_until_settled: no progress after bound; %s pods still pending",
            self.queue.pending_pods())


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int = 0) -> int:
    """Adaptive sampling (:525): 100% under 100 nodes; else
    percentageOfNodesToScore or adaptive 50 − N/125, floored at 5%. Shared
    by the sequential, batched, and wire-service paths."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    pct = percentage
    if pct == 0:
        pct = int(50 - num_all_nodes / 125)
        if pct < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            pct = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num = num_all_nodes * pct // 100
    if num < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num
