"""Pod-lifetime latency ledger: per-segment end-to-end attribution.

``scheduler_scheduling_attempt_duration_seconds`` times one *attempt*; a
pod that bounces through backoffQ, a quota gate, a gang Permit park, DRR
contention, and two ring-poison requeues is invisible end to end. This
module keeps ONE entry per pod UID, opened at the pod's first queue entry
and closed at bind (or terminal delete), accumulating named wall-clock
segments across every attempt:

  queue.active        activeQ dwell (default bucket / uncontended tenant)
  queue.drr_wait      activeQ dwell inside a CONTENDED tenant bucket (the
                      deficit-round-robin rotation is serving other tenants)
  queue.backoff       backoffQ dwell (error requeues, ring/wire poison,
                      move-raced failures)
  queue.unschedulable unschedulable-map park (waiting on a ClusterEvent)
  queue.gated         PreEnqueue park (QuotaAdmission refusing admission)
  cycle.host          pop -> dispatch/decision host work (PreFilter ->
                      Reserve on the oracle path; pop -> device dispatch on
                      the batched paths)
  gang.permit_park    Permit WAIT park (Coscheduling quorum, any WAIT vote)
  device.inflight     dispatched-batch dwell on the device / wire pipeline
                      (batchId-correlated with the flight recorder)
  commit.host         claim -> bind-tail host work (assume/reserve/permit/
                      pre-bind of the commit data plane)
  bind                the store bind transaction through finish

The segment state machine is gap-free by construction — ``transition``
closes the current segment and opens the next at the same clock read — so
``e2e == sum(segments)`` up to float rounding, which the tier-1 tests pin.

On close the ledger observes ``scheduler_pod_e2e_duration_seconds{result}``
and ``scheduler_pod_latency_segment_seconds{segment}``, plus the per-tenant
``scheduler_tenant_e2e_duration_seconds{namespace}`` SLO histogram — the
namespace label is BOUNDED through the quota tenant index (``tenant_fn``):
only namespaces holding a SchedulingQuota weight are labeled, so an
unbounded namespace population cannot explode the registry.

Disabled contract (the PR-2/PR-7 rule): the module recorder is ``None`` by
default and every hook returns after ONE module-global read. Enablement is
explicit — bench/perf harness, ``KTPU_LEDGER=1`` at server setup — and
changes no scheduling decision (placement parity pinned in tests).

Bounded: ``cap`` live entries (oldest evicted, counted on
``scheduler_pod_ledger_evicted_total``), a fixed tail of closed entries for
the /debug/timeline export, and a fixed per-entry interval history. Entries
drop on pod delete, so churn cannot leak.

Thread safety: one leaf lock (locktrace factory) around all state; hooks
are called under the queue lock, from the commit worker, and from the wire
pipeline's claim path — the ledger never takes another lock while holding
its own (metric observations, the eviction counter, and the arbitrary
``tenant_fn`` callback are all emitted AFTER the lock is released), so it
can join no lock-order cycle.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..testing import locktrace

# the declared segment registry: every segment observed on
# scheduler_pod_latency_segment_seconds comes from this set (README glossary)
SEGMENTS = frozenset({
    "queue.active",
    "queue.drr_wait",
    "queue.backoff",
    "queue.unschedulable",
    "queue.gated",
    "cycle.host",
    "gang.permit_park",
    "device.inflight",
    "commit.host",
    "bind",
})

DEFAULT_CAP = 16384          # live entries before oldest-evict
DEFAULT_KEEP_CLOSED = 512    # closed-entry tail kept for the timeline
DEFAULT_MAX_INTERVALS = 128  # per-entry interval history (timeline slices)

_ledger: Optional["PodLatencyLedger"] = None


class _Entry:
    __slots__ = ("key", "namespace", "opened", "seg", "seg_start", "acc",
                 "intervals", "batch_id", "closed", "result")

    def __init__(self, key: str, namespace: str, now: float,
                 max_intervals: int):
        self.key = key
        self.namespace = namespace
        self.opened = now
        self.seg: Optional[str] = None
        self.seg_start = now
        self.acc: Dict[str, float] = {}
        self.intervals: deque = deque(maxlen=max_intervals)
        self.batch_id: Optional[str] = None
        self.closed: Optional[float] = None
        self.result: Optional[str] = None


class PodLatencyLedger:
    """The process recorder: entry table + closed tail + metric feeds."""

    def __init__(self, metrics=None, cap: int = DEFAULT_CAP,
                 now_fn: Optional[Callable[[], float]] = None,
                 tenant_fn: Optional[Callable[[str], object]] = None,
                 keep_closed: int = DEFAULT_KEEP_CLOSED,
                 max_intervals: int = DEFAULT_MAX_INTERVALS):
        self.metrics = metrics
        self.cap = cap
        # wall clock by default so ledger intervals line up with span
        # start/end and flight-recorder timestamps on /debug/timeline;
        # tests inject a FakeClock for deterministic waits
        self.now_fn = now_fn or time.time
        # quota tenant index: ns -> weight (truthy = tenant). Bounds the
        # {namespace} label set of the tenant SLO histogram.
        self.tenant_fn = tenant_fn
        self._max_intervals = max_intervals
        self._lock = locktrace.make_lock("LatencyLedger")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._closed: deque = deque(maxlen=keep_closed)
        self.evicted = 0
        self.opened_total = 0
        self.closed_total = 0

    # ------------------------------------------------------------ internals

    def _entry_locked(self, key: str, namespace: str,
                      now: float) -> _Entry:  # ktpu: locked
        e = self._entries.get(key)
        if e is not None:
            return e
        while len(self._entries) >= self.cap:
            self._entries.popitem(last=False)
            self.evicted += 1  # metric emission happens after lock release
        e = _Entry(key, namespace, now, self._max_intervals)
        self._entries[key] = e
        self.opened_total += 1
        return e

    def _close_segment_locked(self, e: _Entry, now: float) -> None:  # ktpu: locked
        if e.seg is None:
            return
        dur = max(now - e.seg_start, 0.0)
        e.acc[e.seg] = e.acc.get(e.seg, 0.0) + dur
        e.intervals.append((e.seg, e.seg_start, now))

    # ------------------------------------------------------------------ API

    def transition(self, key: str, segment: str, namespace: str = "",
                   batch_id: Optional[str] = None,
                   create: bool = True) -> None:
        """Close the entry's current segment and open ``segment`` at one
        clock read (gap-free). ``create`` governs unknown keys: queue-entry
        hooks create (a pod's lifetime starts at first enqueue); post-queue
        hooks pass ``create=False`` so a pod deleted mid-flight (entry
        already dropped) is never resurrected as a ghost with a bogus
        near-zero e2e."""
        now = self.now_fn()
        with self._lock:
            if not create and key not in self._entries:
                return
            ev0 = self.evicted
            e = self._entry_locked(key, namespace, now)
            if namespace and not e.namespace:
                e.namespace = namespace
            self._close_segment_locked(e, now)
            e.seg = segment
            e.seg_start = now
            if batch_id is not None:
                e.batch_id = batch_id
            evicted = self.evicted - ev0
        self._report_evictions(evicted)

    def transition_many(self, keys: Iterable[str], segment: str,
                        batch_id: Optional[str] = None,
                        create: bool = False) -> None:
        """Batch-path twin: one clock read + one lock round trip for a
        whole dispatched/committed batch. Defaults to ``create=False`` —
        every batch-path segment is post-queue, so an unknown key means
        the pod's entry was dropped (deleted mid-flight) and must stay
        dropped."""
        now = self.now_fn()
        with self._lock:
            ev0 = self.evicted
            for key in keys:
                if not create and key not in self._entries:
                    continue
                e = self._entry_locked(key, "", now)
                self._close_segment_locked(e, now)
                e.seg = segment
                e.seg_start = now
                if batch_id is not None:
                    e.batch_id = batch_id
            evicted = self.evicted - ev0
        self._report_evictions(evicted)

    def _report_evictions(self, n: int) -> None:
        """Eviction-counter emission, outside the ledger lock (leaf-lock
        rule: this call's own evictions, counted under its lock hold)."""
        if n > 0 and self.metrics is not None:
            self.metrics.ledger_evicted.inc(value=float(n))

    def close(self, key: str, result: str = "scheduled") -> Optional[_Entry]:
        now = self.now_fn()
        with self._lock:
            e = self._close_locked(key, result, now)
        if e is not None:
            self._observe_closed(e)
        return e

    def close_many(self, keys: Iterable[str],
                   result: str = "scheduled") -> None:
        now = self.now_fn()
        with self._lock:
            closed = [e for e in (self._close_locked(k, result, now)
                                  for k in keys) if e is not None]
        for e in closed:
            self._observe_closed(e)

    def _close_locked(self, key: str, result: str,
                      now: float) -> Optional[_Entry]:  # ktpu: locked
        e = self._entries.pop(key, None)
        if e is None:
            return None
        self._close_segment_locked(e, now)
        e.seg = None
        e.closed = now
        e.result = result
        self.closed_total += 1
        self._closed.append(e)
        return e

    def _observe_closed(self, e: _Entry) -> None:
        """Metric emission for a just-closed entry — OUTSIDE the ledger
        lock, so it stays a true leaf: metric locks and the arbitrary
        ``tenant_fn`` callback are never entered with the ledger held
        (hooks already run under the queue lock; a tenant_fn reaching
        back into queue-locked state must not close a cycle here)."""
        m = self.metrics
        if m is None:
            return
        e2e = max(e.closed - e.opened, 0.0)
        m.pod_e2e_duration.observe(e2e, e.result)
        for seg, s in e.acc.items():
            m.pod_latency_segment.observe(s, seg)
        # tenant SLO: only quota tenants are labeled (bounded set), and
        # only real schedules count — a deleted pod's lifetime is not a
        # scheduling latency
        if (e.result == "scheduled" and e.namespace
                and self.tenant_fn is not None
                and self.tenant_fn(e.namespace)):
            m.tenant_e2e_duration.observe(e2e, e.namespace)

    def drop(self, key: str) -> Optional[_Entry]:
        """Terminal delete of an unbound pod: close with result="deleted"
        (the entry is removed either way — churn cannot leak)."""
        return self.close(key, result="deleted")

    # ------------------------------------------------------- introspection

    def entry(self, key: str) -> Optional[dict]:
        """Snapshot of one live or recently-closed entry (tests)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = next((c for c in reversed(self._closed)
                          if c.key == key), None)
            if e is None:
                return None
            return self._entry_view_locked(e)

    def _entry_view_locked(self, e: _Entry) -> dict:  # ktpu: locked
        return {
            "pod": e.key,
            "namespace": e.namespace,
            "opened": e.opened,
            "closed": e.closed,
            "result": e.result,
            "segment": e.seg,
            "batchId": e.batch_id,
            "segments": dict(e.acc),
            "intervals": list(e.intervals),
        }

    def timeline_entries(self, limit: Optional[int] = None) -> List[dict]:
        """The newest ``limit`` pods (closed tail first, then live), each
        with its interval history — the ledger half of /debug/timeline.
        Live entries' open segment is closed at 'now' for rendering only."""
        now = self.now_fn()
        with self._lock:
            pool = list(self._closed) + list(self._entries.values())
            if limit is not None and limit >= 0:
                pool = pool[-limit:] if limit else []
            out = []
            for e in pool:
                view = self._entry_view_locked(e)
                if e.closed is None and e.seg is not None:
                    view["intervals"] = view["intervals"] + [
                        (e.seg, e.seg_start, now)]
                out.append(view)
            return out

    def dump(self, limit: Optional[int] = None) -> dict:
        with self._lock:
            live = len(self._entries)
            opened, closed = self.opened_total, self.closed_total
            evicted = self.evicted
        return {
            "enabled": True,
            "cap": self.cap,
            "live": live,
            "opened": opened,
            "closed": closed,
            "evicted": evicted,
            "entries": self.timeline_entries(limit),
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ------------------------------------------------------------- timeline export

def chrome_trace(spans=(), flight=(), ledger: Optional[PodLatencyLedger] = None,
                 dispatch=(), limit: Optional[int] = None) -> dict:
    """One Chrome trace-event JSON document (loadable in Perfetto /
    chrome://tracing) unifying four telemetry layers on one time axis:

      pid 1  host/device spans (utils/tracing.py tail) — complete events,
             one track per trace so concurrent cycles don't interleave
      pid 2  flight-recorder events (backend/telemetry.py) — instants
             carrying batchId/client/epoch args
      pid 3  ledger pod segments — one track per pod, slices named by
             segment with pod UID + batchId args
      pid 4  device dispatch track (DispatchLedger records) — each batch's
             dwell/exec/fetch waterfall as back-to-back slices ending at
             the record's commit time, batchId/program-correlated with the
             pid 1/2 rows above it

    All timestamps are microseconds on the wall clock (spans record
    time.time_ns, the flight recorder, the ledger, and dispatch records
    time.time), so a pod's ``device.inflight`` slice visually brackets its
    batch's dispatch→commit events."""
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "host spans"}},
        {"ph": "M", "name": "process_name", "pid": 2, "tid": 0,
         "args": {"name": "flight recorder"}},
        {"ph": "M", "name": "process_name", "pid": 3, "tid": 0,
         "args": {"name": "pod latency ledger"}},
        {"ph": "M", "name": "process_name", "pid": 4, "tid": 0,
         "args": {"name": "device dispatch"}},
    ]
    trace_tids: Dict[str, int] = {}
    for s in spans:
        tid = trace_tids.setdefault(s.trace_id, len(trace_tids) + 1)
        args = {str(k): str(v) for k, v in s.attributes.items()}
        args["traceId"] = s.trace_id
        events.append({
            "name": s.name, "ph": "X", "pid": 1, "tid": tid,
            "ts": s.start / 1e3,
            "dur": max((s.end - s.start) / 1e3, 0.001),
            "cat": "span", "args": args,
        })
    for ev in flight:
        args = {str(k): v for k, v in ev.items()
                if k not in ("t", "type")}
        events.append({
            "name": ev.get("type", "?"), "ph": "i", "s": "p",
            "pid": 2, "tid": 1,
            "ts": float(ev.get("t", 0.0)) * 1e6,
            "cat": "flight", "args": args,
        })
    if ledger is not None:
        for i, view in enumerate(ledger.timeline_entries(limit), start=1):
            events.append({
                "ph": "M", "name": "thread_name", "pid": 3, "tid": i,
                "args": {"name": view["pod"]}})
            args = {"pod": view["pod"]}
            if view.get("batchId"):
                args["batchId"] = view["batchId"]
            if view.get("result"):
                args["result"] = view["result"]
            for seg, t0, t1 in view["intervals"]:
                events.append({
                    "name": seg, "ph": "X", "pid": 3, "tid": i,
                    "ts": t0 * 1e6,
                    "dur": max((t1 - t0) * 1e6, 0.001),
                    "cat": "ledger", "args": args,
                })
    for rec in dispatch:
        # the record's wall stamp is taken as the wait ends; the window
        # partition (dwell+exec+fetch == wait exactly) walks back from it
        end_us = float(rec.get("t", 0.0)) * 1e6
        win = rec.get("window") or {}
        args = {"program": rec.get("program", "?"),
                "bucket": rec.get("bucket", "-"),
                "batchId": rec.get("batchId", "")}
        for phase in ("fetch", "exec", "dwell"):
            dur_us = max(float(win.get(phase, 0.0)), 0.0) * 1e6
            events.append({
                "name": f"{args['program']}.{phase}", "ph": "X",
                "pid": 4, "tid": 1,
                "ts": end_us - dur_us, "dur": max(dur_us, 0.001),
                "cat": "dispatch", "args": args,
            })
            end_us -= dur_us
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------------- module API
#
# Every hook below starts with one read of the module global and returns
# immediately when the ledger is disabled — the same near-zero disabled
# cost contract as backend/telemetry.py, pinned by tests.

def enable(metrics=None, cap: int = DEFAULT_CAP,
           now_fn: Optional[Callable[[], float]] = None,
           tenant_fn: Optional[Callable[[str], object]] = None,
           keep_closed: int = DEFAULT_KEEP_CLOSED) -> PodLatencyLedger:
    """Install the process ledger (idempotent refresh)."""
    global _ledger
    _ledger = PodLatencyLedger(metrics, cap=cap, now_fn=now_fn,
                               tenant_fn=tenant_fn, keep_closed=keep_closed)
    return _ledger


def disable() -> None:
    global _ledger
    _ledger = None


def get() -> Optional[PodLatencyLedger]:
    return _ledger


def maybe_enable_from_env(metrics=None,
                          tenant_fn: Optional[Callable[[str], object]] = None
                          ) -> None:
    """KTPU_LEDGER=1 turns the ledger on at server setup (the KTPU_TELEMETRY
    twin); 0/unset leaves it off (the zero-cost default)."""
    if os.environ.get("KTPU_LEDGER") != "1":
        return
    if _ledger is None:
        enable(metrics, tenant_fn=tenant_fn)
    else:
        if metrics is not None and _ledger.metrics is None:
            _ledger.metrics = metrics
        if tenant_fn is not None and _ledger.tenant_fn is None:
            _ledger.tenant_fn = tenant_fn


def transition(key: str, segment: str, namespace: str = "",
               batch_id: Optional[str] = None, create: bool = True) -> None:
    led = _ledger
    if led is None:
        return
    led.transition(key, segment, namespace=namespace, batch_id=batch_id,
                   create=create)


def transition_many(keys, segment: str, batch_id: Optional[str] = None,
                    create: bool = False) -> None:
    led = _ledger
    if led is None:
        return
    led.transition_many(keys, segment, batch_id=batch_id, create=create)


def close(key: str, result: str = "scheduled") -> None:
    led = _ledger
    if led is None:
        return
    led.close(key, result=result)


def close_many(keys, result: str = "scheduled") -> None:
    led = _ledger
    if led is None:
        return
    led.close_many(keys, result=result)


def drop(key: str) -> None:
    led = _ledger
    if led is None:
        return
    led.drop(key)


def close_skipped(key: str, pod) -> None:
    """THE one result classification for a pod found gone-or-bound after
    its queue dwell (skipPodSchedule and the gone-or-bound failure exit,
    shared by the oracle, batched, and wire paths so their e2e result
    labels cannot drift): bound (by anyone) closes as "scheduled", absent
    closes as "deleted". No-op when the ledger is off or the key unknown."""
    led = _ledger
    if led is None:
        return
    led.close(key, "scheduled" if pod is not None and pod.spec.node_name
              else "deleted")
