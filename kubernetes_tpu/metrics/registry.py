"""Minimal Prometheus-style metrics registry.

Analog of staging/src/k8s.io/component-base/metrics (the legacyregistry
pattern): counters, gauges, histograms with label vectors, exposition in
Prometheus text format so a scheduler_perf-style metricsCollector can scrape
by metric name (test/integration/scheduler_perf/util.go:204-238).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]


def _escape_label_value(v: str) -> str:
    """Prometheus text-format escaping (exposition format spec: backslash,
    double-quote, and line feed must be escaped inside label values)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: LabelValues) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label_value(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, labels: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self._values: Dict[LabelValues, float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels: str, value: float = 1.0) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + value

    def labels(self, *labels: str) -> float:
        with self._lock:  # scrape-side read races the scheduling thread's inc
            return self._values.get(labels, 0.0)

    def label_sets(self) -> List[LabelValues]:
        with self._lock:
            return list(self._values)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # /metrics scrapes race the scheduling thread's inc
            for lv, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(self.label_names, lv)} {v}")
        return out

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(Counter):
    def set(self, *labels: str, value: float = 0.0) -> None:
        with self._lock:
            self._values[labels] = value

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            for lv, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(self.label_names, lv)} {v}")
        return out


# the scheduler's latency buckets: exponential 1ms..~17s (metrics.go)
def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * factor**i for i in range(count)]


DEFAULT_BUCKETS = exponential_buckets(0.001, 2, 15)


class Histogram:
    def __init__(self, name: str, help_: str, labels: Sequence[str] = (), buckets: Optional[List[float]] = None):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self.buckets = sorted(buckets or DEFAULT_BUCKETS)
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}
        # OpenMetrics exemplars: per (labelset, bucket) the LAST observed
        # (exemplar labels, value) — a slow p99 bucket links to a concrete
        # trace id. Bounded: one slot per bucket per labelset.
        self._exemplars: Dict[LabelValues, Dict[int, Tuple[dict, float]]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *labels: str,
                exemplar: Optional[dict] = None) -> None:
        """O(log buckets): counts are stored PER-BUCKET (non-cumulative) and
        cumulated on the read paths — observe sits on the scheduling hot
        path (extension-point timing per examined node), a linear cumulative
        write loop per sample was a measurable slice of the oracle cycle.

        ``exemplar``: optional {label: value} (e.g. trace/span id) attached
        to the bucket this observation lands in; exposed only in the
        OpenMetrics exposition (the 0.0.4 text format has no exemplars)."""
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * len(self.buckets)
                self._sums[labels] = 0.0
                self._totals[labels] = 0
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
                if exemplar:
                    self._exemplars.setdefault(labels, {})[i] = (
                        dict(exemplar), value)
            self._sums[labels] += value
            self._totals[labels] += 1

    def exemplar_for(self, bucket_index: int, *labels: str):
        """(exemplar labels, observed value) last landed in the bucket, or
        None — the scrape-side accessor tests and dashboards use."""
        with self._lock:
            return self._exemplars.get(labels, {}).get(bucket_index)

    def count(self, *labels: str) -> int:
        with self._lock:
            return self._totals.get(labels, 0)

    def label_sets(self) -> List[LabelValues]:
        """Every label-value combination observed so far (the scrape-side
        iteration surface for a metricsCollector)."""
        with self._lock:
            return list(self._totals)

    def sum(self, *labels: str) -> float:
        with self._lock:
            return self._sums.get(labels, 0.0)

    def percentile(self, q: float, *labels: str) -> float:
        """Linear-interpolated percentile from bucket counts (scrape-side
        estimate, like Prometheus histogram_quantile)."""
        with self._lock:
            counts = list(self._counts.get(labels, ()))
            total = self._totals.get(labels, 0)
        return self._interp(q, counts, total)

    def snapshot(self, *labels: str):
        """Opaque phase marker for ``percentile_since`` — lets a harness
        report percentiles over just a measured phase (scrape-side delta,
        like two Prometheus scrapes around the phase)."""
        with self._lock:
            return (list(self._counts.get(labels, ())), self._totals.get(labels, 0))

    def percentile_since(self, snap, q: float, *labels: str) -> float:
        prev_counts, prev_total = snap
        with self._lock:
            counts_now = list(self._counts.get(labels, ()))
            total_now = self._totals.get(labels, 0)
        if not counts_now:
            return 0.0
        if not prev_counts:
            prev_counts = [0] * len(counts_now)
        counts = [a - b for a, b in zip(counts_now, prev_counts)]
        return self._interp(q, counts, total_now - prev_total)

    def count_since(self, snap, *labels: str) -> int:
        with self._lock:
            return self._totals.get(labels, 0) - snap[1]

    def _interp(self, q: float, counts, total: int) -> float:
        """counts are per-bucket (non-cumulative); cumulate while scanning."""
        if total <= 0 or not counts:
            return 0.0
        target = q * total
        cum = 0
        for i, b in enumerate(self.buckets):
            below = cum
            cum += counts[i]
            if cum >= target:
                in_bucket = counts[i]
                if in_bucket == 0:
                    return b
                frac = (target - below) / in_bucket
                lo = self.buckets[i - 1] if i else 0.0
                return lo + frac * (b - lo)
        return self.buckets[-1]

    def collect(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        # under the lock: a scrape racing observe() could otherwise hit a
        # mid-insert dict or emit +Inf (from _totals) below the last finite
        # cumulative bucket — exactly the invariant the exposition test checks
        with self._lock:
            for lv in sorted(self._totals):
                exemplars = self._exemplars.get(lv, {}) if openmetrics else {}
                cum = 0
                for i, b in enumerate(self.buckets):  # exposition is cumulative
                    cum += self._counts[lv][i]
                    labels = _fmt_labels([*self.label_names, "le"], (*lv, repr(b)))
                    line = f"{self.name}_bucket{labels} {cum}"
                    ex = exemplars.get(i)
                    if ex is not None:
                        ex_labels, ex_value = ex
                        inner = ",".join(
                            f'{k}="{_escape_label_value(v)}"'
                            for k, v in ex_labels.items())
                        line += f" # {{{inner}}} {ex_value}"
                    out.append(line)
                labels = _fmt_labels([*self.label_names, "le"], (*lv, "+Inf"))
                out.append(f"{self.name}_bucket{labels} {self._totals[lv]}")
                out.append(f"{self.name}_sum{_fmt_labels(self.label_names, lv)} {self._sums[lv]}")
                out.append(f"{self.name}_count{_fmt_labels(self.label_names, lv)} {self._totals[lv]}")
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()
            self._exemplars.clear()


class Registry:
    """component-base/metrics legacyregistry analog."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                return self._metrics[metric.name]
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def expose(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition (the /metrics endpoint body). With
        ``openmetrics``, histogram bucket lines carry exemplars (`# {...} v`)
        and the body ends with the spec-required ``# EOF``; the default
        0.0.4 text format is byte-identical to before (exemplars are not
        legal there)."""
        lines: List[str] = []
        with self._lock:  # registration may race a scrape
            metrics = dict(self._metrics)
        for name in sorted(metrics):
            metric = metrics[name]
            if isinstance(metric, Histogram):
                lines.extend(metric.collect(openmetrics=openmetrics))
            else:  # counters/gauges have no exemplar surface
                lines.extend(metric.collect())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()
