"""Scheduler metric set with the reference's metric names and labels
(pkg/scheduler/metrics/metrics.go:42-176) so a scheduler_perf-style
metricsCollector scrapes identically (SURVEY.md §5.5 build mapping)."""

from __future__ import annotations

import threading
import time
from typing import Dict, FrozenSet, Iterable, Tuple

from .registry import Counter, Gauge, Histogram, Registry, exponential_buckets

SCHEDULER_SUBSYSTEM = "scheduler"

# result labels (metrics.go)
SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"


class SchedulerMetrics:
    def __init__(self, registry: Registry = None):
        self.registry = registry or Registry()
        r = self.registry
        self.schedule_attempts = r.register(Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by the result.",
            ["result", "profile"],
        ))
        self.scheduling_attempt_duration = r.register(Histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (algorithm + binding).",
            ["result", "profile"],
        ))
        self.scheduling_algorithm_duration = r.register(Histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency.",
            ["profile"],
        ))
        self.framework_extension_point_duration = r.register(Histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency per extension point.",
            ["extension_point", "status", "profile"],
        ))
        self.plugin_execution_duration = r.register(Histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Plugin execution latency (sampled).",
            ["plugin", "extension_point", "status"],
        ))
        self.pending_pods = r.register(Gauge(
            "scheduler_pending_pods",
            "Pending pods by queue (active|backoff|unschedulable|gated).",
            ["queue"],
        ))
        self.queue_incoming_pods = r.register(Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues by event and queue.",
            ["queue", "event"],
        ))
        self.preemption_attempts = r.register(Counter(
            "scheduler_preemption_attempts_total",
            "Total preemption attempts in the cluster.",
        ))
        self.preemption_victims = r.register(Histogram(
            "scheduler_preemption_victims",
            "Number of selected preemption victims.",
            buckets=[1, 2, 4, 8, 16, 32, 64],
        ))
        self.unschedulable_pods = r.register(Gauge(
            "scheduler_unschedulable_pods",
            "Unschedulable pods broken down by plugin.",
            ["plugin", "profile"],
        ))
        self.cache_size = r.register(Gauge(
            "scheduler_scheduler_cache_size",
            "Scheduler cache entries (nodes|pods|assumed_pods).",
            ["type"],
        ))
        self.goroutines = r.register(Gauge(
            "scheduler_goroutines",
            "Number of running goroutines split by work (device-step analog).",
            ["work"],
        ))
        # TPU-path extensions (new metrics, framework-specific)
        self.device_batch_duration = r.register(Histogram(
            "scheduler_tpu_batch_duration_seconds",
            "Device schedule_batch call latency.",
            ["phase"],  # upload|compute|commit
        ))
        self.device_batch_size = r.register(Histogram(
            "scheduler_tpu_batch_size",
            "Pods per device batch.",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512],
        ))
        # async commit pipeline (backend/tpu_scheduler.py in-flight ring):
        # current dispatched-but-uncommitted batch count, and cumulative
        # seconds the commit site spent blocked on device execution AFTER
        # the packed-block transfer was already staged at dispatch (the
        # residual stall the ring exists to hide — a growing rate here says
        # the ring is too shallow or the host fell behind)
        self.pipeline_inflight = r.register(Gauge(
            "scheduler_pipeline_inflight",
            "Dispatched device batches not yet committed (ring occupancy).",
        ))
        self.pipeline_stall_seconds = r.register(Counter(
            "scheduler_pipeline_stall_seconds_total",
            "Seconds the batch commit site blocked waiting on device results.",
        ))
        # resource.k8s.io (DRA): claim allocation outcomes at Reserve time
        # (allocated|conflict) and Unreserve rollbacks (released)
        self.dra_claim_allocations = r.register(Counter(
            "scheduler_dynamic_resources_claim_allocations_total",
            "ResourceClaim allocation outcomes by result.",
            ["result"],
        ))
        # gang scheduling (Coscheduling/PodGroup): gang-level rejection
        # events (timeout at Permit, a member's failure, a device batch that
        # could not place the whole gang) and how long a gang's first member
        # waits at Permit before the gang releases or is torn down
        self.gangs_rejected = r.register(Counter(
            "scheduler_gangs_rejected_total",
            "PodGroup gang rejection events by reason.",
            ["reason"],
        ))
        self.gang_wait_duration = r.register(Histogram(
            "scheduler_gang_wait_duration_seconds",
            "Gang wait at Permit from first parked member to release/rejection.",
            ["result"],  # scheduled|rejected
        ))
        # slice-topology packing (ops/slice.py): per-superpod fragmentation
        # (1 - largest free contiguous run / free nodes; 0 = one unbroken
        # run or nothing free) refreshed from the host mirror at each slice
        # commit, and how long a slice gang's pods waited from queue pop to
        # a contiguous torus placement landing
        self.slice_fragmentation = r.register(Gauge(
            "scheduler_slice_fragmentation",
            "Torus fragmentation score per superpod (0 contiguous, ->1 shredded).",
            ["superpod"],
        ))
        self.slice_wait_duration = r.register(Histogram(
            "scheduler_slice_wait_duration_seconds",
            "Slice gang wait from batch pop to contiguous placement commit.",
            ["result"],  # scheduled|rejected
        ))
        # fault-tolerant wire path (backend/service.py): transport retries,
        # breaker state (0 closed, 1 half-open, 2 open), and cumulative time
        # spent scheduling through the sequential oracle because the device
        # service was unavailable
        self.wire_retries = r.register(Counter(
            "scheduler_wire_retries_total",
            "Device-service transport retries by operation.",
            ["op"],
        ))
        self.backend_circuit_state = r.register(Gauge(
            "scheduler_backend_circuit_state",
            "Device-service circuit breaker state (0 closed, 1 half-open, 2 open).",
        ))
        self.degraded_seconds = r.register(Counter(
            "scheduler_degraded_seconds_total",
            "Seconds spent in breaker-open degraded (oracle fallback) mode.",
        ))
        # active-active HA (per-client device-service sessions): live
        # session count as seen by the last heartbeat, peer-fence takeover
        # events this replica observed (and adopted after), and typed
        # commit conflicts (another replica owned the pod/capacity)
        self.client_sessions = r.register(Gauge(
            "scheduler_client_sessions",
            "Live scheduler sessions on the shared device service.",
        ))
        self.ha_takeovers = r.register(Counter(
            "scheduler_ha_takeovers_total",
            "Peer scheduler sessions fenced and adopted by this replica.",
        ))
        self.commit_conflicts = r.register(Counter(
            "scheduler_commit_conflicts_total",
            "Ownership-check conflicts at device commit time.",
            ["client"],
        ))
        # device-side HA fabric (backend/fabric.py): which replica the
        # fabric currently routes to (index into the endpoint list),
        # primary failovers by trigger family, and per-endpoint replica
        # health (1 healthy / 0 down) as seen by calls + Health probes
        self.fabric_active_replica = r.register(Gauge(
            "scheduler_fabric_active_replica",
            "Index of the device-service replica the fabric routes to.",
        ))
        self.fabric_failovers = r.register(Counter(
            "scheduler_fabric_failovers_total",
            "Device-fabric primary failovers by triggering error family.",
            ["reason"],
        ))
        self.fabric_replica_health = r.register(Gauge(
            "scheduler_fabric_replica_health",
            "Device-service replica health by endpoint (1 up, 0 down).",
            ["endpoint"],
        ))
        # pipelined wire transport + warm-standby replication: wire batches
        # submitted but not yet processed (the wire ring's occupancy), how
        # many delta generations each standby's mirror lags the primary
        # stream, and the wire bytes the background replicator shipped to
        # standbys (full seeds vs incremental deltas) — the denominator of
        # the O(dirty)-resync-at-promote evidence
        self.wire_inflight = r.register(Gauge(
            "scheduler_wire_inflight",
            "Wire batches in flight on the pipelined transport.",
        ))
        self.standby_replication_lag = r.register(Gauge(
            "scheduler_standby_replication_lag",
            "Delta generations a standby replica lags the primary stream.",
            ["endpoint"],
        ))
        self.standby_resync_bytes = r.register(Counter(
            "scheduler_standby_resync_bytes_total",
            "Wire bytes shipped to standbys by the background replicator "
            "(full = seed/reseed, delta = incremental dirty set).",
            ["kind"],
        ))
        # device-runtime observability (backend/telemetry.py): XLA compile
        # ledger per (program, bucket signature) with retrace counts (a
        # compile beyond a program's first — the BatchSizer's bucket walk
        # shows up here when it recompiles mid-run), accelerator memory
        # stats, host<->device transfer volume, and flight-recorder event
        # counts by type
        self.xla_compilations = r.register(Counter(
            "scheduler_xla_compilations_total",
            "XLA backend compilations by program and bucket signature.",
            ["program", "bucket"],
        ))
        self.xla_compile_duration = r.register(Histogram(
            "scheduler_xla_compile_seconds",
            "XLA backend compile latency by program.",
            ["program"],
            buckets=exponential_buckets(0.01, 2, 14),
        ))
        self.xla_retraces = r.register(Counter(
            "scheduler_xla_retraces_total",
            "XLA compilations beyond a program's first (retraces).",
            ["program"],
        ))
        self.hbm_bytes = r.register(Gauge(
            "scheduler_device_hbm_bytes",
            "Device memory stats sample (in_use|peak|limit).",
            ["kind"],
        ))
        self.device_transfer_bytes = r.register(Counter(
            "scheduler_device_transfer_bytes_total",
            "Host<->device transfer volume (upload = row sync, fetch = "
            "packed result block).",
            ["direction"],
        ))
        self.flight_events = r.register(Counter(
            "scheduler_flight_recorder_events_total",
            "Batch flight-recorder events by type.",
            ["type"],
        ))
        # dispatch profiler (backend/telemetry.py DispatchLedger): the
        # commit-wait waterfall per program — dwell (submit→exec start,
        # inferred from the in-flight ring overlap), exec (device run
        # time), fetch (packed-block device→host transfer)
        self.device_dispatch_duration = r.register(Histogram(
            "scheduler_device_dispatch_seconds",
            "Per-dispatch device-time decomposition by program and phase "
            "(dwell|exec|fetch).",
            ["program", "phase"],
            buckets=exponential_buckets(0.0002, 2, 16),
        ))
        # multi-tenant admission (SchedulingQuota + QuotaAdmission plugin):
        # the scheduler-side ledger per (namespace, dimension), admission
        # decisions at the gate/Reserve, gated pods woken by targeted
        # quota-release moves, and the fair-share dequeuer's tenant turns
        # (the denominator of the quota-weighted fairness bound)
        self.quota_usage = r.register(Gauge(
            "scheduler_quota_usage",
            "Scheduler-side quota ledger usage by namespace and dimension.",
            ["namespace", "resource"],
        ))
        self.quota_decisions = r.register(Counter(
            "scheduler_quota_admission_decisions_total",
            "Pod-level quota admission outcomes by namespace "
            "(admitted at Reserve charge; rejected once per over-quota "
            "episode, not per re-check).",
            ["namespace", "result"],
        ))
        self.quota_released_pods = r.register(Counter(
            "scheduler_quota_released_pods_total",
            "Gated pods re-admitted by targeted quota-release queue moves.",
            ["namespace"],
        ))
        # cohort borrowing: the loaned portion of the ledger (subset of
        # scheduler_quota_usage) and reclaim-by-preemption pass outcomes
        # (evicted / noop / suspended-by-breaker)
        self.quota_borrowed = r.register(Gauge(
            "scheduler_quota_borrowed",
            "Ledger usage charged against cohort headroom (loans) by "
            "namespace and dimension.",
            ["namespace", "resource"],
        ))
        self.quota_reclaims = r.register(Counter(
            "scheduler_quota_reclaims_total",
            "Cohort reclaim-by-preemption pass outcomes.",
            ["result"],
        ))
        self.fair_share_turns = r.register(Counter(
            "scheduler_fair_share_turns_total",
            "Deficit-round-robin dequeue turns served per tenant namespace.",
            ["namespace"],
        ))
        # elastic clusters (node churn / drain / spot reclamation): informer
        # node-event volume by action, evictions by machinery (drain wave,
        # spot NoExecute storm, taint manager), and device row-slot reuse
        # (the free-list keeping DeviceState capacity bounded under churn)
        self.node_events = r.register(Counter(
            "scheduler_node_events_total",
            "Node informer events observed by the scheduler, by action.",
            ["action"],
        ))
        self.evicted_pods = r.register(Counter(
            "scheduler_evicted_pods_total",
            "Pods evicted by the elasticity machinery, by reason "
            "(drain|spot|taint).",
            ["reason"],
        ))
        self.device_slot_reuse = r.register(Counter(
            "scheduler_device_slot_reuse_total",
            "Tombstoned DeviceState row slots handed to new nodes "
            "(bounded-capacity churn instead of monotonic growth).",
        ))
        # commit data plane (backend/commit_plane.py): per-batch commit
        # engine stage latencies (assume → bind → finish → notify →
        # post_bind, plus the whole-batch total) and the notification /
        # WAL / queue-move deliveries coalesced into per-batch operations
        # instead of per-pod ones
        self.commit_batch_duration = r.register(Histogram(
            "scheduler_commit_batch_duration_seconds",
            "Batched commit engine latency by stage.",
            ["stage"],
        ))
        self.commit_coalesced_events = r.register(Counter(
            "scheduler_commit_coalesced_events_total",
            "Per-pod deliveries coalesced into batched commit operations "
            "(queue_move|wal_record|cache_op|post_bind).",
            ["kind"],
        ))
        # pod-lifetime latency ledger (metrics/latency_ledger.py): one entry
        # per pod from first enqueue to bind (or terminal delete), spanning
        # every attempt — the end-to-end complement of the per-attempt
        # histogram. Segments are the named wall-clock slices of that
        # lifetime (queue.active/backoff/unschedulable/gated/drr_wait,
        # cycle.host, gang.permit_park, device.inflight, commit.host, bind);
        # the tenant histogram is the per-namespace SLO feed, its label set
        # bounded by the quota tenant index. Buckets reach ~160s: a pod can
        # legitimately dwell minutes across backoff/gate parks.
        _e2e_buckets = exponential_buckets(0.005, 2, 16)
        self.pod_e2e_duration = r.register(Histogram(
            "scheduler_pod_e2e_duration_seconds",
            "Pod end-to-end latency from first enqueue to bind (or terminal "
            "delete), across all attempts.",
            ["result"],
            buckets=_e2e_buckets,
        ))
        self.pod_latency_segment = r.register(Histogram(
            "scheduler_pod_latency_segment_seconds",
            "Per-pod lifetime wall-clock attribution by named segment "
            "(observed once per segment at pod close).",
            ["segment"],
            buckets=_e2e_buckets,
        ))
        self.tenant_e2e_duration = r.register(Histogram(
            "scheduler_tenant_e2e_duration_seconds",
            "Pod end-to-end latency per tenant namespace (quota tenants "
            "only — the fair-share SLO feed).",
            ["namespace"],
            buckets=_e2e_buckets,
        ))
        self.ledger_evicted = r.register(Counter(
            "scheduler_pod_ledger_evicted_total",
            "Latency-ledger entries evicted at the live-entry cap (oldest "
            "first; nonzero means e2e attribution lost pods).",
        ))

        # continuous rebalancing (controllers/rebalance.py): the background
        # descheduler's control-loop evidence — executed/empty/suspended
        # wave outcomes, total pods migrated, the packing-entropy score the
        # trigger band watches (1.0 = load smeared evenly over every node,
        # ->0 = consolidated), and whether the SLO guardrail breaker
        # currently has rebalancing suspended (0/1).
        self.rebalance_waves = r.register(Counter(
            "scheduler_rebalance_waves_total",
            "Rebalance wave attempts by outcome (executed / empty / "
            "suspended).",
            ["result"],
        ))
        self.rebalance_migrations = r.register(Counter(
            "scheduler_rebalance_migrations_total",
            "Pods evicted by rebalance migration waves (each re-binds via "
            "the normal requeue path).",
        ))
        self.packing_entropy = r.register(Gauge(
            "scheduler_packing_entropy",
            "Mean normalized bin-packing entropy over live resource axes "
            "(the rebalance trigger's score; lower is better packed).",
        ))
        self.rebalance_suspended = r.register(Gauge(
            "scheduler_rebalance_suspended",
            "1 while the tenant-SLO guardrail breaker holds rebalancing "
            "suspended, else 0.",
        ))

        # unschedulable_pods bookkeeping: gauge value = number of pods
        # CURRENTLY unschedulable attributed to each (plugin, profile); a
        # pod's attribution is replaced on every failed attempt and removed
        # when it schedules or is deleted (the reference decrements via
        # its pending-pods recorder; a bare set(1) never comes back down)
        self._unsched_lock = threading.Lock()
        self._unsched_pods: Dict[str, Tuple[str, FrozenSet[str]]] = {}
        self._unsched_counts: Dict[Tuple[str, str], int] = {}

    def observe_attempt(self, result: str, profile: str, duration_s: float) -> None:
        self.schedule_attempts.inc(result, profile)
        self.scheduling_attempt_duration.observe(duration_s, result, profile)

    def mark_unschedulable(self, pod_key: str, profile: str,
                           plugins: Iterable[str]) -> None:
        """Attribute ``pod_key``'s unschedulability to ``plugins``,
        replacing any previous attribution for the pod."""
        with self._unsched_lock:
            self._clear_unschedulable_locked(pod_key)
            ps = frozenset(p for p in plugins if p)
            if not ps:
                return
            self._unsched_pods[pod_key] = (profile, ps)
            for p in ps:
                k = (p, profile)
                n = self._unsched_counts.get(k, 0) + 1
                self._unsched_counts[k] = n
                self.unschedulable_pods.set(p, profile, value=n)

    def clear_unschedulable(self, pod_key: str) -> None:
        """Drop the pod's attribution (it scheduled, was deleted, or was
        bound by someone else)."""
        with self._unsched_lock:
            self._clear_unschedulable_locked(pod_key)

    def _clear_unschedulable_locked(self, pod_key: str) -> None:  # ktpu: locked
        prev = self._unsched_pods.pop(pod_key, None)
        if prev is None:
            return
        profile, ps = prev
        for p in ps:
            k = (p, profile)
            n = max(self._unsched_counts.get(k, 0) - 1, 0)
            self._unsched_counts[k] = n
            self.unschedulable_pods.set(p, profile, value=n)

    def sync_queue_gauges(self, pending: dict) -> None:
        for q, n in pending.items():
            self.pending_pods.set(q, value=n)

    def sync_cache_gauges(self, nodes: int, pods: int, assumed: int) -> None:
        self.cache_size.set("nodes", value=nodes)
        self.cache_size.set("pods", value=pods)
        self.cache_size.set("assumed_pods", value=assumed)


_global = None


def global_metrics() -> SchedulerMetrics:
    """legacyregistry analog: one process-wide metric set."""
    global _global
    if _global is None:
        _global = SchedulerMetrics()
    return _global
