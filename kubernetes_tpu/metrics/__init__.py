"""Metrics: prometheus-style registry + the scheduler metric set."""

from .registry import Counter, Gauge, Histogram, Registry, exponential_buckets
from .scheduler_metrics import SchedulerMetrics, global_metrics

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "exponential_buckets",
    "SchedulerMetrics",
    "global_metrics",
]
