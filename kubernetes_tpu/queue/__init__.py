from .scheduling_queue import SchedulingQueue  # noqa: F401
