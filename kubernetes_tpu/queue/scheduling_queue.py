"""Three-part scheduling queue (internal/queue/scheduling_queue.go).

activeQ        heap ordered by the profile's QueueSort (priority desc, FIFO)
podBackoffQ    heap ordered by backoff expiry (1s → 10s doubling, :766)
unschedulable  map of pods that failed, waiting for a relevant ClusterEvent

Event-driven reactivation (``move_all_to_active_or_backoff``) is gated on the
cluster-event map: a pod moves only if some plugin it failed on registered
interest in the fired event (:614,:627), or on the wildcard flush.  The
``move_request_cycle`` guard (:163-167) keeps pods that failed *during* an
in-flight cycle eligible for the move that raced with them.

Flush tickers (:432,:463) become explicit ``flush_*`` calls driven by the
scheduler loop (no background goroutines; the loop is single-threaded and the
TPU batch path wants deterministic drain points anyway).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api.types import Pod
from ..framework.types import ClusterEvent, QueuedPodInfo

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_UNSCHEDULABLE_TIMEOUT = 300.0  # flushUnschedulablePodsLeftover, 5min

LessFn = Callable[[QueuedPodInfo], object]  # sort-key extractor


class SchedulingQueue:
    def __init__(
        self,
        less_key: Optional[LessFn] = None,
        initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        unschedulable_timeout: float = DEFAULT_UNSCHEDULABLE_TIMEOUT,
        cluster_event_map: Optional[Dict[ClusterEvent, Set[str]]] = None,
        now_fn=time.monotonic,
        metrics=None,
        gang_key_fn=None,
        gang_coactivation_interval: Optional[float] = None,
    ):
        # default QueueSort: priority desc then FIFO (PrioritySort)
        self.less_key = less_key or (lambda qp: (-qp.pod.spec.priority, qp.timestamp))
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.unschedulable_timeout = unschedulable_timeout
        self.cluster_event_map = cluster_event_map or {}
        # (event, failed-plugin-set) -> bool memo: a bind fires POD_ADD into
        # move_all for EVERY unschedulable pod; distinct plugin sets are few,
        # so the O(|event map|) scan runs once per (event, set), not per pod
        # per bind (was 3.2M ClusterEvent.match calls in the Unschedulable
        # workload's measured window)
        self._event_match_memo: Dict[tuple, bool] = {}
        self.now_fn = now_fn
        # SchedulerMetrics (or None): queue_incoming_pods counters on every
        # transition + pending_pods gauge sync (metrics.go:120-134; both were
        # registered-but-dead before the queue owned them)
        self._metrics = metrics

        # gang co-activation (Coscheduling): pod -> group key (or None).
        # When a member enters the active path its unschedulable siblings
        # move too, so a gang re-attempts TOGETHER instead of trickling in
        # one member per event and timing out at Permit. The per-gang
        # interval is the starvation guard: a flapping gang cannot spin the
        # queue faster than the backoff it would otherwise pay.
        self.gang_key_fn = gang_key_fn
        self._gang_co_interval = (gang_coactivation_interval
                                  if gang_coactivation_interval is not None
                                  else initial_backoff)
        self._gang_last_co: Dict[str, float] = {}

        self._counter = itertools.count()  # FIFO tie-break inside heaps
        self._active: List[Tuple[object, int, QueuedPodInfo]] = []
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._in_queue: Set[str] = set()  # keys in active/backoff heaps
        self.scheduling_cycle = 0
        self.move_request_cycle = -1

    # ------------------------------------------------------------- helpers

    def _backoff_duration(self, qp: QueuedPodInfo) -> float:
        """calculateBackoffDuration (:766): initial · 2^(attempts-1), capped."""
        d = self.initial_backoff
        for _ in range(1, qp.attempts):
            d *= 2
            if d >= self.max_backoff:
                return self.max_backoff
        return d

    def _push_active(self, qp: QueuedPodInfo, event: Optional[str] = None) -> None:
        key = qp.pod.key()
        if key in self._in_queue:
            return
        heapq.heappush(self._active, (self.less_key(qp), next(self._counter), qp))
        self._in_queue.add(key)
        self._record_incoming("active", event)

    def _push_backoff(self, qp: QueuedPodInfo, event: Optional[str] = None) -> None:
        key = qp.pod.key()
        if key in self._in_queue:
            return
        expiry = qp.timestamp + self._backoff_duration(qp)
        heapq.heappush(self._backoff, (expiry, next(self._counter), qp))
        self._in_queue.add(key)
        self._record_incoming("backoff", event)

    def _record_incoming(self, queue: str, event: Optional[str]) -> None:
        if self._metrics is not None and event is not None:
            self._metrics.queue_incoming_pods.inc(queue, event)

    def _sync_gauges(self) -> None:
        """pending_pods gauge ← the three sub-queue sizes (SchedulerQueue
        Incoming/Pending recorders; cheap enough to run per transition)."""
        if self._metrics is not None:
            self._metrics.sync_queue_gauges(self.pending_pods())

    # ------------------------------------------------------------- API

    def add(self, pod: Pod) -> None:
        """New unscheduled pod (informer add) → activeQ (:300). A gang
        member's arrival co-activates its parked siblings — the late 32nd
        pod of a gang must wake the 31 that failed PreFilter on it."""
        self._push_active(QueuedPodInfo(pod=pod, timestamp=self.now_fn()),
                          event="PodAdd")
        if self.gang_key_fn is not None:
            gkey = self.gang_key_fn(pod)
            if gkey is not None:
                self.activate_gang(gkey)
        self._sync_gauges()

    def update(self, old: Optional[Pod], new: Pod) -> None:
        """Pod update may make an unschedulable pod schedulable again (:525);
        a pod the queue has never seen falls through to activeQ (reference
        Update's final AddNewPod branch)."""
        key = new.key()
        if key in self._in_queue:
            return  # will be scheduled with fresh object at pop time via store
        qp = self._unschedulable.pop(key, None)
        if qp is not None:
            qp.pod = new
            self._push_backoff(qp, event="PodUpdate")
            self._sync_gauges()
        else:
            self.add(new)

    def delete(self, pod: Pod) -> None:
        key = pod.key()
        self._unschedulable.pop(key, None)
        if key in self._in_queue:
            self._in_queue.discard(key)
            self._active = [e for e in self._active if e[2].pod.key() != key]
            heapq.heapify(self._active)
            self._backoff = [e for e in self._backoff if e[2].pod.key() != key]
            heapq.heapify(self._backoff)
        self._sync_gauges()

    def pop(self) -> Optional[QueuedPodInfo]:
        """Next pod to schedule, or None (non-blocking; the reference blocks,
        :484 — the loop idles instead). Bumps attempts + scheduling_cycle."""
        qp = self._pop_unsynced()
        if qp is not None:
            self._sync_gauges()
        return qp

    def _pop_unsynced(self) -> Optional[QueuedPodInfo]:
        self.flush_backoff_completed()
        if not self._active:
            return None
        _, _, qp = heapq.heappop(self._active)
        self._in_queue.discard(qp.pod.key())
        qp.attempts += 1
        self.scheduling_cycle += 1
        return qp

    def pop_batch(self, k: int) -> List[QueuedPodInfo]:
        """Drain up to k pods in queue order — the TPU micro-batch feed.
        The pending gauge syncs ONCE per batch: per-pop intermediate values
        are unobservable by a scraper and k locked gauge writes per cycle
        would sit on the batched hot path for nothing."""
        out = []
        for _ in range(k):
            qp = self._pop_unsynced()
            if qp is None:
                break
            out.append(qp)
        if out:
            self._sync_gauges()
        return out

    def add_unschedulable_if_not_present(self, qp: QueuedPodInfo, pod_scheduling_cycle: int,
                                         error: bool = False) -> None:
        """Failed pod → unschedulable map, or backoffQ if a move request
        raced with its cycle (:393 AddUnschedulableIfNotPresent).

        ``error=True`` marks a pod rejected by a cycle ERROR (device batch
        failure, bind error) rather than an unschedulable verdict: no
        ClusterEvent will ever reactivate it (it failed no plugin), so it
        re-enters via the backoffQ — the reference's rate-limited error
        requeue (attempts already incremented at pop, so the backoff grows
        1s→10s instead of hot-looping the active queue)."""
        key = qp.pod.key()
        if key in self._in_queue or key in self._unschedulable:
            return
        qp.timestamp = self.now_fn()
        if error or self.move_request_cycle >= pod_scheduling_cycle:
            self._push_backoff(qp, event="ScheduleAttemptFailure")
        else:
            self._unschedulable[key] = qp
            self._record_incoming("unschedulable", "ScheduleAttemptFailure")
        self._sync_gauges()

    def move_all_to_active_or_backoff_queue(self, event: ClusterEvent) -> int:
        """Reactivate unschedulable pods whose failed plugins registered
        interest in ``event`` (:614 MoveAllToActiveOrBackoffQueue). Moved
        gang members pull their parked siblings along (a member waking
        WITHOUT its gang just parks at Permit and times out)."""
        self.move_request_cycle = self.scheduling_cycle
        label = event.label or str(event.resource)
        moved = 0
        gangs_moved: Set[str] = set()
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            if self._pod_matches_event(qp, event):
                del self._unschedulable[key]
                self._requeue(qp, event=label)
                moved += 1
                if self.gang_key_fn is not None:
                    gkey = self.gang_key_fn(qp.pod)
                    if gkey is not None:
                        gangs_moved.add(gkey)
        for gkey in gangs_moved:
            moved += self.activate_gang(gkey)
        if moved:
            self._sync_gauges()
        return moved

    def activate_gang(self, gkey: str) -> int:
        """Move every unschedulable member of ``gkey`` to active/backoff
        (siblings travel together). Rate-limited per gang — the starvation
        guard: a huge gang cycling through rejection cannot re-flood the
        active queue faster than once per interval, so singleton pods keep
        getting their turn."""
        if self.gang_key_fn is None:
            return 0
        now = self.now_fn()
        last = self._gang_last_co.get(gkey)
        if last is not None and now - last < self._gang_co_interval:
            return 0
        moved = 0
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            if self.gang_key_fn(qp.pod) == gkey:
                del self._unschedulable[key]
                self._requeue(qp, event="GangActivate")
                moved += 1
        if moved:
            self._gang_last_co[gkey] = now
            self.move_request_cycle = self.scheduling_cycle
            self._sync_gauges()
        return moved

    def _pod_matches_event(self, qp: QueuedPodInfo, event: ClusterEvent) -> bool:
        if event.is_wildcard():
            return True
        failed = frozenset(qp.unschedulable_plugins)
        memo_key = (event.resource, event.action_type, event.label, failed)
        hit = self._event_match_memo.get(memo_key)
        if hit is None:
            hit = any(
                registered.match(event)
                and (not failed or plugins & failed)
                for registered, plugins in self.cluster_event_map.items())
            self._event_match_memo[memo_key] = hit
        return hit

    def _requeue(self, qp: QueuedPodInfo, event: Optional[str] = None) -> None:
        """Moved pods land in backoffQ unless their backoff already lapsed."""
        if self.now_fn() - qp.timestamp >= self._backoff_duration(qp):
            self._push_active(qp, event=event)
        else:
            self._push_backoff(qp, event=event)

    def flush_backoff_completed(self) -> None:
        """backoffQ → activeQ for expired backoffs (:432)."""
        now = self.now_fn()
        flushed = False
        while self._backoff and self._backoff[0][0] <= now:
            _, _, qp = heapq.heappop(self._backoff)
            self._in_queue.discard(qp.pod.key())
            self._push_active(qp, event="BackoffComplete")
            flushed = True
        if flushed:
            self._sync_gauges()

    def flush_unschedulable_left_over(self) -> None:
        """Pods stuck unschedulable > timeout get retried (:463)."""
        now = self.now_fn()
        flushed = False
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            if now - qp.timestamp > self.unschedulable_timeout:
                del self._unschedulable[key]
                self._requeue(qp, event="UnschedulableTimeout")
                flushed = True
        if flushed:
            self._sync_gauges()

    def assigned_pod_updated_or_added(self, pod: Pod) -> None:
        """An assigned pod changed: pods failed on affinity may now fit
        (movePodsToActiveOrBackoffQueue with Pod events)."""
        from . import events

        self.move_all_to_active_or_backoff_queue(events.POD_ADD)

    # ------------------------------------------------------------- stats

    def pending_pods(self) -> Dict[str, int]:
        return {
            "active": len(self._active),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
        }

    def pending_pod_infos(self) -> List[QueuedPodInfo]:
        """All queued pods across the three sub-queues (PendingPods, :530) —
        the debugger/comparer's queue-side truth."""
        return (
            [e[2] for e in self._active]
            + [e[2] for e in self._backoff]
            + list(self._unschedulable.values())
        )

    def dump(self) -> Dict[str, object]:
        """Structured snapshot of the three sub-queues (the /debug/queue
        introspection body; the JSON twin of dumper.go's queue section).

        Called from the serving thread while the scheduling thread mutates
        the queue: each sub-queue is first shallow-copied with a C-level
        ``list()`` (atomic under the GIL), so iteration never races a
        concurrent push/delete — the snapshot may be a moment stale, which
        is fine for a debug endpoint."""
        now = self.now_fn()
        active = list(self._active)
        backoff = list(self._backoff)
        unschedulable = list(self._unschedulable.values())

        def entry(qp: QueuedPodInfo, **extra):
            return {
                "pod": qp.pod.key(),
                "priority": qp.pod.spec.priority,
                "attempts": qp.attempts,
                "unschedulablePlugins": sorted(qp.unschedulable_plugins),
                **extra,
            }

        return {
            "counts": {"active": len(active), "backoff": len(backoff),
                       "unschedulable": len(unschedulable)},
            "schedulingCycle": self.scheduling_cycle,
            "moveRequestCycle": self.move_request_cycle,
            "active": [entry(e[2]) for e in sorted(active)],
            "backoff": [entry(e[2], backoffRemaining=max(e[0] - now, 0.0))
                        for e in sorted(backoff)],
            "unschedulable": [entry(qp, parkedFor=max(now - qp.timestamp, 0.0))
                              for qp in unschedulable],
        }

    def __len__(self) -> int:
        return len(self._active) + len(self._backoff) + len(self._unschedulable)
