"""Three-part scheduling queue (internal/queue/scheduling_queue.go).

activeQ        heap ordered by the profile's QueueSort (priority desc, FIFO)
podBackoffQ    heap ordered by backoff expiry (1s → 10s doubling, :766)
unschedulable  map of pods that failed, waiting for a relevant ClusterEvent
               (including GATED pods a PreEnqueue plugin refused admission)

Event-driven reactivation (``move_all_to_active_or_backoff``) is gated on the
cluster-event map: a pod moves only if some plugin it failed on registered
interest in the fired event (:614,:627), or on the wildcard flush.  The
``move_request_cycle`` guard (:163-167) keeps pods that failed *during* an
in-flight cycle eligible for the move that raced with them.

Pre-enqueue gating: every transition toward activeQ/backoffQ re-runs the
profile's PreEnqueue gate (``pre_enqueue_fn``); refused pods park in the
unschedulable map with ``gated=True`` — so a reactivation wave (assigned-pod
delete, gang teardown, unschedulable-timeout flush) can never move a pod
whose namespace is still over quota (the reactivation-thrash guard).

Fair-share dequeueing: namespaces with a SchedulingQuota (``ns_weight_fn``
returns a weight) get their own activeQ sub-heap and are served by deficit
round robin in proportion to weight — one flooding tenant cannot starve the
rest. WITHIN a tenant's turn the profile's QueueSort key still orders pods,
so gang members stay adjacent; a gang larger than the tenant's quantum keeps
the turn via gang continuation (the deficit goes negative and is paid back
over the following rounds). Namespaces without a quota share the default
bucket, which participates in the rotation with weight 1.

Flush tickers (:432,:463) become explicit ``flush_*`` calls driven by the
scheduler loop (no background goroutines; the loop is single-threaded and the
TPU batch path wants deterministic drain points anyway).
"""

from __future__ import annotations

import bisect
import functools
import heapq
import itertools
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api.types import Pod
from ..framework.types import ClusterEvent, QueuedPodInfo
from ..metrics import latency_ledger
from ..testing import locktrace

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_UNSCHEDULABLE_TIMEOUT = 300.0  # flushUnschedulablePodsLeftover, 5min

# DRR quantum: pods a weight-1 tenant may drain per rotation turn. Large
# enough that small gangs stay in one turn, small enough that a turn cannot
# monopolize a micro-batch.
DEFAULT_FAIR_QUANTUM = 4.0

LessFn = Callable[[QueuedPodInfo], object]  # sort-key extractor


def _locked(fn):
    """Every public entry point runs under the queue's RLock: the queue is
    mutated by the scheduling loop but READ by the serving threads
    (/debug/queue dump, pending gauges) and, under the cmd topology, poked
    by informer handlers. The lock is reentrant — public methods call each
    other (update→add, pop→flush) — and comes from the locktrace factory so
    the chaos suites can prove the queue participates in no lock-order
    cycle. The lock-discipline pass treats @_locked bodies as guarded."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


class SchedulingQueue:
    def __init__(
        self,
        less_key: Optional[LessFn] = None,
        initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        unschedulable_timeout: float = DEFAULT_UNSCHEDULABLE_TIMEOUT,
        cluster_event_map: Optional[Dict[ClusterEvent, Set[str]]] = None,
        now_fn=time.monotonic,
        metrics=None,
        gang_key_fn=None,
        gang_coactivation_interval: Optional[float] = None,
        pre_enqueue_fn: Optional[Callable[[Pod], Optional[object]]] = None,
        ns_weight_fn: Optional[Callable[[str], Optional[float]]] = None,
        fair_quantum: float = DEFAULT_FAIR_QUANTUM,
    ):
        # default QueueSort: priority desc then FIFO (PrioritySort)
        self.less_key = less_key or (lambda qp: (-qp.pod.spec.priority, qp.timestamp))
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.unschedulable_timeout = unschedulable_timeout
        self.cluster_event_map = cluster_event_map or {}
        # (event, failed-plugin-set) -> bool memo: a bind fires POD_ADD into
        # move_all for EVERY unschedulable pod; distinct plugin sets are few,
        # so the O(|event map|) scan runs once per (event, set), not per pod
        # per bind (was 3.2M ClusterEvent.match calls in the Unschedulable
        # workload's measured window)
        self._event_match_memo: Dict[tuple, bool] = {}
        self.now_fn = now_fn
        # SchedulerMetrics (or None): queue_incoming_pods counters on every
        # transition + pending_pods gauge sync (metrics.go:120-134; both were
        # registered-but-dead before the queue owned them)
        self._metrics = metrics

        # gang co-activation (Coscheduling): pod -> group key (or None).
        # When a member enters the active path its unschedulable siblings
        # move too, so a gang re-attempts TOGETHER instead of trickling in
        # one member per event and timing out at Permit. The per-gang
        # interval is the starvation guard: a flapping gang cannot spin the
        # queue faster than the backoff it would otherwise pay.
        self.gang_key_fn = gang_key_fn
        self._gang_co_interval = (gang_coactivation_interval
                                  if gang_coactivation_interval is not None
                                  else initial_backoff)
        self._gang_last_co: Dict[str, float] = {}

        # pre-enqueue gate: fn(pod) -> None (admit) or a non-success Status
        # (park gated; status.plugin attributes the gate for event matching)
        self.pre_enqueue_fn = pre_enqueue_fn
        # fair share: fn(namespace) -> weight for tenant namespaces, None
        # for default-bucket namespaces
        self.ns_weight_fn = ns_weight_fn
        self._fair_quantum = fair_quantum
        self._active_ns: Dict[str, List[Tuple[object, int, QueuedPodInfo]]] = {}
        # sorted(_active_ns) maintained incrementally (bisect on bucket
        # create/empty) — _drr_pop runs once per pop and must not re-sort
        self._drr_names: List[str] = []
        self._deficit: Dict[str, float] = {}
        self._drr_cur: Optional[str] = None
        self._gang_cont: Optional[Tuple[str, str]] = None

        self._lock = locktrace.make_rlock("SchedulingQueue")
        # commit-plane coalescing window (coalesce_moves): while not None,
        # move_all_to_active_or_backoff_queue defers its event here and the
        # window exit runs ONE unschedulable-map scan over the union —
        # a batch of binds otherwise fires one full-map scan per bound pod
        self._move_backlog: Optional[List[ClusterEvent]] = None
        self._counter = itertools.count()  # FIFO tie-break inside heaps
        self._active: List[Tuple[object, int, QueuedPodInfo]] = []
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._in_queue: Set[str] = set()  # keys in active/backoff heaps
        self.scheduling_cycle = 0
        self.move_request_cycle = -1

    # ------------------------------------------------------------- helpers

    def _backoff_duration(self, qp: QueuedPodInfo) -> float:
        """calculateBackoffDuration (:766): initial · 2^(attempts-1), capped."""
        d = self.initial_backoff
        for _ in range(1, qp.attempts):
            d *= 2
            if d >= self.max_backoff:
                return self.max_backoff
        return d

    def _tenant_of(self, pod: Pod) -> Optional[str]:
        """Fair-share bucket for a pod: its namespace when that namespace is
        a tenant (has a SchedulingQuota weight), else None (default bucket)."""
        if self.ns_weight_fn is None:
            return None
        ns = pod.meta.namespace
        return ns if self.ns_weight_fn(ns) is not None else None

    def _push_active(self, qp: QueuedPodInfo, event: Optional[str] = None) -> None:  # ktpu: locked
        key = qp.pod.key()
        if key in self._in_queue:
            return
        entry = (self.less_key(qp), next(self._counter), qp)
        tenant = self._tenant_of(qp.pod)
        if tenant is None:
            heapq.heappush(self._active, entry)
        else:
            if tenant not in self._active_ns:
                bisect.insort(self._drr_names, tenant)
            heapq.heappush(self._active_ns.setdefault(tenant, []), entry)
        self._in_queue.add(key)
        self._record_incoming("active", event)
        # latency ledger: activeQ dwell of a tenant-bucketed pod under
        # contention (another bucket is live, so the DRR rotation is what
        # the pod actually waits on) attributes to queue.drr_wait
        contended = (tenant is not None
                     and len(self._active_ns)
                     + (1 if self._active else 0) > 1)
        latency_ledger.transition(
            key, "queue.drr_wait" if contended else "queue.active",
            namespace=qp.pod.meta.namespace)

    def _push_backoff(self, qp: QueuedPodInfo, event: Optional[str] = None) -> None:  # ktpu: locked
        key = qp.pod.key()
        if key in self._in_queue:
            return
        expiry = qp.timestamp + self._backoff_duration(qp)
        heapq.heappush(self._backoff, (expiry, next(self._counter), qp))
        self._in_queue.add(key)
        self._record_incoming("backoff", event)
        latency_ledger.transition(key, "queue.backoff",
                                  namespace=qp.pod.meta.namespace)

    def _record_incoming(self, queue: str, event: Optional[str]) -> None:
        if self._metrics is not None and event is not None:
            self._metrics.queue_incoming_pods.inc(queue, event)

    def _sync_gauges(self) -> None:
        """pending_pods gauge ← the sub-queue sizes (SchedulerQueue
        Incoming/Pending recorders; cheap enough to run per transition)."""
        if self._metrics is not None:
            self._metrics.sync_queue_gauges(self.pending_pods())

    # -------------------------------------------------------- pre-enqueue gate

    def _park_gated(self, qp: QueuedPodInfo, event: Optional[str]) -> bool:  # ktpu: locked
        """Run the PreEnqueue gate for a pod about to enter active/backoff.
        True = refused and parked gated in the unschedulable map (with the
        gating plugin attributed, so its release event can wake the pod)."""
        if self.pre_enqueue_fn is None:
            return False
        key = qp.pod.key()
        if key in self._in_queue:
            return False
        st = self.pre_enqueue_fn(qp.pod)
        if st is None:
            qp.gated = False
            return False
        qp.gated = True
        qp.timestamp = self.now_fn()
        plugin = getattr(st, "plugin", "")
        if plugin:
            qp.unschedulable_plugins.add(plugin)
        if key not in self._unschedulable:
            self._record_incoming("gated", event)
        self._unschedulable[key] = qp
        latency_ledger.transition(key, "queue.gated",
                                  namespace=qp.pod.meta.namespace)
        return True

    # ------------------------------------------------------------- API

    @_locked
    def add(self, pod: Pod) -> None:
        """New unscheduled pod (informer add) → activeQ (:300), unless the
        PreEnqueue gate parks it. A gang member's arrival co-activates its
        parked siblings — the late 32nd pod of a gang must wake the 31 that
        failed PreFilter on it."""
        qp = QueuedPodInfo(pod=pod, timestamp=self.now_fn())
        if not self._park_gated(qp, "PodAdd"):
            self._push_active(qp, event="PodAdd")
        if self.gang_key_fn is not None:
            gkey = self.gang_key_fn(pod)
            if gkey is not None:
                self.activate_gang(gkey)
        self._sync_gauges()

    @_locked
    def update(self, old: Optional[Pod], new: Pod) -> None:
        """Pod update may make an unschedulable pod schedulable again (:525);
        a pod the queue has never seen falls through to activeQ (reference
        Update's final AddNewPod branch)."""
        key = new.key()
        if key in self._in_queue:
            return  # will be scheduled with fresh object at pop time via store
        qp = self._unschedulable.pop(key, None)
        if qp is not None:
            qp.pod = new
            if not self._park_gated(qp, "PodUpdate"):
                self._push_backoff(qp, event="PodUpdate")
            self._sync_gauges()
        else:
            self.add(new)

    @_locked
    def delete(self, pod: Pod) -> None:
        key = pod.key()
        # terminal delete of an unbound pod: the ledger entry drops (closed
        # result="deleted") so cluster churn cannot leak entries
        latency_ledger.drop(key)
        self._unschedulable.pop(key, None)
        if key in self._in_queue:
            self._in_queue.discard(key)
            self._active = [e for e in self._active if e[2].pod.key() != key]
            heapq.heapify(self._active)
            # tenant buckets are keyed by namespace, so only the pod's own
            # bucket can hold it — rebuilding every tenant heap would make
            # each delete O(total active pods) under churn
            ns = pod.meta.namespace
            heap = self._active_ns.get(ns)
            if heap is not None:
                h = [e for e in heap if e[2].pod.key() != key]
                if h:
                    heapq.heapify(h)
                    self._active_ns[ns] = h
                else:
                    del self._active_ns[ns]
                    self._drop_drr_name(ns)
            self._backoff = [e for e in self._backoff if e[2].pod.key() != key]
            heapq.heapify(self._backoff)
        self._sync_gauges()

    @_locked
    def pop(self) -> Optional[QueuedPodInfo]:
        """Next pod to schedule, or None (non-blocking; the reference blocks,
        :484 — the loop idles instead). Bumps attempts + scheduling_cycle."""
        qp = self._pop_unsynced()
        if qp is not None:
            self._sync_gauges()
        return qp

    def _pop_unsynced(self) -> Optional[QueuedPodInfo]:  # ktpu: locked
        self.flush_backoff_completed()
        qp = self._pop_active()
        if qp is None:
            return None
        self._in_queue.discard(qp.pod.key())
        qp.attempts += 1
        self.scheduling_cycle += 1
        latency_ledger.transition(qp.pod.key(), "cycle.host",
                                  namespace=qp.pod.meta.namespace)
        return qp

    def _pop_active(self) -> Optional[QueuedPodInfo]:  # ktpu: locked
        if not self._active_ns:
            # no tenant heaps: the exact legacy single-heap order
            if not self._active:
                return None
            return heapq.heappop(self._active)[2]
        return self._drr_pop()

    # -------------------------------------------------- fair-share dequeueing

    def _weight_of(self, ns: str) -> float:
        if not ns:  # default bucket (unquota'd namespaces)
            return 1.0
        w = self.ns_weight_fn(ns) if self.ns_weight_fn is not None else None
        return max(float(w), 0.0) if w is not None else 1.0

    def _drop_drr_name(self, ns: str) -> None:  # ktpu: locked
        i = bisect.bisect_left(self._drr_names, ns)
        if i < len(self._drr_names) and self._drr_names[i] == ns:
            del self._drr_names[i]

    def _drr_bucket(self, ns: str) -> List:  # ktpu: locked
        return self._active if ns == "" else self._active_ns[ns]

    def _drr_pop(self) -> Optional[QueuedPodInfo]:  # ktpu: locked
        # tenant heaps are never empty (emptied buckets are dropped at the
        # _drr_take/delete sites), so _drr_names IS sorted(buckets) — no
        # per-pop dict rebuild or sort on the batched-drain hot path
        has_default = bool(self._active)
        n_buckets = len(self._active_ns) + (1 if has_default else 0)
        if n_buckets == 0:
            return None
        if n_buckets == 1:
            # uncontended service is free — classic DRR only tracks deficit
            # while tenants compete. Charging here would bank unbounded debt
            # for a tenant that ran alone (one -1 per solo pop) and starve
            # it for thousands of rotations once a second tenant appears.
            ns = "" if has_default else self._drr_names[0]
            return self._drr_take(ns, self._drr_bucket(ns), charge=False)
        # gang continuation: a tenant mid-gang keeps the turn regardless of
        # deficit (which goes negative and is paid back next rounds) — a
        # gang must never interleave with another tenant's pods
        if self._gang_cont is not None:
            ns, gkey = self._gang_cont
            h = self._active if ns == "" else self._active_ns.get(ns)
            if (h and self.gang_key_fn is not None
                    and self.gang_key_fn(h[0][2].pod) == gkey):
                return self._drr_take(ns, h)
            self._gang_cont = None
        names = ([""] if has_default else []) + self._drr_names
        cur = self._drr_cur
        cur_live = ((cur == "" and has_default)
                    or (cur in self._active_ns))
        if cur_live and self._deficit.get(cur, 0.0) >= 1.0:
            return self._drr_take(cur, self._drr_bucket(cur))  # finish turn
        start = (names.index(cur) + 1) if cur_live else 0
        for step in range(len(names)):
            ns = names[(start + step) % len(names)]
            w = self._weight_of(ns)
            credit = self._fair_quantum * w
            # cap banked credit at two quanta: a tenant that idles through
            # rotations must not save up an unbounded burst
            self._deficit[ns] = min(self._deficit.get(ns, 0.0) + credit,
                                    max(2.0 * credit, 1.0))
            if self._deficit[ns] >= 1.0:
                return self._drr_take(ns, self._drr_bucket(ns))
        # every candidate is weight-0 (background tenants): stay
        # work-conserving rather than wedging the queue. No charge — their
        # rotation credit is 0, so debt could never be paid back and would
        # starve any of them later granted a real weight.
        ns = names[start % len(names)]
        return self._drr_take(ns, self._drr_bucket(ns), charge=False)

    def _drr_take(self, ns: str, heap: List, charge: bool = True) -> QueuedPodInfo:  # ktpu: locked
        _k, _c, qp = heapq.heappop(heap)
        if heap:
            if charge:
                self._deficit[ns] = self._deficit.get(ns, 0.0) - 1.0
        else:
            # classic DRR: an emptied queue forfeits leftover credit
            self._deficit.pop(ns, None)
            if ns:
                self._active_ns.pop(ns, None)
                self._drop_drr_name(ns)
        if self._drr_cur != ns:
            self._drr_cur = ns
            if self._metrics is not None and ns:
                self._metrics.fair_share_turns.inc(ns)
        gkey = self.gang_key_fn(qp.pod) if self.gang_key_fn is not None else None
        self._gang_cont = (ns, gkey) if gkey is not None else None
        return qp

    @_locked
    def pop_batch(self, k: int) -> List[QueuedPodInfo]:
        """Drain up to k pods in queue order — the TPU micro-batch feed.
        The pending gauge syncs ONCE per batch: per-pop intermediate values
        are unobservable by a scraper and k locked gauge writes per cycle
        would sit on the batched hot path for nothing."""
        out = []
        for _ in range(k):
            qp = self._pop_unsynced()
            if qp is None:
                break
            out.append(qp)
        if out:
            self._sync_gauges()
        return out

    @_locked
    def add_unschedulable_if_not_present(self, qp: QueuedPodInfo, pod_scheduling_cycle: int,
                                         error: bool = False) -> None:
        """Failed pod → unschedulable map, or backoffQ if a move request
        raced with its cycle (:393 AddUnschedulableIfNotPresent).

        ``error=True`` marks a pod rejected by a cycle ERROR (device batch
        failure, bind error) rather than an unschedulable verdict: no
        ClusterEvent will ever reactivate it (it failed no plugin), so it
        re-enters via the backoffQ — the reference's rate-limited error
        requeue (attempts already incremented at pop, so the backoff grows
        1s→10s instead of hot-looping the active queue)."""
        key = qp.pod.key()
        if key in self._in_queue or key in self._unschedulable:
            return
        qp.timestamp = self.now_fn()
        if error or self.move_request_cycle >= pod_scheduling_cycle:
            if not self._park_gated(qp, "ScheduleAttemptFailure"):
                self._push_backoff(qp, event="ScheduleAttemptFailure")
        elif not self._park_gated(qp, "ScheduleAttemptFailure"):
            # the PreEnqueue gate re-check first: a pod that failed its
            # cycle on the quota gate (PreFilter caught what PreEnqueue
            # raced past) parks GATED, not plain-unschedulable, so only the
            # targeted quota-release move — never the timeout flush or an
            # unrelated event wave — can wake it
            self._unschedulable[key] = qp
            self._record_incoming("unschedulable", "ScheduleAttemptFailure")
            latency_ledger.transition(key, "queue.unschedulable",
                                      namespace=qp.pod.meta.namespace)
        self._sync_gauges()

    @_locked
    def move_all_to_active_or_backoff_queue(self, event: ClusterEvent) -> int:
        """Reactivate unschedulable pods whose failed plugins registered
        interest in ``event`` (:614 MoveAllToActiveOrBackoffQueue). Moved
        gang members pull their parked siblings along (a member waking
        WITHOUT its gang just parks at Permit and times out). Pods the
        PreEnqueue gate still refuses re-park without a queue move.

        Inside a ``coalesce_moves`` window the scan is DEFERRED (returns 0):
        the event joins the window's backlog and the exit flush runs one
        union scan. ``move_request_cycle`` still advances immediately — a
        racing cycle's failure must see the pending move and take the
        backoffQ, exactly as with the eager scan."""
        self.move_request_cycle = self.scheduling_cycle
        if self._move_backlog is not None:
            self._move_backlog.append(event)
            if self._metrics is not None:
                self._metrics.commit_coalesced_events.inc("queue_move")
            return 0
        return self._move_all_locked((event,))

    def _move_all_locked(self, events) -> int:  # ktpu: locked
        """One scan of the unschedulable map against every event in
        ``events``; a pod moves once, attributed to the first event that
        matches it."""
        moved = 0
        gangs_moved: Set[str] = set()
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            for event in events:
                if self._pod_matches_event(qp, event):
                    del self._unschedulable[key]
                    if self._requeue(qp, event=event.label
                                     or str(event.resource)):
                        moved += 1
                        if self.gang_key_fn is not None:
                            gkey = self.gang_key_fn(qp.pod)
                            if gkey is not None:
                                gangs_moved.add(gkey)
                    break
        for gkey in gangs_moved:
            moved += self.activate_gang(gkey)
        if moved:
            self._sync_gauges()
        return moved

    def coalesce_moves(self):
        """Context manager: defer every move_all_to_active_or_backoff_queue
        fired inside the window into ONE union scan at exit (the commit
        data plane's notification coalescing — a committed batch of N binds
        fires N POD_ADD moves, each a full unschedulable-map scan without
        this). Windows nest: only the outermost flushes. Targeted moves
        (move_gated_pods, activate_gang) stay eager — they are O(released),
        not O(map)."""
        queue = self

        class _Window:
            def __enter__(self):
                with queue._lock:
                    self._owner = queue._move_backlog is None
                    if self._owner:
                        queue._move_backlog = []
                return self

            def __exit__(self, *exc):
                if self._owner:
                    queue.flush_coalesced_moves()
                return False

        return _Window()

    @_locked
    def flush_coalesced_moves(self) -> int:
        """Close the coalescing window: run the single union scan over the
        deferred events (deduplicated — a batch of binds repeats POD_ADD)."""
        backlog, self._move_backlog = self._move_backlog, None
        if not backlog:
            return 0
        events = list(dict.fromkeys(backlog))
        return self._move_all_locked(events)

    @_locked
    def move_gated_pods(self, namespace: Optional[str] = None,
                        plugin: Optional[str] = None,
                        admit_fn: Optional[Callable[[Pod], Optional[object]]] = None,
                        event: str = "QuotaReleased") -> int:
        """Targeted reactivation for a PreEnqueue gate release (quota
        headroom opened in ``namespace``): move gated pods — and pods whose
        failure is attributed to ``plugin`` — back toward activeQ, re-gated
        through ``admit_fn`` (a shadow-ledger gate: one freed slot admits
        one pod) or, absent one, the regular pre-enqueue re-check. Pods
        still refused never fire a queue move; admitted pods go straight to
        activeQ — they are not backing off a failure, the headroom they
        waited for just opened."""
        moved = 0
        for key in list(self._unschedulable):
            qp = self._unschedulable.get(key)
            if qp is None:
                continue
            if namespace is not None and qp.pod.meta.namespace != namespace:
                continue
            if not qp.gated and (plugin is None
                                 or plugin not in qp.unschedulable_plugins):
                continue
            if admit_fn is not None:
                st = admit_fn(qp.pod)
                if st is not None:
                    qp.gated = True  # refreshed park, no queue move
                    continue
                del self._unschedulable[key]
                qp.gated = False
            else:
                del self._unschedulable[key]
                qp.gated = False
                if self._park_gated(qp, event):
                    continue  # the regular gate still refuses
            self._push_active(qp, event=event)
            moved += 1
            if self._metrics is not None:
                self._metrics.quota_released_pods.inc(qp.pod.meta.namespace)
        if moved:
            self.move_request_cycle = self.scheduling_cycle
            self._sync_gauges()
        return moved

    @_locked
    def activate_gang(self, gkey: str) -> int:
        """Move every unschedulable member of ``gkey`` to active/backoff
        (siblings travel together). Rate-limited per gang — the starvation
        guard: a huge gang cycling through rejection cannot re-flood the
        active queue faster than once per interval, so singleton pods keep
        getting their turn."""
        if self.gang_key_fn is None:
            return 0
        now = self.now_fn()
        last = self._gang_last_co.get(gkey)
        if last is not None and now - last < self._gang_co_interval:
            return 0
        moved = 0
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            if self.gang_key_fn(qp.pod) == gkey:
                del self._unschedulable[key]
                if self._requeue(qp, event="GangActivate"):
                    moved += 1
        if moved:
            self._gang_last_co[gkey] = now
            self.move_request_cycle = self.scheduling_cycle
            self._sync_gauges()
        return moved

    def _pod_matches_event(self, qp: QueuedPodInfo, event: ClusterEvent) -> bool:  # ktpu: locked
        if event.is_wildcard():
            return True
        failed = frozenset(qp.unschedulable_plugins)
        memo_key = (event.resource, event.action_type, event.label, failed)
        hit = self._event_match_memo.get(memo_key)
        if hit is None:
            hit = any(
                registered.match(event)
                and (not failed or plugins & failed)
                for registered, plugins in self.cluster_event_map.items())
            self._event_match_memo[memo_key] = hit
        return hit

    def _requeue(self, qp: QueuedPodInfo, event: Optional[str] = None) -> bool:  # ktpu: locked
        """Moved pods land in backoffQ unless their backoff already lapsed —
        after the PreEnqueue gate re-check (a still-refused pod re-parks
        gated instead; returns False: no queue move happened)."""
        if self._park_gated(qp, event):
            return False
        if self.now_fn() - qp.timestamp >= self._backoff_duration(qp):
            self._push_active(qp, event=event)
        else:
            self._push_backoff(qp, event=event)
        return True

    @_locked
    def flush_backoff_completed(self) -> None:
        """backoffQ → activeQ for expired backoffs (:432), re-gated: quota
        may have filled while the pod backed off."""
        now = self.now_fn()
        flushed = False
        while self._backoff and self._backoff[0][0] <= now:
            _, _, qp = heapq.heappop(self._backoff)
            self._in_queue.discard(qp.pod.key())
            if not self._park_gated(qp, "BackoffComplete"):
                self._push_active(qp, event="BackoffComplete")
            flushed = True
        if flushed:
            self._sync_gauges()

    @_locked
    def flush_unschedulable_left_over(self) -> None:
        """Pods stuck unschedulable > timeout get retried (:463). Gated pods
        are exempt: the gate condition (namespace over quota) is level-held
        and re-checked on every release — a timeout flush would just churn
        them through ``_requeue`` back into the same parked state."""
        now = self.now_fn()
        flushed = False
        for key in list(self._unschedulable):
            qp = self._unschedulable[key]
            if qp.gated:
                continue
            if now - qp.timestamp > self.unschedulable_timeout:
                del self._unschedulable[key]
                self._requeue(qp, event="UnschedulableTimeout")
                flushed = True
        if flushed:
            self._sync_gauges()

    @_locked
    def assigned_pod_updated_or_added(self, pod: Pod) -> None:
        """An assigned pod changed: pods failed on affinity may now fit
        (movePodsToActiveOrBackoffQueue with Pod events)."""
        from . import events

        self.move_all_to_active_or_backoff_queue(events.POD_ADD)

    # ------------------------------------------------------------- stats

    @_locked
    def pending_pods(self) -> Dict[str, int]:
        gated = sum(1 for qp in self._unschedulable.values() if qp.gated)
        return {
            "active": len(self._active) + sum(
                len(h) for h in self._active_ns.values()),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable) - gated,
            "gated": gated,
        }

    @_locked
    def pending_pod_infos(self) -> List[QueuedPodInfo]:
        """All queued pods across the sub-queues (PendingPods, :530) —
        the debugger/comparer's queue-side truth."""
        return (
            [e[2] for e in self._active]
            + [e[2] for h in self._active_ns.values() for e in h]
            + [e[2] for e in self._backoff]
            + list(self._unschedulable.values())
        )

    @_locked
    def dump(self) -> Dict[str, object]:
        """Structured snapshot of the sub-queues (the /debug/queue
        introspection body; the JSON twin of dumper.go's queue section).

        Called from the serving thread while the scheduling thread mutates
        the queue: each sub-queue is first shallow-copied with a C-level
        ``list()`` (atomic under the GIL), so iteration never races a
        concurrent push/delete — the snapshot may be a moment stale, which
        is fine for a debug endpoint."""
        now = self.now_fn()
        active = list(self._active)
        for ns in list(self._active_ns):
            active += list(self._active_ns.get(ns, ()))
        backoff = list(self._backoff)
        unschedulable = list(self._unschedulable.values())

        def entry(qp: QueuedPodInfo, **extra):
            return {
                "pod": qp.pod.key(),
                "priority": qp.pod.spec.priority,
                "attempts": qp.attempts,
                "unschedulablePlugins": sorted(qp.unschedulable_plugins),
                **extra,
            }

        counts = self.pending_pods()
        return {
            "counts": dict(counts),
            "schedulingCycle": self.scheduling_cycle,
            "moveRequestCycle": self.move_request_cycle,
            "fairShare": {
                "tenants": {ns: len(h) for ns, h in self._active_ns.items()},
                "deficits": {ns: round(d, 3)
                             for ns, d in self._deficit.items()},
                "currentTurn": self._drr_cur,
            },
            "active": [entry(e[2]) for e in sorted(active)],
            "backoff": [entry(e[2], backoffRemaining=max(e[0] - now, 0.0))
                        for e in sorted(backoff)],
            "unschedulable": [entry(qp, parkedFor=max(now - qp.timestamp, 0.0))
                              for qp in unschedulable if not qp.gated],
            "gated": [entry(qp, parkedFor=max(now - qp.timestamp, 0.0))
                      for qp in unschedulable if qp.gated],
        }

    @_locked
    def __len__(self) -> int:
        return (len(self._active)
                + sum(len(h) for h in self._active_ns.values())
                + len(self._backoff) + len(self._unschedulable))
