"""Named cluster events that reactivate unschedulable pods
(internal/queue/events.go:25-91)."""

from ..framework.types import (
    ADD,
    ALL,
    ClusterEvent,
    DELETE,
    NODE,
    POD,
    PV,
    PVC,
    SCHEDULING_QUOTA,
    STORAGE_CLASS,
    UPDATE,
    UPDATE_NODE_ALLOCATABLE,
    UPDATE_NODE_LABEL,
    UPDATE_NODE_TAINT,
    WILDCARD,
)

UNSCHEDULABLE_TIMEOUT = ClusterEvent(WILDCARD, ALL, "UnschedulableTimeout")
NODE_ADD = ClusterEvent(NODE, ADD, "NodeAdd")
NODE_DELETE = ClusterEvent(NODE, DELETE, "NodeDelete")
POD_ADD = ClusterEvent(POD, ADD, "PodAdd")
POD_DELETE = ClusterEvent(POD, DELETE, "AssignedPodDelete")
# HA fence: a dead scheduler replica's uncommitted capacity was released —
# from a parked pod's perspective the same wake-up as an assigned-pod
# delete (real capacity freed), but labeled so queue_incoming_pods can
# attribute the surge to the takeover
SCHEDULER_TAKEOVER = ClusterEvent(POD, DELETE, "SchedulerTakeover")
# drain/spot eviction wave (controllers/drain.py): bound pods were deleted
# en masse — capacity freed for everything parked on resource fit, labeled
# so the rebind surge is attributable to the wave rather than organic churn
EVICTION = ClusterEvent(POD, DELETE, "EvictionWave")
POD_UPDATE = ClusterEvent(POD, UPDATE, "AssignedPodUpdate")
NODE_ALLOCATABLE_CHANGE = ClusterEvent(NODE, UPDATE_NODE_ALLOCATABLE, "NodeAllocatableChange")
NODE_LABEL_CHANGE = ClusterEvent(NODE, UPDATE_NODE_LABEL, "NodeLabelChange")
NODE_TAINT_CHANGE = ClusterEvent(NODE, UPDATE_NODE_TAINT, "NodeTaintChange")
# namespace quota headroom opened (a charged pod released capacity, or the
# SchedulingQuota object itself grew): wakes ONLY pods gated/failed on the
# QuotaAdmission plugin — and the queue's pre-enqueue re-check keeps pods in
# still-over-quota namespaces parked, so sustained over-quota load cannot
# thrash the active queue
QUOTA_RELEASE = ClusterEvent(SCHEDULING_QUOTA, ALL, "QuotaReleased")
PVC_ADD = ClusterEvent(PVC, ADD, "PvcAdd")
PV_ADD = ClusterEvent(PV, ADD, "PvAdd")
STORAGE_CLASS_ADD = ClusterEvent(STORAGE_CLASS, ADD, "StorageClassAdd")
