"""Node agent (L4c): hollow kubelet + kubemark cluster.

The reference tests 5k-node scheduling without 5k machines via kubemark
hollow nodes (pkg/kubemark/hollow_kubelet.go:65): a real control plane with
kubelets whose container runtime is fake. Same here: HollowKubelet registers
its Node, heartbeats a Lease + NodeStatus, and runs the pod syncLoop against
a no-op runtime (Pending → Running → Succeeded), which is exactly what the
scheduler/controller stack needs to observe. The checkpoint manager mirrors
pkg/kubelet/checkpointmanager (checksummed state files surviving restarts).
"""

from .checkpoint import CheckpointManager
from .hollow import HollowKubelet
from .kubemark import HollowCluster

__all__ = ["CheckpointManager", "HollowCluster", "HollowKubelet"]
