"""Kubemark hollow cluster (cmd/kubemark/hollow-node.go + test/kubemark):
N hollow kubelets against one store — how thousand-node scheduling behavior
is exercised without machines.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..api.types import Node
from ..api.wrappers import make_node
from ..apiserver.store import ClusterStore
from .hollow import HollowKubelet


def default_node(i: int) -> Node:
    return (
        make_node(f"hollow-node-{i}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": 110})
        .label("topology.kubernetes.io/zone", f"zone-{i % 3}")
        .label("kubernetes.io/hostname", f"hollow-node-{i}")
        .obj()
    )


class HollowCluster:
    def __init__(self, store: ClusterStore, n_nodes: int,
                 node_fn: Callable[[int], Node] = default_node,
                 now_fn=time.monotonic, startup_delay: float = 0.0,
                 with_runtime: bool = False,
                 with_volume_manager: bool = False):
        """``with_runtime``: each hollow kubelet gets its own
        FakeRuntimeService + PLEG (the hollow-node.go injected-CRI mode);
        ``with_volume_manager``: PVC mounts gate Pending→Running (attach
        treated as instant — kubemark has no attachdetach controller)."""
        self.store = store
        self.kubelets: List[HollowKubelet] = []
        for i in range(n_nodes):
            runtime = None
            if with_runtime:
                from .cri import FakeRuntimeService

                runtime = FakeRuntimeService(now_fn=now_fn)
            k = HollowKubelet(store, node_fn(i), now_fn=now_fn,
                              startup_delay=startup_delay, runtime=runtime)
            if with_volume_manager:
                from .volume_manager import VolumeManager

                k.volume_manager = VolumeManager(store, k.node_name,
                                                 require_attach=False)
            self.kubelets.append(k)

    def register_all(self) -> None:
        for k in self.kubelets:
            k.register()

    def tick(self) -> int:
        """One kubelet round across the fleet; returns status transitions."""
        return sum(k.run_once() for k in self.kubelets)

    def settle(self, max_rounds: int = 20) -> int:
        total = 0
        for _ in range(max_rounds):
            n = self.tick()
            total += n
            if n == 0:
                break
        return total

    def kubelet_for(self, node_name: str) -> Optional[HollowKubelet]:
        for k in self.kubelets:
            if k.node_name == node_name:
                return k
        return None
