"""Kubelet eviction manager (pkg/kubelet/eviction/eviction_manager.go).

The node agent's self-defense loop: observe resource pressure signals,
report pressure conditions on the Node object, and evict pods — lowest
"value" first — until the signal clears. The reference's synchronize()
(eviction_manager.go:233) runs every 10s:

  1. collect signals (memory.available, nodefs.available, pid.available)
     from the stats provider (summary API; here a pluggable ``stats_fn``);
  2. threshold crossings set node conditions (MemoryPressure/DiskPressure/
     PIDPressure) — the nodelifecycle controller mirrors conditions as
     NoSchedule taints so the scheduler keeps new pods away;
  3. rank active pods for the starved resource (rankMemoryPressure,
     eviction/helpers.go:1144): pods EXCEEDING their request first, then by
     priority ascending, then by usage-over-request descending;
  4. evict ONE pod per pass (evictPod, :570): phase Failed, reason
     "Evicted" — one at a time so the next observation sees the relief.

Pressure conditions persist for a grace period after the signal clears
(pressureTransitionPeriod, default 30s here vs the reference's 5m) to
prevent condition flapping.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional

from ..api.types import Pod
from ..apiserver.store import ClusterStore, Conflict, NotFound

SIGNAL_MEMORY_AVAILABLE = "memory.available"
SIGNAL_NODEFS_AVAILABLE = "nodefs.available"
SIGNAL_PID_AVAILABLE = "pid.available"

# signal -> node condition attribute (core/v1 NodeConditionType)
_CONDITION_OF = {
    SIGNAL_MEMORY_AVAILABLE: "memory_pressure",
    SIGNAL_NODEFS_AVAILABLE: "disk_pressure",
    SIGNAL_PID_AVAILABLE: "pid_pressure",
}

# eviction_manager.go evictionMaxPodGracePeriod default hard-eviction set
DEFAULT_THRESHOLDS = {
    SIGNAL_MEMORY_AVAILABLE: 100 << 20,   # 100Mi
    SIGNAL_NODEFS_AVAILABLE: 1 << 30,     # 10% stand-in: 1Gi
    SIGNAL_PID_AVAILABLE: 300,
}

REASON_EVICTED = "Evicted"


@dataclasses.dataclass
class PodStats:
    """Per-pod usage for ranking (summary API stand-in): bytes for memory/
    disk signals, count for pids."""

    memory_bytes: int = 0
    disk_bytes: int = 0
    pids: int = 0

    def usage_for(self, signal: str) -> int:
        if signal == SIGNAL_MEMORY_AVAILABLE:
            return self.memory_bytes
        if signal == SIGNAL_NODEFS_AVAILABLE:
            return self.disk_bytes
        return self.pids


class EvictionManager:
    def __init__(self, store: ClusterStore, node_name: str,
                 stats_fn: Callable[[], Dict[str, int]],
                 pod_stats_fn: Optional[Callable[[str], PodStats]] = None,
                 thresholds: Optional[Dict[str, int]] = None,
                 pressure_transition_period: float = 30.0,
                 now_fn=time.monotonic):
        """``stats_fn`` returns the node's current signal values (available
        amounts); ``pod_stats_fn(pod_key)`` per-pod usage for ranking."""
        self.store = store
        self.node_name = node_name
        self.stats_fn = stats_fn
        self.pod_stats_fn = pod_stats_fn or (lambda key: PodStats())
        self.thresholds = dict(DEFAULT_THRESHOLDS, **(thresholds or {}))
        self.transition_period = pressure_transition_period
        self.now_fn = now_fn
        self._last_observed_pressure: Dict[str, float] = {}
        self.evicted_total = 0

    # -------------------------------------------------------------- signals

    def _crossed(self, signals: Dict[str, int]) -> List[str]:
        out = []
        for sig, threshold in self.thresholds.items():
            if sig in signals and signals[sig] < threshold:
                out.append(sig)
        return out

    def _set_conditions(self, under_pressure: List[str]) -> None:
        """Write pressure conditions (with the anti-flap transition grace)
        onto the Node object."""
        node = self.store.nodes.get(self.node_name)
        if node is None:
            return
        now = self.now_fn()
        for sig in under_pressure:
            self._last_observed_pressure[sig] = now
        want: Dict[str, bool] = {}
        for sig, attr in _CONDITION_OF.items():
            last = self._last_observed_pressure.get(sig)
            want[attr] = last is not None and (now - last) < self.transition_period
        if all(getattr(node.status, a) == v for a, v in want.items()):
            return
        new = node.clone() if hasattr(node, "clone") else dataclasses.replace(node)
        new.status = dataclasses.replace(node.status, **want)
        try:
            self.store.update_node(new)
        except (Conflict, NotFound):
            pass  # raced; next pass reconciles

    # -------------------------------------------------------------- ranking

    def _active_pods(self) -> List[Pod]:
        return [p for p in self.store.snapshot_map("Pod").values()
                if p.spec.node_name == self.node_name
                and p.status.phase in ("Pending", "Running")]

    def _rank(self, pods: List[Pod], signal: str) -> List[Pod]:
        """helpers.go:1144 rankMemoryPressure ordering: exceeds-request
        first, then priority ascending, then usage-over-request descending."""
        req_key = {"memory.available": "memory",
                   "nodefs.available": "ephemeral-storage"}.get(signal)

        def metrics(p: Pod):
            usage = self.pod_stats_fn(p.meta.key()).usage_for(signal)
            req = 0
            if req_key is not None:
                req = p.resource_request().get(req_key, 0)
                if req_key == "memory":
                    req *= 1024  # canonical memory ints are KiB
            exceeds = usage > req
            return (0 if exceeds else 1, p.spec.priority, -(usage - req))

        return sorted(pods, key=metrics)

    # ------------------------------------------------------------- evict

    def _evict(self, pod: Pod, signal: str) -> bool:
        """evictPod (:570): phase Failed + reason Evicted. The workload
        controllers see a Failed pod and replace it; the scheduler places
        the replacement off this node (pressure taint)."""
        new = pod.clone()
        new.status.phase = "Failed"
        new.status.reason = REASON_EVICTED
        new.status.message = (
            f"The node was low on resource: {signal}. "
            f"Threshold: {self.thresholds.get(signal)}.")
        try:
            self.store.update_pod(new)
        except (Conflict, NotFound):
            return False
        self.evicted_total += 1
        return True

    def synchronize(self) -> Optional[str]:
        """One pass (:233): returns the evicted pod's key, or None."""
        signals = self.stats_fn()
        under = self._crossed(signals)
        self._set_conditions(under)
        if not under:
            return None
        # memory pressure outranks disk (the reference evaluates signals in
        # threshold order and picks the first starved resource to reclaim)
        signal = under[0]
        ranked = self._rank(self._active_pods(), signal)
        for pod in ranked:
            if self._evict(pod, signal):
                return pod.meta.key()
        return None
