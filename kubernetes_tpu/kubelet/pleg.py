"""Pod Lifecycle Event Generator (pkg/kubelet/pleg/generic.go).

The kubelet's syncLoop must react to container state changes it did not
cause (crashes, OOM kills, runtime restarts). The reference's GenericPLEG
relists the runtime every second, diffs each pod's container states against
the previous relist, and emits PodLifecycleEvents that syncLoopIteration
(kubelet.go:2061) consumes to trigger per-pod syncs.

This PLEG speaks the CRI surface (kubelet/cri.py FakeRuntimeService or
CRIClient over real gRPC): ListPodSandbox + ListContainers are the relist,
sandbox/container ids key the state records, and the event types mirror
pleg/generic.go's (ContainerStarted/ContainerDied/ContainerRemoved/
PodSync). Relist health doubles as the runtime liveness probe
(Healthy(), generic.go:134 — a stuck runtime shows up as relist age).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"
CONTAINER_REMOVED = "ContainerRemoved"
POD_SYNC = "PodSync"

_RUNNING = "CONTAINER_RUNNING"
_EXITED = "CONTAINER_EXITED"

# relist staleness above this marks the runtime unhealthy
# (pleg/generic.go:135 relistThreshold = 3min)
RELIST_THRESHOLD_S = 180.0


@dataclasses.dataclass(frozen=True)
class PodLifecycleEvent:
    """pleg/pleg.go PodLifecycleEvent: the pod key + what happened."""

    pod_uid: str
    pod_key: str  # "namespace/name" — the syncLoop's dirty-pod key
    type: str
    data: str = ""  # container id for container events


class GenericPLEG:
    def __init__(self, runtime, now_fn=time.monotonic):
        self.runtime = runtime
        self.now_fn = now_fn
        # sandbox id -> {container id -> state}; sandbox id -> meta
        self._containers: Dict[str, Dict[str, str]] = {}
        self._sandbox_meta: Dict[str, dict] = {}
        self._sandbox_state: Dict[str, str] = {}
        self.last_relist: Optional[float] = None
        self.events_emitted = 0

    # ------------------------------------------------------------------ util

    @staticmethod
    def _pod_key(meta: dict) -> str:
        return f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"

    def healthy(self) -> bool:
        """generic.go:134 Healthy: relist must have run recently."""
        if self.last_relist is None:
            return True  # not started yet
        return (self.now_fn() - self.last_relist) < RELIST_THRESHOLD_S

    # ---------------------------------------------------------------- relist

    def relist(self) -> List[PodLifecycleEvent]:
        """One relist pass (generic.go:190): snapshot the runtime, diff
        against the previous snapshot, emit events."""
        events: List[PodLifecycleEvent] = []
        sandboxes = {s["id"]: s for s in self.runtime.list_pod_sandbox()}
        containers_now: Dict[str, Dict[str, str]] = {}
        for sid, sbx in sandboxes.items():
            containers_now[sid] = {
                c["id"]: c["state"] for c in self.runtime.list_containers(sid)
            }
            cfg = sbx.get("config") or sbx  # FakeRuntimeService nests config
            meta = {"name": cfg.get("name", ""),
                    "namespace": cfg.get("namespace", "default"),
                    "uid": cfg.get("uid", "")}
            self._sandbox_meta[sid] = meta

        seen = set(sandboxes) | set(self._containers)
        for sid in seen:
            meta = self._sandbox_meta.get(sid, {})
            key = self._pod_key(meta)
            uid = meta.get("uid", "")
            old = self._containers.get(sid, {})
            new = containers_now.get(sid, {})
            for cid in set(old) | set(new):
                o, n = old.get(cid), new.get(cid)
                if o == n:
                    continue
                if n == _RUNNING:
                    events.append(PodLifecycleEvent(uid, key, CONTAINER_STARTED, cid))
                elif n == _EXITED and o == _RUNNING:
                    events.append(PodLifecycleEvent(uid, key, CONTAINER_DIED, cid))
                elif n is None:
                    # removed (or the whole sandbox vanished)
                    t = (CONTAINER_DIED if o == _RUNNING else CONTAINER_REMOVED)
                    events.append(PodLifecycleEvent(uid, key, t, cid))
                    if o == _RUNNING:
                        events.append(
                            PodLifecycleEvent(uid, key, CONTAINER_REMOVED, cid))
                else:
                    events.append(PodLifecycleEvent(uid, key, POD_SYNC, cid))
            # sandbox state change with no container change still syncs
            sb_old = self._sandbox_state.get(sid)
            sb_new = sandboxes[sid]["state"] if sid in sandboxes else None
            if sb_old != sb_new and not any(e.pod_key == key for e in events):
                events.append(PodLifecycleEvent(uid, key, POD_SYNC))
            if sb_new is not None:
                self._sandbox_state[sid] = sb_new
            else:
                self._sandbox_state.pop(sid, None)
                self._sandbox_meta.pop(sid, None)

        self._containers = containers_now
        self.last_relist = self.now_fn()
        self.events_emitted += len(events)
        return events
