"""Hollow kubelet (pkg/kubemark/hollow_kubelet.go:65,95 + the kubelet
control loop shape of pkg/kubelet/kubelet.go:1405 Run / :1987 syncLoop).

Lifecycle per sync:
- register: create/refresh the Node object (kubelet_node_status.go)
- heartbeat: renew the node Lease (component-helpers lease controller) and
  the NodeStatus every status period
- syncLoop: pods bound to this node transition Pending → Running after a
  configurable startup delay; pods annotated ``kubelet/terminates-after``
  complete to Succeeded once run that long; deleted pods vanish immediately
  (no graceful-termination window in the hollow runtime)
- admission: pods bound beyond the node's ``pods`` allocatable are rejected
  Failed, newest first — the hollow stand-in for eviction_manager.go
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from ..api.types import Lease, Node, ObjectMeta, Pod
from ..apiserver.store import ClusterStore, Conflict, NotFound
from ..controllers.nodelifecycle import NODE_LEASE_NAMESPACE

TERMINATES_AFTER_ANNOTATION = "kubelet/terminates-after"
DEFAULT_LEASE_DURATION = 40.0
DEFAULT_STARTUP_DELAY = 0.0


class HollowKubelet:
    def __init__(self, store: ClusterStore, node: Node,
                 now_fn=time.monotonic,
                 startup_delay: float = DEFAULT_STARTUP_DELAY,
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 runtime=None):
        self.store = store
        self.node_name = node.name()
        self._node_template = node
        self.now_fn = now_fn
        self.startup_delay = startup_delay
        self.lease_duration = lease_duration
        self._started_at: Dict[str, float] = {}  # pod key → Running since
        self.registered = False
        # CRI runtime (kubelet/cri.py FakeRuntimeService or CRIClient):
        # when present, syncPod materializes pod state through RunPodSandbox/
        # CreateContainer/StartContainer and teardown through StopPodSandbox/
        # RemovePodSandbox (kubelet.go:1502 syncPod's runtime calls)
        self.runtime = runtime
        self._sandbox_of: Dict[str, str] = {}  # pod key → sandbox id
        # PLEG (pleg/generic.go): relists the runtime and emits lifecycle
        # events; syncLoopIteration consumes them to repair pods whose
        # containers changed state underneath the kubelet (crashes, runtime
        # restarts). Only meaningful with a runtime attached.
        from .pleg import GenericPLEG

        self.pleg = GenericPLEG(runtime, now_fn=now_fn) if runtime is not None else None
        self.pleg_restarts = 0  # containers restarted off PLEG died events
        # eviction manager seam (kubelet/eviction.py EvictionManager):
        # attach via attach_eviction_manager(); run_once drives it
        self.eviction_manager = None
        # resource-manager seam (kubelet/cm.py TopologyManager over
        # CPU/Device managers): admission gate at Pending→Running
        self.topology_manager = None
        # volume-manager seam (kubelet/volume_manager.py): PVC mounts gate
        # the Pending→Running transition (WaitForAttachAndMount)
        self.volume_manager = None

    # ------------------------------------------------------------ registration

    def register(self) -> None:
        """Create the Node object (kubelet_node_status.go registerWithAPIServer)."""
        try:
            self.store.create_node(self._node_template)
        except Conflict:
            pass
        self.registered = True
        self.heartbeat()

    # ------------------------------------------------------------ heartbeats

    @property
    def _lease_key(self) -> str:
        return f"{NODE_LEASE_NAMESPACE}/{self.node_name}"

    def heartbeat(self) -> None:
        """Renew the node Lease (the cheap 10s heartbeat the nodelifecycle
        controller watches; NodeStatus stays on its slower period)."""
        now = self.now_fn()
        lease = self.store.get_lease(self._lease_key)
        if lease is None:
            self.store.create_lease(Lease(
                meta=ObjectMeta(name=self.node_name, namespace=NODE_LEASE_NAMESPACE),
                holder_identity=self.node_name,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now,
                renew_time=now,
            ))
            return
        new = dataclasses.replace(lease, renew_time=now)
        new.meta = dataclasses.replace(lease.meta)
        try:
            self.store.update_lease(new, expect_rv=lease.meta.resource_version)
        except (Conflict, NotFound):
            pass  # raced with another writer; next beat wins

    # ------------------------------------------------------------ syncLoop

    def _my_pods(self):
        return [p for p in self.store.snapshot_map("Pod").values()
                if p.spec.node_name == self.node_name]

    def _allowed_pods(self) -> int:
        node = self.store.nodes.get(self.node_name)
        if node is None:
            return 0
        return int(node.status.allocatable.get("pods", 0) or 0)

    def sync(self) -> int:
        """One syncLoopIteration over this node's pods (kubelet.go:2061);
        returns the number of pod status transitions written."""
        if not self.registered:
            self.register()
        now = self.now_fn()
        transitions = 0
        my_pods = self._my_pods()
        if self.volume_manager is not None:
            self.volume_manager.reconcile()  # once per tick; gates read cheaply
        # admission: the pods-capacity over-commit rejects newest first
        # (eviction_manager.go stand-in; scheduler normally prevents this)
        allowed = self._allowed_pods()
        if allowed and len([p for p in my_pods if p.status.phase in ("Pending", "Running")]) > allowed:
            active = sorted(
                (p for p in my_pods if p.status.phase in ("Pending", "Running")),
                key=lambda p: p.meta.resource_version,
            )
            for pod in active[allowed:]:
                self._runtime_remove(pod.meta.key())  # evicted: tear down
                self._set_phase(pod, "Failed")
                transitions += 1
            my_pods = self._my_pods()
        for pod in my_pods:
            key = pod.meta.key()
            if pod.status.phase == "Pending":
                started = self._started_at.setdefault(key, now)
                if now - started >= self.startup_delay:
                    if (self.volume_manager is not None and pod.spec.volumes
                            and not self.volume_manager.wait_for_attach_and_mount(
                                pod, reconcile=False)):
                        continue  # volumes not attached+mounted yet: retry next sync
                    if not self._cm_admit(pod):
                        transitions += 1
                        continue
                    self._runtime_start(pod)
                    self._set_phase(pod, "Running", start_time=now)
                    transitions += 1
            elif pod.status.phase == "Running":
                self._started_at.setdefault(key, now)
                if self.runtime is not None and key not in self._sandbox_of:
                    # bound pods arrive already Running (the binding
                    # subresource sets the phase); reconcile the runtime to
                    # match — the PLEG relist-and-repair direction
                    self._runtime_start(pod)
                ttl = pod.meta.annotations.get(TERMINATES_AFTER_ANNOTATION)
                if ttl is not None and now - self._started_at[key] >= float(ttl):
                    self._runtime_stop(key)
                    self._set_phase(pod, "Succeeded")
                    transitions += 1
        # forget state for pods that left the node; their sandboxes are
        # removed (the PLEG relist + garbage path, pleg/generic.go)
        live = {p.meta.key() for p in self._my_pods()}
        for key in list(self._started_at):
            if key not in live:
                del self._started_at[key]
                self._runtime_remove(key)
                if self.topology_manager is not None:
                    self.topology_manager.release(key)
        return transitions

    def _cm_admit(self, pod: Pod) -> bool:
        """Resource-manager admission (cm/topologymanager scope Admit): a
        hint-rejected pod fails with the TopologyAffinityError reason —
        the reference's UnexpectedAdmissionError path."""
        if self.topology_manager is None:
            return True
        from .cm import TopologyAffinityError

        try:
            self.topology_manager.admit(pod)
            return True
        except TopologyAffinityError as e:
            new = pod.clone()
            new.status.phase = "Failed"
            new.status.reason = "TopologyAffinityError"
            new.status.message = str(e)
            try:
                self.store.update_pod(new)
            except Exception:  # noqa: BLE001 — deleted mid-sync
                pass
            return False

    # ---------------------------------------------------------- CRI syncPod

    def _runtime_start(self, pod: Pod) -> None:
        """syncPod's create path: sandbox up, containers created+started."""
        if self.runtime is None:
            return
        sid = self.runtime.run_pod_sandbox({
            "name": pod.meta.name, "namespace": pod.meta.namespace,
            "uid": pod.meta.uid, "labels": dict(pod.meta.labels)})
        self._sandbox_of[pod.meta.key()] = sid
        for c in pod.spec.containers:
            cid = self.runtime.create_container(
                sid, {"name": c.name, "image": c.image})
            self.runtime.start_container(cid)

    def _runtime_stop(self, pod_key: str) -> None:
        """Graceful completion: containers stop first (exit 0 — a Succeeded
        pod's containers must not read as SIGKILLed), then the sandbox."""
        if self.runtime is None:
            return
        sid = self._sandbox_of.get(pod_key)
        if sid is not None:
            for c in self.runtime.list_containers(sid):
                if c["state"] == "CONTAINER_RUNNING":
                    self.runtime.stop_container(c["id"])
            self.runtime.stop_pod_sandbox(sid)

    def _runtime_remove(self, pod_key: str) -> None:
        if self.runtime is None:
            return
        sid = self._sandbox_of.pop(pod_key, None)
        if sid is not None:
            self.runtime.stop_pod_sandbox(sid)
            self.runtime.remove_pod_sandbox(sid)

    def _set_phase(self, pod: Pod, phase: str, start_time: Optional[float] = None) -> None:
        new = pod.clone()
        new.status.phase = phase
        if start_time is not None and not new.status.start_time:
            new.status.start_time = start_time
        try:
            self.store.update_pod(new)
        except NotFound:
            pass  # deleted mid-sync

    # ------------------------------------------------------------- PLEG loop

    def _process_pleg_events(self) -> int:
        """syncLoopIteration's plegCh arm (kubelet.go:2061): a ContainerDied
        for a pod that should be Running is repaired per restartPolicy
        (Always — the default; hollow pods carry no explicit policy)."""
        if self.pleg is None:
            return 0
        from .pleg import CONTAINER_DIED

        repaired = 0
        for ev in self.pleg.relist():
            if ev.type != CONTAINER_DIED:
                continue
            pod = self.store.get_pod(ev.pod_key)
            if pod is None or pod.status.phase != "Running":
                continue  # deletion teardown or completed pod: expected death
            sid = self._sandbox_of.get(ev.pod_key)
            if sid is None:
                continue
            status = self.runtime.container_status(ev.data)
            if status is not None and status["state"] == "CONTAINER_EXITED":
                self.runtime.remove_container(ev.data)
                cid = self.runtime.create_container(
                    sid, {"name": status.get("name", "c"),
                          "image": status.get("image", "")})
                self.runtime.start_container(cid)
                repaired += 1
                self.pleg_restarts += 1
        return repaired

    def attach_eviction_manager(self, mgr) -> None:
        self.eviction_manager = mgr

    def run_once(self) -> int:
        """register + heartbeat + PLEG relist + eviction pass + sync —
        one full kubelet tick."""
        if not self.registered:
            self.register()
        self.heartbeat()
        self._process_pleg_events()
        if self.eviction_manager is not None:
            self.eviction_manager.synchronize()
        return self.sync()
