"""Kubelet volume manager + status manager (pkg/kubelet/volumemanager/,
pkg/kubelet/status/ — the last L4c internals).

VolumeManager keeps the desired-state-of-world (every PVC volume of every
pod bound to this node) reconciled against the actual-state-of-world
(what is "mounted"): a pod's volumes must be attached (VolumeAttachment
written by the attachdetach controller) and mounted before the pod may
run (volumemanager/volume_manager.go WaitForAttachAndMount); pods leaving
the node unmount their volumes (reconciler.go). The mount operation
itself is environment — the state machine and the run-gate are the parity
surface.

StatusManager (status/status_manager.go) is the kubelet's write-through
cache for pod status: versioned per-pod status with no-op suppression, so
the API server sees each distinct status exactly once.
"""

from __future__ import annotations

from typing import Dict, Set, Tuple


class VolumeManager:
    def __init__(self, store, node_name: str, require_attach: bool = True):
        self.store = store
        self.node_name = node_name
        # in-tree PVC volumes "mount" only after the attachdetach controller
        # wrote the VolumeAttachment (False = treat attach as instant, the
        # kubemark mode)
        self.require_attach = require_attach
        self.mounted: Set[Tuple[str, str]] = set()  # (pod key, pvc name)
        self.mounts_total = 0
        self.unmounts_total = 0

    # ------------------------------------------------------------ desired

    def _desired(self) -> Set[Tuple[str, str]]:
        out = set()
        for pod in self.store.snapshot_map("Pod").values():
            if pod.spec.node_name != self.node_name:
                continue
            if pod.status.phase in ("Succeeded", "Failed"):
                continue
            for claim in pod.spec.volumes:
                out.add((pod.meta.key(), claim))
        return out

    def _attached(self, pod_ns: str, claim: str) -> bool:
        pvc = self.store.pvcs.get(f"{pod_ns}/{claim}")
        if pvc is None or not pvc.bound_pv:
            return False
        if not self.require_attach:
            return True
        for va in self.store.volume_attachments.values():
            if va.pv_name == pvc.bound_pv and va.node_name == self.node_name \
                    and va.attached:
                return True
        return False

    # ---------------------------------------------------------- reconcile

    def reconcile(self) -> int:
        """One reconciler pass (reconciler.go:159): mount newly-desired
        volumes whose PV is attached, unmount no-longer-desired ones.
        Returns state transitions."""
        desired = self._desired()
        changes = 0
        for key in list(self.mounted - desired):
            self.mounted.discard(key)
            self.unmounts_total += 1
            changes += 1
        for pod_key, claim in desired - self.mounted:
            ns = pod_key.split("/", 1)[0]
            if self._attached(ns, claim):
                self.mounted.add((pod_key, claim))
                self.mounts_total += 1
                changes += 1
        return changes

    def wait_for_attach_and_mount(self, pod, reconcile: bool = True) -> bool:
        """volume_manager.go:368 WaitForAttachAndMount, non-blocking form:
        True when every volume of ``pod`` is mounted (the syncLoop's
        run-gate; the caller retries next sync instead of blocking).
        ``reconcile=False`` makes this a pure read of the mounted set —
        the syncLoop reconciles ONCE per tick and gates each pod cheaply
        (a per-pod reconcile would be O(pending x pods x attachments))."""
        if reconcile:
            self.reconcile()
        key = pod.meta.key()
        return all((key, claim) in self.mounted for claim in pod.spec.volumes)


class StatusManager:
    """status/status_manager.go: per-pod versioned status cache with no-op
    suppression — SetPodStatus bumps a version only when the status
    actually changed; syncPod writes only unsynced versions."""

    def __init__(self, store):
        self.store = store
        self._versions: Dict[str, int] = {}
        self._synced: Dict[str, int] = {}
        self._status: Dict[str, tuple] = {}
        self.api_writes = 0

    @staticmethod
    def _sig(status) -> tuple:
        return (status.phase, status.reason, status.message,
                status.nominated_node_name)

    def set_pod_status(self, pod, status) -> None:
        key = pod.meta.key()
        sig = self._sig(status)
        if self._status.get(key) == sig:
            return  # no-op update suppressed
        self._status[key] = sig
        self._versions[key] = self._versions.get(key, 0) + 1

    def sync(self) -> int:
        """Write every unsynced status through the API; returns writes."""
        wrote = 0
        for key, version in list(self._versions.items()):
            if self._synced.get(key) == version:
                continue
            pod = self.store.get_pod(key)
            if pod is None:
                self._versions.pop(key, None)
                self._synced.pop(key, None)
                self._status.pop(key, None)
                continue
            phase, reason, message, nominated = self._status[key]
            new = pod.clone()
            new.status.phase = phase
            new.status.reason = reason
            new.status.message = message
            new.status.nominated_node_name = nominated
            try:
                self.store.update_pod(new)
                wrote += 1
                self.api_writes += 1
                self._synced[key] = version
            except Exception:  # noqa: BLE001 — conflict: retry next sync
                pass
        return wrote
