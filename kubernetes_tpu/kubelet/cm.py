"""Kubelet container/resource managers (pkg/kubelet/cm/ — the last L4c
internals gap: cpumanager, devicemanager, topologymanager).

Reduced to the decision surfaces that change pod outcomes:

  * ``CPUManager`` (cm/cpumanager/policy_static.go): the static policy
    gives GUARANTEED pods with integer CPU requests exclusive cores drawn
    from the shared pool, preferring cores packed on one NUMA node;
    everything else runs on the shared pool. Assignments checkpoint
    through the checksummed CheckpointManager (cpu_manager_state file) so
    they survive kubelet restarts.
  * ``DeviceManager`` (cm/devicemanager/manager.go): device plugins
    register allocatable device IDs per extended resource; pods requesting
    the resource get specific device IDs allocated, checkpointed
    (kubelet_internal_checkpoint), and released on pod removal.
  * ``TopologyManager`` (cm/topologymanager/): merges the NUMA affinity
    hints the other managers provide; policies none / best-effort /
    restricted / single-numa-node; restricted+single-numa reject pods
    whose merged hint is not preferred (the TopologyAffinityError path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from .checkpoint import CheckpointManager

CPU_STATE_CHECKPOINT = "cpu_manager_state"
DEVICE_STATE_CHECKPOINT = "kubelet_internal_checkpoint"

POLICY_NONE = "none"
POLICY_BEST_EFFORT = "best-effort"
POLICY_RESTRICTED = "restricted"
POLICY_SINGLE_NUMA = "single-numa-node"


class TopologyAffinityError(Exception):
    """topologymanager admission failure (scope.go Admit): the pod's
    resource hints cannot be satisfied under the configured policy."""


@dataclasses.dataclass(frozen=True)
class TopologyHint:
    """cm/topologymanager/topology_hints.go: a NUMA-node set that can
    satisfy a request, and whether it is the minimal (preferred) one."""

    numa_nodes: Tuple[int, ...]
    preferred: bool


def _is_guaranteed_integer_cpu(pod: Pod) -> Optional[int]:
    """policy_static.go: exclusive cores only for Guaranteed QoS pods whose
    cpu request is a whole number of cores (requests == limits)."""
    from ..api import resource as resource_api

    total = 0
    for c in pod.spec.containers:
        req = c.requests.get("cpu")
        if req is None:
            return None
        lim = c.limits.get("cpu", req)
        r = resource_api.canonical("cpu", req)
        if r != resource_api.canonical("cpu", lim) or r % 1000:
            return None
        total += r // 1000
    return total or None


class CPUManager:
    def __init__(self, checkpoints: CheckpointManager,
                 cores_per_numa: Sequence[int] = (4, 4)):
        """``cores_per_numa``: core count per NUMA node; core ids are
        assigned sequentially (node 0: 0..n-1, node 1: n.., ...)."""
        self.checkpoints = checkpoints
        self.numa_of: Dict[int, int] = {}
        core = 0
        for node, n in enumerate(cores_per_numa):
            for _ in range(n):
                self.numa_of[core] = node
                core += 1
        self.assignments: Dict[str, List[int]] = {}  # pod key -> cores
        self._restore()

    # ------------------------------------------------------------ state

    def _restore(self) -> None:
        doc = self.checkpoints.get_checkpoint(CPU_STATE_CHECKPOINT)
        if doc:
            self.assignments = {k: list(v) for k, v in doc["entries"].items()}

    def _persist(self) -> None:
        self.checkpoints.create_checkpoint(
            CPU_STATE_CHECKPOINT, {"entries": self.assignments})

    # ------------------------------------------------------------ pool

    def _free_cores(self) -> List[int]:
        used = {c for cores in self.assignments.values() for c in cores}
        return [c for c in sorted(self.numa_of) if c not in used]

    def topology_hints(self, pod: Pod) -> Optional[List[TopologyHint]]:
        """Per-NUMA feasibility for the pod's exclusive-core demand; None =
        no exclusive demand (no hint, topologymanager treats as don't-care)."""
        want = _is_guaranteed_integer_cpu(pod)
        if want is None:
            return None
        free = self._free_cores()
        by_numa: Dict[int, int] = {}
        for c in free:
            by_numa[self.numa_of[c]] = by_numa.get(self.numa_of[c], 0) + 1
        hints = [TopologyHint((node,), True)
                 for node, n in sorted(by_numa.items()) if n >= want]
        if not hints and len(free) >= want:
            hints.append(TopologyHint(tuple(sorted(by_numa)), False))
        return hints

    def allocate(self, pod: Pod, hint: Optional[TopologyHint] = None) -> List[int]:
        """Assign exclusive cores (empty list = shared pool). Prefers cores
        on the hint's NUMA nodes, packing one node first."""
        key = pod.meta.key()
        if key in self.assignments:
            return self.assignments[key]
        want = _is_guaranteed_integer_cpu(pod)
        if want is None:
            return []
        free = self._free_cores()
        if hint is not None:
            preferred = [c for c in free if self.numa_of[c] in hint.numa_nodes]
            free = preferred + [c for c in free if c not in preferred]
        if len(free) < want:
            raise TopologyAffinityError(
                f"not enough exclusive cores: want {want}, free {len(free)}")
        cores = free[:want]
        self.assignments[key] = cores
        self._persist()
        return cores

    def release(self, pod_key: str) -> None:
        if self.assignments.pop(pod_key, None) is not None:
            self._persist()


class DeviceManager:
    def __init__(self, checkpoints: CheckpointManager):
        self.checkpoints = checkpoints
        # resource -> {device id -> numa node}
        self.registry: Dict[str, Dict[str, int]] = {}
        # pod key -> {resource -> [device ids]}
        self.allocations: Dict[str, Dict[str, List[str]]] = {}
        self._restore()

    def _restore(self) -> None:
        doc = self.checkpoints.get_checkpoint(DEVICE_STATE_CHECKPOINT)
        if doc:
            self.allocations = {
                k: {r: list(ids) for r, ids in v.items()}
                for k, v in doc["pod_devices"].items()}

    def _persist(self) -> None:
        self.checkpoints.create_checkpoint(
            DEVICE_STATE_CHECKPOINT, {"pod_devices": self.allocations})

    # ---------------------------------------------------------- plugins

    def register_plugin(self, resource: str, devices: Dict[str, int]) -> None:
        """Device plugin registration (ListAndWatch's device set): device
        id -> NUMA node."""
        self.registry[resource] = dict(devices)

    def _free_devices(self, resource: str) -> List[str]:
        used = {d for alloc in self.allocations.values()
                for r, ids in alloc.items() if r == resource for d in ids}
        return [d for d in sorted(self.registry.get(resource, ()))
                if d not in used]

    def _demand(self, pod: Pod) -> Dict[str, int]:
        from ..api import resource as resource_api

        out: Dict[str, int] = {}
        for c in pod.spec.containers:
            for res, q in c.requests.items():
                if res in self.registry:
                    out[res] = out.get(res, 0) + resource_api.canonical(res, q)
        return out

    def topology_hints(self, pod: Pod) -> Optional[List[TopologyHint]]:
        demand = self._demand(pod)
        if not demand:
            return None
        hints: Optional[set] = None
        for res, want in demand.items():
            free = self._free_devices(res)
            by_numa: Dict[int, int] = {}
            for d in free:
                node = self.registry[res][d]
                by_numa[node] = by_numa.get(node, 0) + 1
            mine = {(node,) for node, n in by_numa.items() if n >= want}
            hints = mine if hints is None else (hints & mine)
        out = [TopologyHint(h, True) for h in sorted(hints or ())]
        if not out and all(len(self._free_devices(r)) >= w
                           for r, w in demand.items()):
            out.append(TopologyHint(tuple(sorted(
                {n for r in demand for n in self.registry[r].values()})), False))
        return out

    def allocate(self, pod: Pod, hint: Optional[TopologyHint] = None
                 ) -> Dict[str, List[str]]:
        key = pod.meta.key()
        if key in self.allocations:
            return self.allocations[key]
        demand = self._demand(pod)
        if not demand:
            return {}
        alloc: Dict[str, List[str]] = {}
        for res, want in demand.items():
            free = self._free_devices(res)
            if hint is not None:
                preferred = [d for d in free
                             if self.registry[res][d] in hint.numa_nodes]
                free = preferred + [d for d in free if d not in preferred]
            if len(free) < want:
                raise TopologyAffinityError(
                    f"insufficient {res}: want {want}, free {len(free)}")
            alloc[res] = free[:want]
        self.allocations[key] = alloc
        self._persist()
        return alloc

    def release(self, pod_key: str) -> None:
        if self.allocations.pop(pod_key, None) is not None:
            self._persist()


class TopologyManager:
    """cm/topologymanager/scope_container.go Admit, reduced to pod scope:
    gather each provider's hints, merge (bitwise-AND of NUMA sets across
    providers, narrowest preferred wins), allocate under the merged hint."""

    def __init__(self, policy: str = POLICY_BEST_EFFORT,
                 providers: Sequence[object] = ()):
        assert policy in (POLICY_NONE, POLICY_BEST_EFFORT,
                          POLICY_RESTRICTED, POLICY_SINGLE_NUMA)
        self.policy = policy
        self.providers = list(providers)

    def _merge(self, all_hints: List[List[TopologyHint]]) -> TopologyHint:
        """topology_manager.go mergeProvidersHints: cross-product AND; the
        best (fewest NUMA nodes, preferred) non-empty intersection wins."""
        merged: Optional[TopologyHint] = None
        from itertools import product

        for combo in product(*all_hints):
            nodes = None
            preferred = all(h.preferred for h in combo)
            for h in combo:
                s = set(h.numa_nodes)
                nodes = s if nodes is None else (nodes & s)
            if not nodes:
                continue
            cand = TopologyHint(tuple(sorted(nodes)), preferred)
            if merged is None or (cand.preferred, -len(cand.numa_nodes)) > \
                    (merged.preferred, -len(merged.numa_nodes)):
                merged = cand
        return merged if merged is not None else TopologyHint((), False)

    def _allocate_all(self, pod: Pod, hint: Optional[TopologyHint]) -> None:
        """Allocate across providers with ROLLBACK: a later provider's
        failure must release what earlier providers already persisted, or
        the Failed pod (which stays in the store) pins cores/devices
        forever and later pods are spuriously rejected."""
        done = []
        try:
            for p in self.providers:
                p.allocate(pod, hint)
                done.append(p)
        except TopologyAffinityError:
            for p in done:
                p.release(pod.meta.key())
            raise

    def admit(self, pod: Pod) -> Optional[TopologyHint]:
        """Admit + allocate; raises TopologyAffinityError on rejection.
        Returns the merged hint (None when no provider had demand)."""
        if self.policy == POLICY_NONE:
            self._allocate_all(pod, None)
            return None
        all_hints = [h for p in self.providers
                     if (h := p.topology_hints(pod)) is not None]
        if not all_hints:
            return None
        if any(not hs for hs in all_hints):
            raise TopologyAffinityError("a provider has no feasible placement")
        merged = self._merge(all_hints)
        if not merged.numa_nodes:
            raise TopologyAffinityError("providers' NUMA hints do not intersect")
        if self.policy == POLICY_SINGLE_NUMA and (
                not merged.preferred or len(merged.numa_nodes) != 1):
            raise TopologyAffinityError(
                f"single-numa-node policy rejects hint {merged.numa_nodes}")
        if self.policy == POLICY_RESTRICTED and not merged.preferred:
            raise TopologyAffinityError(
                f"restricted policy rejects non-preferred hint {merged.numa_nodes}")
        self._allocate_all(pod, merged)
        return merged

    def release(self, pod_key: str) -> None:
        for p in self.providers:
            p.release(pod_key)
