"""CRI seam: the kubelet ⇄ container-runtime gRPC boundary
(staging/src/k8s.io/cri-api/pkg/apis/runtime/v1/api.proto; remote client
pkg/kubelet/cri/remote/).

Three pieces:
  * ``FakeRuntimeService`` — an in-process runtime holding the
    sandbox/container state machines (the kubemark hollow-kubelet injected
    fake CRI, pkg/kubemark/hollow_kubelet.go:95).
  * ``serve_cri``/``CRIClient`` — real gRPC bindings over
    native/ktpu_cri.proto (generic method handlers, like the device
    service: grpc_tools is absent, protoc compiles the messages on demand).
  * ``HollowKubelet`` integration — pass ``runtime=`` (fake or client) and
    the syncLoop materializes pod phases through RunPodSandbox /
    CreateContainer / StartContainer / StopPodSandbox instead of bare
    status writes (kubelet.go:1502 syncPod's runtime calls).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_PROTO_DIR = os.path.join(_REPO_ROOT, "native")
_PROTO = os.path.join(_PROTO_DIR, "ktpu_cri.proto")
_BUILD_DIR = os.path.join(_PROTO_DIR, "build")
_PB2 = os.path.join(_BUILD_DIR, "ktpu_cri_pb2.py")

_pb2 = None
_pb2_lock = threading.Lock()

SERVICE = "ktpu.cri.v1.RuntimeService"
RUNTIME_NAME = "ktpu-hollow"
RUNTIME_VERSION = "v1"


def pb2_available() -> bool:
    """True when pb2() will succeed (the CRI messages are not vendored
    yet — gRPC-path tests skip with a reason instead of erroring when
    the on-demand build cannot happen)."""
    from ..utils.protoc import build_available

    return build_available(_pb2, _PB2, _PROTO)


def pb2():
    global _pb2
    if _pb2 is not None:
        return _pb2
    with _pb2_lock:
        if _pb2 is not None:
            return _pb2
        if (not os.path.exists(_PB2)
                or os.path.getmtime(_PB2) < os.path.getmtime(_PROTO)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            subprocess.run(
                ["protoc", f"--python_out={_BUILD_DIR}", "-I", _PROTO_DIR, _PROTO],
                check=True, capture_output=True, timeout=60)
        spec = importlib.util.spec_from_file_location("ktpu_cri_pb2", _PB2)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _pb2 = mod
        return _pb2


class FakeRuntimeService:
    """Sandbox/container state machines behind the CRI method surface.
    Method names and transitions mirror the reference service
    (api.proto rpcs); ids are deterministic per (namespace, name)."""

    def __init__(self, now_fn=time.monotonic):
        self.now_fn = now_fn
        self._lock = threading.Lock()
        self.sandboxes: Dict[str, dict] = {}
        self.containers: Dict[str, dict] = {}
        self.images: Dict[str, dict] = {}
        self.calls: List[str] = []  # rpc journal (test observability)

    def _note(self, rpc: str) -> None:
        self.calls.append(rpc)

    # -- runtime

    def version(self) -> dict:
        self._note("Version")
        return {"version": "0.1.0", "runtime_name": RUNTIME_NAME,
                "runtime_version": RUNTIME_VERSION}

    def run_pod_sandbox(self, config: dict) -> str:
        self._note("RunPodSandbox")
        sid = f"sbx-{config.get('namespace', 'default')}-{config.get('name', '')}"
        with self._lock:
            self.sandboxes[sid] = {
                "id": sid, "config": dict(config), "state": "SANDBOX_READY",
                "created_at": int(self.now_fn() * 1e9),
            }
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        self._note("StopPodSandbox")
        with self._lock:
            sb = self.sandboxes.get(sandbox_id)
            if sb is not None:
                sb["state"] = "SANDBOX_NOTREADY"
            for c in self.containers.values():
                if (c["config"].get("pod_sandbox_id") == sandbox_id
                        and c["state"] == "CONTAINER_RUNNING"):
                    c["state"] = "CONTAINER_EXITED"
                    c["finished_at"] = int(self.now_fn() * 1e9)
                    c["exit_code"] = 137

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self._note("RemovePodSandbox")
        with self._lock:
            self.sandboxes.pop(sandbox_id, None)
            for cid in [c["id"] for c in self.containers.values()
                        if c["config"].get("pod_sandbox_id") == sandbox_id]:
                self.containers.pop(cid, None)

    def list_pod_sandbox(self) -> List[dict]:
        self._note("ListPodSandbox")
        with self._lock:
            return [dict(s) for s in self.sandboxes.values()]

    def pod_sandbox_status(self, sandbox_id: str) -> Optional[dict]:
        self._note("PodSandboxStatus")
        with self._lock:
            s = self.sandboxes.get(sandbox_id)
            return dict(s) if s else None

    # -- containers

    def create_container(self, sandbox_id: str, config: dict) -> str:
        self._note("CreateContainer")
        cid = f"ctr-{sandbox_id}-{config.get('name', '')}"
        with self._lock:
            self.containers[cid] = {
                "id": cid,
                "config": dict(config, pod_sandbox_id=sandbox_id),
                "state": "CONTAINER_CREATED",
                "created_at": int(self.now_fn() * 1e9),
                "started_at": 0, "finished_at": 0, "exit_code": 0,
            }
        image = config.get("image", "")
        if image:
            self.pull_image(image)
        return cid

    def start_container(self, container_id: str) -> None:
        self._note("StartContainer")
        with self._lock:
            c = self.containers.get(container_id)
            if c is None:
                raise KeyError(container_id)
            c["state"] = "CONTAINER_RUNNING"
            c["started_at"] = int(self.now_fn() * 1e9)

    def stop_container(self, container_id: str, timeout: int = 0) -> None:
        self._note("StopContainer")
        with self._lock:
            c = self.containers.get(container_id)
            if c is not None and c["state"] == "CONTAINER_RUNNING":
                c["state"] = "CONTAINER_EXITED"
                c["finished_at"] = int(self.now_fn() * 1e9)
                c["exit_code"] = 0

    def remove_container(self, container_id: str) -> None:
        self._note("RemoveContainer")
        with self._lock:
            self.containers.pop(container_id, None)

    def list_containers(self, sandbox_id: str = "") -> List[dict]:
        self._note("ListContainers")
        with self._lock:
            return [dict(c) for c in self.containers.values()
                    if not sandbox_id
                    or c["config"].get("pod_sandbox_id") == sandbox_id]

    def container_status(self, container_id: str) -> Optional[dict]:
        self._note("ContainerStatus")
        with self._lock:
            c = self.containers.get(container_id)
            return dict(c) if c else None

    # -- images

    def pull_image(self, image: str) -> str:
        self._note("PullImage")
        with self._lock:
            self.images.setdefault(image, {"id": f"img-{image}", "size": 1 << 20})
        return f"img-{image}"

    def list_images(self) -> List[dict]:
        self._note("ListImages")
        with self._lock:
            return [{"id": v["id"], "repo_tags": [k], "size": v["size"]}
                    for k, v in self.images.items()]

    def remove_image(self, image: str) -> None:
        self._note("RemoveImage")
        with self._lock:
            self.images.pop(image, None)


# ------------------------------------------------------------------ transport

_SANDBOX_STATES = ("SANDBOX_READY", "SANDBOX_NOTREADY")
_CONTAINER_STATES = ("CONTAINER_CREATED", "CONTAINER_RUNNING", "CONTAINER_EXITED")


def _sandbox_to_proto(p, s: dict):
    return p.PodSandbox(
        id=s["id"],
        config=p.PodSandboxConfig(**{
            k: v for k, v in s["config"].items()
            if k in ("name", "namespace", "uid", "labels", "annotations")}),
        state=_SANDBOX_STATES.index(s["state"]),
        created_at=s["created_at"])


def _container_to_proto(p, c: dict):
    cfg = c["config"]
    return p.Container(
        id=c["id"],
        config=p.ContainerConfig(name=cfg.get("name", ""),
                                 image=cfg.get("image", ""),
                                 pod_sandbox_id=cfg.get("pod_sandbox_id", "")),
        state=_CONTAINER_STATES.index(c["state"]),
        created_at=c["created_at"], started_at=c["started_at"],
        finished_at=c["finished_at"], exit_code=c["exit_code"])


def serve_cri(service: FakeRuntimeService, port: int = 0):
    """Bind the runtime to a localhost gRPC server; returns (server, port)."""
    import grpc
    from concurrent import futures

    p = pb2()

    def h(req_cls, resp_builder):
        return grpc.unary_unary_rpc_method_handler(
            lambda request, _ctx: resp_builder(request),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString())

    handlers = grpc.method_handlers_generic_handler(SERVICE, {
        "Version": h(p.VersionRequest, lambda r: p.VersionResponse(
            **service.version())),
        "RunPodSandbox": h(p.RunPodSandboxRequest, lambda r: p.RunPodSandboxResponse(
            pod_sandbox_id=service.run_pod_sandbox({
                "name": r.config.name, "namespace": r.config.namespace,
                "uid": r.config.uid, "labels": dict(r.config.labels),
                "annotations": dict(r.config.annotations)}))),
        "StopPodSandbox": h(p.StopPodSandboxRequest, lambda r: (
            service.stop_pod_sandbox(r.pod_sandbox_id), p.StopPodSandboxResponse())[1]),
        "RemovePodSandbox": h(p.RemovePodSandboxRequest, lambda r: (
            service.remove_pod_sandbox(r.pod_sandbox_id), p.RemovePodSandboxResponse())[1]),
        "ListPodSandbox": h(p.ListPodSandboxRequest, lambda r: p.ListPodSandboxResponse(
            items=[_sandbox_to_proto(p, s) for s in service.list_pod_sandbox()])),
        "PodSandboxStatus": h(p.PodSandboxStatusRequest, lambda r: p.PodSandboxStatusResponse(
            status=_sandbox_to_proto(p, service.pod_sandbox_status(r.pod_sandbox_id) or
                                     {"id": "", "config": {}, "state": "SANDBOX_NOTREADY",
                                      "created_at": 0}))),
        "CreateContainer": h(p.CreateContainerRequest, lambda r: p.CreateContainerResponse(
            container_id=service.create_container(r.pod_sandbox_id, {
                "name": r.config.name, "image": r.config.image}))),
        "StartContainer": h(p.StartContainerRequest, lambda r: (
            service.start_container(r.container_id), p.StartContainerResponse())[1]),
        "StopContainer": h(p.StopContainerRequest, lambda r: (
            service.stop_container(r.container_id, r.timeout), p.StopContainerResponse())[1]),
        "RemoveContainer": h(p.RemoveContainerRequest, lambda r: (
            service.remove_container(r.container_id), p.RemoveContainerResponse())[1]),
        "ListContainers": h(p.ListContainersRequest, lambda r: p.ListContainersResponse(
            containers=[_container_to_proto(p, c)
                        for c in service.list_containers(r.pod_sandbox_id)])),
        "ContainerStatus": h(p.ContainerStatusRequest, lambda r: p.ContainerStatusResponse(
            status=_container_to_proto(p, service.container_status(r.container_id) or {
                "id": "", "config": {}, "state": "CONTAINER_EXITED",
                "created_at": 0, "started_at": 0, "finished_at": 0, "exit_code": 0}))),
        "PullImage": h(p.PullImageRequest, lambda r: p.PullImageResponse(
            image_ref=service.pull_image(r.image.image))),
        "ListImages": h(p.ListImagesRequest, lambda r: p.ListImagesResponse(
            images=[p.Image(id=i["id"], repo_tags=i["repo_tags"], size=i["size"])
                    for i in service.list_images()])),
        "RemoveImage": h(p.RemoveImageRequest, lambda r: (
            service.remove_image(r.image.image), p.RemoveImageResponse())[1]),
    })
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handlers,))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


class CRIClient:
    """Remote runtime client (pkg/kubelet/cri/remote/remote_runtime.go):
    the same python surface as FakeRuntimeService, over the wire."""

    def __init__(self, endpoint: str):
        import grpc

        p = pb2()
        self._p = p
        self._channel = grpc.insecure_channel(endpoint)

        def rpc(name, req_cls, resp_cls):
            return self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)

        self._version = rpc("Version", p.VersionRequest, p.VersionResponse)
        self._run = rpc("RunPodSandbox", p.RunPodSandboxRequest, p.RunPodSandboxResponse)
        self._stop_sb = rpc("StopPodSandbox", p.StopPodSandboxRequest, p.StopPodSandboxResponse)
        self._rm_sb = rpc("RemovePodSandbox", p.RemovePodSandboxRequest, p.RemovePodSandboxResponse)
        self._list_sb = rpc("ListPodSandbox", p.ListPodSandboxRequest, p.ListPodSandboxResponse)
        self._create = rpc("CreateContainer", p.CreateContainerRequest, p.CreateContainerResponse)
        self._start = rpc("StartContainer", p.StartContainerRequest, p.StartContainerResponse)
        self._stop_c = rpc("StopContainer", p.StopContainerRequest, p.StopContainerResponse)
        self._list_c = rpc("ListContainers", p.ListContainersRequest, p.ListContainersResponse)
        self._images = rpc("ListImages", p.ListImagesRequest, p.ListImagesResponse)
        self._sb_status = rpc("PodSandboxStatus", p.PodSandboxStatusRequest,
                              p.PodSandboxStatusResponse)
        self._c_status = rpc("ContainerStatus", p.ContainerStatusRequest,
                             p.ContainerStatusResponse)
        self._rm_c = rpc("RemoveContainer", p.RemoveContainerRequest,
                         p.RemoveContainerResponse)
        self._pull = rpc("PullImage", p.PullImageRequest, p.PullImageResponse)
        self._rm_img = rpc("RemoveImage", p.RemoveImageRequest, p.RemoveImageResponse)

    def version(self) -> dict:
        r = self._version(self._p.VersionRequest())
        return {"version": r.version, "runtime_name": r.runtime_name,
                "runtime_version": r.runtime_version}

    def run_pod_sandbox(self, config: dict) -> str:
        return self._run(self._p.RunPodSandboxRequest(
            config=self._p.PodSandboxConfig(
                name=config.get("name", ""), namespace=config.get("namespace", ""),
                uid=config.get("uid", ""), labels=config.get("labels") or {},
            ))).pod_sandbox_id

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        self._stop_sb(self._p.StopPodSandboxRequest(pod_sandbox_id=sandbox_id))

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self._rm_sb(self._p.RemovePodSandboxRequest(pod_sandbox_id=sandbox_id))

    def list_pod_sandbox(self) -> list:
        return [{"id": s.id, "state": _SANDBOX_STATES[s.state],
                 "config": {"name": s.config.name, "namespace": s.config.namespace}}
                for s in self._list_sb(self._p.ListPodSandboxRequest()).items]

    def create_container(self, sandbox_id: str, config: dict) -> str:
        return self._create(self._p.CreateContainerRequest(
            pod_sandbox_id=sandbox_id,
            config=self._p.ContainerConfig(name=config.get("name", ""),
                                           image=config.get("image", "")),
        )).container_id

    def start_container(self, container_id: str) -> None:
        self._start(self._p.StartContainerRequest(container_id=container_id))

    def stop_container(self, container_id: str, timeout: int = 0) -> None:
        self._stop_c(self._p.StopContainerRequest(container_id=container_id,
                                                  timeout=timeout))

    def list_containers(self, sandbox_id: str = "") -> list:
        return [{"id": c.id, "state": _CONTAINER_STATES[c.state],
                 "config": {"name": c.config.name, "image": c.config.image,
                            "pod_sandbox_id": c.config.pod_sandbox_id}}
                for c in self._list_c(
                    self._p.ListContainersRequest(pod_sandbox_id=sandbox_id)).containers]

    def list_images(self) -> list:
        return [{"id": i.id, "repo_tags": list(i.repo_tags), "size": i.size}
                for i in self._images(self._p.ListImagesRequest()).images]

    def pod_sandbox_status(self, sandbox_id: str) -> Optional[dict]:
        s = self._sb_status(self._p.PodSandboxStatusRequest(
            pod_sandbox_id=sandbox_id)).status
        if not s.id:
            return None
        return {"id": s.id, "state": _SANDBOX_STATES[s.state],
                "config": {"name": s.config.name, "namespace": s.config.namespace}}

    def container_status(self, container_id: str) -> Optional[dict]:
        c = self._c_status(self._p.ContainerStatusRequest(
            container_id=container_id)).status
        if not c.id:
            return None
        return {"id": c.id, "state": _CONTAINER_STATES[c.state],
                "exit_code": c.exit_code,
                "config": {"name": c.config.name, "image": c.config.image,
                           "pod_sandbox_id": c.config.pod_sandbox_id}}

    def remove_container(self, container_id: str) -> None:
        self._rm_c(self._p.RemoveContainerRequest(container_id=container_id))

    def pull_image(self, image: str) -> str:
        return self._pull(self._p.PullImageRequest(
            image=self._p.ImageSpec(image=image))).image_ref

    def remove_image(self, image: str) -> None:
        self._rm_img(self._p.RemoveImageRequest(
            image=self._p.ImageSpec(image=image)))

    def close(self) -> None:
        self._channel.close()
