"""Checkpoint manager (pkg/kubelet/checkpointmanager/checkpoint_manager.go:36,56).

The one reference component with durable local state: checksummed files so
device-manager allocations survive kubelet restarts. Same contract here:
JSON payload + CRC; a corrupt or tampered file fails verification on read.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional


class CorruptCheckpointError(Exception):
    """Checksum mismatch (errors.ErrCorruptCheckpoint)."""


class CheckpointManager:
    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)

    def _path(self, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise ValueError(f"invalid checkpoint name {name!r}")
        return os.path.join(self.dir, name)

    def create_checkpoint(self, name: str, data: dict) -> None:
        """Atomic write: payload + crc32, tmp-then-rename
        (checkpoint_manager.go CreateCheckpoint)."""
        payload = json.dumps(data, sort_keys=True)
        doc = {"data": payload, "checksum": zlib.crc32(payload.encode())}
        tmp = self._path(name) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self._path(name))

    def get_checkpoint(self, name: str) -> Optional[dict]:
        """Read + verify; None when absent, CorruptCheckpointError on
        checksum mismatch (GetCheckpoint + VerifyChecksum)."""
        try:
            with open(self._path(name)) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError) as e:
            raise CorruptCheckpointError(str(e)) from e
        payload = doc.get("data", "")
        if zlib.crc32(payload.encode()) != doc.get("checksum"):
            raise CorruptCheckpointError(name)
        return json.loads(payload)

    def remove_checkpoint(self, name: str) -> None:
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            pass

    def list_checkpoints(self) -> list:
        return sorted(
            n for n in os.listdir(self.dir) if not n.endswith(".tmp")
        )
