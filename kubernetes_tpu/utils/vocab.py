"""String→id vocabularies for the device encoding.

The device never sees strings: label keys, (key,value) pairs, taints, ports,
images, extended-resource names and topology keys are interned host-side into
dense integer ids.  Ids are append-only and stable for the life of a Vocab, so
device-resident tensors indexed by id never need re-encoding when new strings
appear (they only need wider padding, handled by capacity doubling in the
backend).

Id 0 is reserved as "absent/invalid" in every vocab, which lets 0-padded
tensors be self-masking.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List, Optional


class Vocab:
    """Intern table. Id 0 is reserved; real ids start at 1.

    Ids are stable for as long as an item stays interned. ``release`` frees
    an id back to an internal free-list, so id space stays BOUNDED under
    churn (the elastic-cluster contract: removed nodes must not consume
    vocab forever). A release invalidates every cached encoding holding the
    freed id — the owner (ClusterEncoder) clears its template caches, and
    live rows never reference a freed id because reference-counted callers
    only release at refcount zero."""

    def __init__(self, name: str = ""):
        self.name = name
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = [None]  # index 0 reserved
        self._free: List[int] = []
        self.releases = 0

    def __len__(self) -> int:
        return len(self._items)

    def live(self) -> int:
        """Number of currently-interned items (capacity minus holes)."""
        return len(self._ids)

    def id(self, item: Hashable) -> int:
        """Intern ``item``, returning its stable id (allocating if new;
        freed ids are reused before the table grows)."""
        i = self._ids.get(item)
        if i is None:
            if self._free:
                i = self._free.pop()
                self._items[i] = item
            else:
                i = len(self._items)
                self._items.append(item)
            self._ids[item] = i
        return i

    def release(self, item: Hashable) -> Optional[int]:
        """Free ``item``'s id for reuse; returns the freed id (None if the
        item was never interned). Callers own the cache-invalidation
        contract described in the class docstring."""
        i = self._ids.pop(item, None)
        if i is not None:
            self._items[i] = None
            self._free.append(i)
            self.releases += 1
        return i

    def lookup(self, item: Hashable) -> int:
        """Id of ``item`` or 0 if never interned (no allocation)."""
        return self._ids.get(item, 0)

    def item(self, i: int) -> Hashable:
        return self._items[i]

    def ids(self, items: Iterable[Hashable]) -> List[int]:
        return [self.id(x) for x in items]


class LabelVocabs:
    """The vocab set the selector/taint compiler works against.

    keys:   label key strings
    pairs:  (key, value) tuples — the unit of In/NotIn bitset tests
    """

    def __init__(self):
        self.keys = Vocab("label-keys")
        self.pairs = Vocab("label-pairs")
        # label keys that appear in Gt/Lt expressions get numeric slots
        self.numeric_keys = Vocab("numeric-label-keys")

    def pair_id(self, key: str, value: str) -> int:
        self.keys.id(key)
        return self.pairs.id((key, value))

    def key_id(self, key: str) -> int:
        return self.keys.id(key)
