"""Platform pinning for entrypoints.

On relay-tunneled TPU hosts the platform-registration hook can override the
``JAX_PLATFORMS`` environment variable, so pinning requires BOTH the env var
(read at import) and ``jax.config.update`` (wins for the lazily-initialized
backend). Call before any jax array op has run.
"""

from __future__ import annotations

import os


def force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — older config name; env var still applies
        pass
