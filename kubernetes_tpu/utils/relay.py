"""Relay diagnostics: device-transfer accounting + platform probing.

The axon TPU tunnel makes every host<->device materialization a network
round-trip, so the batch path's contract is ONE blocking device read per
batch cycle (the node_idx materialization at commit; ROADMAP r3 'kill
per-execution relay syncs'). This module gives that invariant a seam:
hot-path code reports materializations through count_sync(), and tests wrap
a workload in track() to assert the per-batch budget — the §5.2 drift-
detector pattern applied to transfer regressions.
"""

from __future__ import annotations

import contextlib
import subprocess
import sys
import threading
import time
from collections import Counter
from typing import Optional, Tuple

_local = threading.local()


def count_sync(tag: str) -> None:
    """Record one blocking device materialization on this thread (no-op
    unless inside track())."""
    c = getattr(_local, "counter", None)
    if c is not None:
        c[tag] += 1


@contextlib.contextmanager
def track():
    """Collect sync counts on this thread: ``with track() as c: ...`` —
    ``c`` is a Counter of tag -> materializations."""
    prev = getattr(_local, "counter", None)
    c: Counter = Counter()
    _local.counter = c
    try:
        yield c
    finally:
        _local.counter = prev


def probe_platform(timeout_s: Optional[float] = None) -> Tuple[str, dict]:
    """Subprocess-probe the ambient jax platform WITHOUT initializing the
    backend in-process (a wedged axon relay hangs or raises on init — the
    probe documents reachability per run; bench.py's per-round evidence).
    Returns (platform-or-"cpu-fallback", diagnostic dict)."""
    import os

    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "60"))
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu", {"outcome": "forced-cpu"}
    probe = "import jax; jax.devices(); print(jax.default_backend())"
    diag: dict = {}
    # Spread attempts across a window instead of 2 back-to-back tries: the
    # relay wedges in stretches, so a gap between attempts samples distinct
    # health periods (VERDICT r3: "2x60s back-to-back is brittle").
    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
    gap_s = float(os.environ.get("BENCH_PROBE_GAP", "30"))
    for attempt in range(attempts):
        if attempt:
            time.sleep(gap_s)
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode != 0:
                outcome = f"rc={out.returncode}"
            elif not out.stdout.strip():
                outcome = "empty-stdout"
            else:
                outcome = "ok"
            diag = {"outcome": outcome,
                    "duration_s": round(time.perf_counter() - t0, 2),
                    "attempt": attempt}
            if out.returncode != 0:
                diag["error_tail"] = out.stderr.strip()[-300:]
            if outcome == "ok":
                return out.stdout.strip().splitlines()[-1], diag
            if out.returncode != 0 and diag["duration_s"] < 5:
                break  # deterministic fast failure (jax broken/absent):
                       # retrying with gaps only delays the cpu fallback
        except subprocess.TimeoutExpired:
            diag = {"outcome": "timeout",
                    "duration_s": round(time.perf_counter() - t0, 2),
                    "attempt": attempt}
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu-fallback", diag
