"""Injectable clock (testing/clock analog): real monotonic by default, a
manually-advanced FakeClock in tests so backoff expiry is deterministic."""

from __future__ import annotations

import time


class FakeClock:
    def __init__(self, start: float = 1000.0):
        self._now = start

    def __call__(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds


def monotonic() -> float:
    return time.monotonic()
