"""Event recorder — user-visible scheduling events.

Analog of client-go tools/events (event_broadcaster.go:162 NewRecorder) with
the series-deduplication the events API performs: repeated (object, reason,
note) tuples within the dedup window increment a count instead of appending.
The scheduler emits 'Scheduled' and 'FailedScheduling' exactly where the
reference does (schedule_one.go:263 bind success, :292 skip, :843 failure).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class Event:
    object_key: str
    reason: str
    note: str
    type: str = TYPE_NORMAL
    action: str = ""
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)


class EventRecorder:
    def __init__(self, dedup_window: float = 600.0, now_fn=time.time,
                 store=None, reporting_controller: str = ""):
        """``store``: when given, events also persist as core/v1 Event
        objects through the store (the events API write path,
        event_broadcaster.go:162 — kubectl get events then shows them and
        the EventRateLimit admission plugin can meter them); series dedup
        updates the stored object's count instead of creating anew."""
        self.events: List[Event] = []
        self._index: Dict[Tuple[str, str, str], int] = {}
        self.dedup_window = dedup_window
        self.now_fn = now_fn
        self.store = store
        self.reporting_controller = reporting_controller
        self._stored_keys: Dict[Tuple[str, str, str], str] = {}

    def eventf(self, object_key: str, ev_type: str, reason: str, action: str, note: str) -> None:
        key = (object_key, reason, note)
        now = self.now_fn()
        i = self._index.get(key)
        if i is not None and now - self.events[i].last_timestamp < self.dedup_window:
            self.events[i].count += 1
            self.events[i].last_timestamp = now
            self._persist(key, self.events[i])
            return
        self._index[key] = len(self.events)
        ev = Event(object_key, reason, note, ev_type, action, 1, now, now)
        self.events.append(ev)
        # a NEW series must create a new stored object — a stale stored-key
        # from an expired series would be overwritten (count reset, history
        # destroyed) by the update path
        self._stored_keys.pop(key, None)
        self._persist(key, ev)

    def _persist(self, key, ev: Event) -> None:
        if self.store is None:
            return
        import dataclasses as _dc

        from ..api import types as api_types

        ns, _, obj_name = ev.object_key.partition("/")
        if not obj_name:
            ns, obj_name = "default", ev.object_key
        store_key = self._stored_keys.get(key)
        try:
            if store_key is not None and self.store.events.get(store_key) is not None:
                cur = self.store.events[store_key]
                new = _dc.replace(cur, count=ev.count,
                                  last_timestamp=ev.last_timestamp)
                new.meta = _dc.replace(cur.meta)
                self.store.update_object("Event", new)
                return
            # reason in the name: two distinct events for one object in the
            # same microsecond must not collide (the silent-Conflict path
            # would drop the second series entirely)
            name = f"{obj_name}.{ev.reason.lower()}.{int(ev.first_timestamp * 1e6):x}"
            obj = api_types.Event(
                meta=api_types.ObjectMeta(name=name, namespace=ns),
                involved_object=ev.object_key, reason=ev.reason,
                message=ev.note, type=ev.type, count=ev.count,
                first_timestamp=ev.first_timestamp,
                last_timestamp=ev.last_timestamp,
                reporting_controller=self.reporting_controller)
            self.store.create_object("Event", obj)
            self._stored_keys[key] = obj.meta.key()
        except Exception:  # noqa: BLE001 — event loss must never break the
            pass           # component emitting it (rate-limited, conflicts)

    def for_object(self, object_key: str) -> List[Event]:
        return [e for e in self.events if e.object_key == object_key]
