"""Event recorder — user-visible scheduling events.

Analog of client-go tools/events (event_broadcaster.go:162 NewRecorder) with
the series-deduplication the events API performs: repeated (object, reason,
note) tuples within the dedup window increment a count instead of appending.
The scheduler emits 'Scheduled' and 'FailedScheduling' exactly where the
reference does (schedule_one.go:263 bind success, :292 skip, :843 failure).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class Event:
    object_key: str
    reason: str
    note: str
    type: str = TYPE_NORMAL
    action: str = ""
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)


class EventRecorder:
    def __init__(self, dedup_window: float = 600.0, now_fn=time.time):
        self.events: List[Event] = []
        self._index: Dict[Tuple[str, str, str], int] = {}
        self.dedup_window = dedup_window
        self.now_fn = now_fn

    def eventf(self, object_key: str, ev_type: str, reason: str, action: str, note: str) -> None:
        key = (object_key, reason, note)
        now = self.now_fn()
        i = self._index.get(key)
        if i is not None and now - self.events[i].last_timestamp < self.dedup_window:
            self.events[i].count += 1
            self.events[i].last_timestamp = now
            return
        self._index[key] = len(self.events)
        self.events.append(Event(object_key, reason, note, ev_type, action, 1, now, now))

    def for_object(self, object_key: str) -> List[Event]:
        return [e for e in self.events if e.object_key == object_key]
