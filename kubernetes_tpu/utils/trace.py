"""utiltrace analog (k8s.io/utils/trace): always-on cheap latency attribution.

The reference opens a trace per scheduling cycle and logs step timings only
when the cycle exceeds a threshold (schedule_one.go:312 utiltrace.New +
LogIfLong(100ms)).  Steps are recorded unconditionally (two clock reads), the
formatting cost is paid only on slow cycles.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    __slots__ = ("name", "fields", "start", "steps", "now_fn")

    def __init__(self, name: str, now_fn=time.monotonic, **fields):
        self.name = name
        self.fields = fields
        self.now_fn = now_fn
        self.start = now_fn()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((self.now_fn(), msg))

    def total(self) -> float:
        return self.now_fn() - self.start

    def log_if_long(self, threshold_s: float, sink=None) -> Optional[str]:
        total = self.total()
        if total < threshold_s:
            return None
        parts = [f'Trace "{self.name}" ({", ".join(f"{k}={v}" for k, v in self.fields.items())}) total={total*1000:.1f}ms:']
        prev = self.start
        for t, msg in self.steps:
            parts.append(f"  +{(t - prev)*1000:.1f}ms {msg}")
            prev = t
        text = "\n".join(parts)
        (sink or logger.info)(text)
        return text
