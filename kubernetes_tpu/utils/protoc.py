"""Shared protoc-build availability check.

Three modules compile a .proto on demand into ``native/build`` with the
same gate (api/protobuf.py, kubelet/cri.py, backend/grpc_service.py —
the last also prefers its hash-gated vendored module). The availability
rule lives here ONCE so a future change (e.g. tolerating a missing
.proto, or also requiring grpcio) cannot leave the three ``pb2()`` gates
silently inconsistent.
"""

from __future__ import annotations

import os


def build_available(cached_module, pb2_path: str, proto_path: str) -> bool:
    """True when an on-demand protoc build will succeed (or already did):
    the module object is already imported, a cached build at ``pb2_path``
    is at least as fresh as ``proto_path``, or protoc is on PATH."""
    import shutil

    if cached_module is not None:
        return True
    if not os.path.exists(proto_path):
        # the pb2() builders compare mtimes against the .proto even when
        # a cached build exists, so a missing source means every path
        # through pb2() raises — having protoc changes nothing
        return False
    try:
        if (os.path.exists(pb2_path)
                and os.path.getmtime(pb2_path) >= os.path.getmtime(proto_path)):
            return True
    except OSError:
        return False
    return shutil.which("protoc") is not None
