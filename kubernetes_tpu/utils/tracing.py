"""OTel-style span tracing (SURVEY §5.1: component-base/traces/utils.go
NewProvider — the OTLP exporter seam, re-expressed without an OTLP
endpoint in this image).

A process-global tracer (None = disabled, the default: the disabled check
is one global read on the hot path). Spans nest per-thread; finished spans
go to the exporter — in-memory for tests, JSON-lines for offline analysis
(OTLP-shaped dicts: traceId/spanId/parentSpanId/name/start/end/attributes,
loadable into any OTLP-compatible viewer).

    tracing.enable(JsonFileExporter("/tmp/spans.jsonl"))
    with tracing.span("scheduling.cycle", pod="ns/p"):
        with tracing.span("device.dispatch"):
            ...

The scheduler wraps its cycle phases (snapshot/filter/score on the
sequential path; sync/encode/dispatch/commit on the batch path), giving the
per-phase latency attribution the reference gets from utiltrace +
APIServerTracing spans."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import List, Optional

_tracer: Optional["Tracer"] = None


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attributes")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attributes: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start = time.time_ns()
        self.end = 0
        self.attributes = attributes

    def to_otlp(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_id or "",
            "name": self.name,
            "startTimeUnixNano": self.start,
            "endTimeUnixNano": self.end,
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in self.attributes.items()
            ],
        }

    @property
    def duration_s(self) -> float:
        return (self.end - self.start) / 1e9


class InMemoryExporter:
    def __init__(self):
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_name(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]


class JsonFileExporter:
    """One OTLP-shaped JSON object per line."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def export(self, span: Span) -> None:
        with self._lock:
            self._f.write(json.dumps(span.to_otlp()) + "\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()


class Tracer:
    def __init__(self, exporter):
        self.exporter = exporter
        self._local = threading.local()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        stack = self._stack()
        if stack:
            trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
        else:
            trace_id, parent_id = uuid.uuid4().hex, None
        with self._run_span(name, trace_id, parent_id, attributes) as s:
            yield s

    @contextlib.contextmanager
    def span_remote(self, name: str, trace_id: str, parent_id: str,
                    **attributes):
        """A span whose parent lives in ANOTHER process (the W3C
        traceparent seam): the local thread stack starts from the remote
        context, so nested spans chain under the caller's trace."""
        with self._run_span(name, trace_id, parent_id, attributes) as s:
            yield s

    @contextlib.contextmanager
    def _run_span(self, name, trace_id, parent_id, attributes):
        stack = self._stack()
        s = Span(name, trace_id, parent_id, attributes)
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.time_ns()
            stack.pop()
            try:
                self.exporter.export(s)
            except Exception:  # noqa: BLE001 — tracing must never fail the
                pass           # operation it instruments (a full disk would
                               # otherwise read as device death upstream)


def enable(exporter=None) -> "Tracer":
    """Install the process tracer (None exporter = in-memory)."""
    global _tracer
    _tracer = Tracer(exporter or InMemoryExporter())
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def get() -> Optional[Tracer]:
    return _tracer


@contextlib.contextmanager
def span(name: str, **attributes):
    """No-op when tracing is disabled (one global read)."""
    t = _tracer
    if t is None:
        yield None
    else:
        with t.span(name, **attributes) as s:
            yield s


def current() -> Optional[Span]:
    """The active span on this thread, or None (disabled / no open span)."""
    t = _tracer
    if t is None:
        return None
    stack = t._stack()
    return stack[-1] if stack else None


def annotate(**attributes) -> None:
    """Attach attributes to the active span (no-op when tracing is disabled
    or no span is open — one global read). The device-telemetry layer uses
    this to ride ``device.upload``/``device.fetch`` byte counts on the
    ``device.sync`` / ``device.commit.wait`` spans without the call sites
    having to thread span handles around."""
    s = current()
    if s is None:
        return
    s.attributes.update(attributes)


def emit(name: str, start_ns: int, end_ns: int, **attributes) -> None:
    """Export one ALREADY-FINISHED span with explicit timestamps, parented
    under this thread's active span (no-op when tracing is disabled — one
    global read). The dispatch profiler uses this to back-fill the
    ``device.dispatch.{dwell,exec,fetch}`` waterfall under the still-open
    ``device.commit.wait`` span: the phases are only known once the
    blocking wait returns, after their wall-clock windows have passed."""
    t = _tracer
    if t is None:
        return
    stack = t._stack()
    if stack:
        trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
    else:
        trace_id, parent_id = uuid.uuid4().hex, None
    s = Span(name, trace_id, parent_id, attributes)
    s.start = int(start_ns)
    s.end = int(end_ns)
    try:
        t.exporter.export(s)
    except Exception:  # noqa: BLE001 — same never-fail rule as _run_span
        pass


def format_traceparent() -> Optional[str]:
    """W3C traceparent of the active span (``00-<trace_id>-<span_id>-01``),
    or None when tracing is disabled or no span is open. Inject this into a
    wire request so the server side parents under the caller's trace."""
    s = current()
    if s is None:
        return None
    return f"00-{s.trace_id}-{s.span_id}-01"


def parse_traceparent(tp) -> Optional[tuple]:
    """``(trace_id, parent_span_id)`` from a traceparent string, or None on
    anything malformed (propagation is best-effort; a bad header just means
    the server span roots its own trace)."""
    if not tp or not isinstance(tp, str):
        return None
    parts = tp.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


@contextlib.contextmanager
def span_from_remote(traceparent, name: str, **attributes):
    """Open a span parented under a remote caller's traceparent (the server
    half of cross-boundary propagation). Falls back to a normal local span
    when the context is absent/malformed; no-op when tracing is disabled."""
    t = _tracer
    if t is None:
        yield None
        return
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        with t.span(name, **attributes) as s:
            yield s
    else:
        with t.span_remote(name, parsed[0], parsed[1], **attributes) as s:
            yield s


def tail(n: int = 256) -> List[Span]:
    """Last ``n`` finished spans when the active exporter keeps them in
    memory (InMemoryExporter); [] otherwise — the /debug/spans feed."""
    t = _tracer
    spans = getattr(getattr(t, "exporter", None), "spans", None) if t else None
    if not spans or n <= 0:  # n=0 means none, not all (spans[-0:] trap)
        return []
    return list(spans[-n:])


def maybe_enable_from_env() -> None:
    """KTPU_TRACE_FILE=<path> turns on JSON-lines span export (the
    --tracing-config-file analog of the cmd binaries)."""
    path = os.environ.get("KTPU_TRACE_FILE")
    if path and _tracer is None:
        enable(JsonFileExporter(path))
