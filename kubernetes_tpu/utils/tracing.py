"""OTel-style span tracing (SURVEY §5.1: component-base/traces/utils.go
NewProvider — the OTLP exporter seam, re-expressed without an OTLP
endpoint in this image).

A process-global tracer (None = disabled, the default: the disabled check
is one global read on the hot path). Spans nest per-thread; finished spans
go to the exporter — in-memory for tests, JSON-lines for offline analysis
(OTLP-shaped dicts: traceId/spanId/parentSpanId/name/start/end/attributes,
loadable into any OTLP-compatible viewer).

    tracing.enable(JsonFileExporter("/tmp/spans.jsonl"))
    with tracing.span("scheduling.cycle", pod="ns/p"):
        with tracing.span("device.dispatch"):
            ...

The scheduler wraps its cycle phases (snapshot/filter/score on the
sequential path; sync/encode/dispatch/commit on the batch path), giving the
per-phase latency attribution the reference gets from utiltrace +
APIServerTracing spans."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import List, Optional

_tracer: Optional["Tracer"] = None


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attributes")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attributes: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start = time.time_ns()
        self.end = 0
        self.attributes = attributes

    def to_otlp(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_id or "",
            "name": self.name,
            "startTimeUnixNano": self.start,
            "endTimeUnixNano": self.end,
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in self.attributes.items()
            ],
        }

    @property
    def duration_s(self) -> float:
        return (self.end - self.start) / 1e9


class InMemoryExporter:
    def __init__(self):
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_name(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]


class JsonFileExporter:
    """One OTLP-shaped JSON object per line."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def export(self, span: Span) -> None:
        with self._lock:
            self._f.write(json.dumps(span.to_otlp()) + "\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()


class Tracer:
    def __init__(self, exporter):
        self.exporter = exporter
        self._local = threading.local()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        stack = self._stack()
        if stack:
            trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
        else:
            trace_id, parent_id = uuid.uuid4().hex, None
        s = Span(name, trace_id, parent_id, attributes)
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.time_ns()
            stack.pop()
            try:
                self.exporter.export(s)
            except Exception:  # noqa: BLE001 — tracing must never fail the
                pass           # operation it instruments (a full disk would
                               # otherwise read as device death upstream)


def enable(exporter=None) -> "Tracer":
    """Install the process tracer (None exporter = in-memory)."""
    global _tracer
    _tracer = Tracer(exporter or InMemoryExporter())
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def get() -> Optional[Tracer]:
    return _tracer


@contextlib.contextmanager
def span(name: str, **attributes):
    """No-op when tracing is disabled (one global read)."""
    t = _tracer
    if t is None:
        yield None
    else:
        with t.span(name, **attributes) as s:
            yield s


def maybe_enable_from_env() -> None:
    """KTPU_TRACE_FILE=<path> turns on JSON-lines span export (the
    --tracing-config-file analog of the cmd binaries)."""
    path = os.environ.get("KTPU_TRACE_FILE")
    if path and _tracer is None:
        enable(JsonFileExporter(path))
