"""Feature gates (component-base/featuregate/feature_gate.go:117,159).

A mutable known-features registry with per-feature default + lock-in
(GA features cannot be disabled), set from a --feature-gates map string.
Plugins receive a distilled view (plugins/registry.go:47 feature.Features).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Tuple

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    pre_release: str = BETA
    locked_to_default: bool = False  # GA lock (featuregate LockToDefault)


# the scheduling-relevant 1.25-era gates (pkg/features/kube_features.go subset)
DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    "DefaultPodTopologySpread": FeatureSpec(True, GA, True),
    "MinDomainsInPodTopologySpread": FeatureSpec(False, ALPHA),
    "NodeInclusionPolicyInPodTopologySpread": FeatureSpec(False, ALPHA),
    "PodAffinityNamespaceSelector": FeatureSpec(True, GA, True),
    "PodDisruptionBudget": FeatureSpec(True, GA, True),
    "PodOverhead": FeatureSpec(True, BETA),
    "ReadWriteOncePod": FeatureSpec(False, ALPHA),
    "VolumeCapacityPriority": FeatureSpec(False, ALPHA),
    # this framework's own gates
    "TPUBatchedScheduling": FeatureSpec(True, BETA),
    "TPUPallasKernels": FeatureSpec(True, BETA),
}


class FeatureGate:
    def __init__(self, known: Dict[str, FeatureSpec] = None):
        self._lock = threading.Lock()
        self._known = dict(known if known is not None else DEFAULT_FEATURES)
        self._enabled: Dict[str, bool] = {}

    def add(self, features: Dict[str, FeatureSpec]) -> None:
        """Register additional known features (featuregate Add)."""
        with self._lock:
            for name, spec in features.items():
                existing = self._known.get(name)
                if existing is not None and existing != spec:
                    raise ValueError(f"feature {name} already registered differently")
                self._known[name] = spec

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name}")
            return spec.default

    def set_from_map(self, overrides: Dict[str, bool]) -> None:
        """Apply explicit settings (SetFromMap); locked features reject
        non-default values."""
        with self._lock:
            for name, value in overrides.items():
                spec = self._known.get(name)
                if spec is None:
                    raise ValueError(f"unknown feature gate {name}")
                if spec.locked_to_default and value != spec.default:
                    raise ValueError(
                        f"cannot set feature gate {name} to {value}: locked to {spec.default}"
                    )
                self._enabled[name] = value

    def set_from_string(self, s: str) -> None:
        """--feature-gates 'A=true,B=false' flag form."""
        if not s:
            return
        overrides = {}
        for part in s.split(","):
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"missing = in feature gate {part!r}")
            name, _, val = part.partition("=")
            if val.lower() not in ("true", "false"):
                raise ValueError(f"invalid feature gate value {part!r}")
            overrides[name.strip()] = val.lower() == "true"
        self.set_from_map(overrides)

    def known_features(self) -> Iterable[Tuple[str, FeatureSpec]]:
        with self._lock:
            return sorted(self._known.items())


# process-global gate (the reference's DefaultFeatureGate)
DEFAULT_FEATURE_GATE = FeatureGate()
