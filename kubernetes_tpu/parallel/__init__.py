from .mesh import make_node_mesh, make_sharded_schedule_fn, shard_node_tensors  # noqa: F401
