from .mesh import (  # noqa: F401
    make_node_mesh,
    make_sharded_schedule_fn,
    shard_node_tensors,
    shard_topo_counts,
)
