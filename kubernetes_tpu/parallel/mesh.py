"""Node-axis sharding over a jax.sharding.Mesh.

The reference's only hot-loop parallelism is a 16-goroutine chunked
parallel-for over nodes (parallelize/parallelism.go:27); the TPU equivalent
shards the node axis of the device mirror across the mesh and runs the SAME
schedule_batch program under shard_map. Cross-device traffic per scan step is
three scalar collectives (pmax of the best score, pmin of the winning axis
index, psum of the winning global slot) riding ICI — the "per-shard
filter+score+local-top-k, then tiny collective" pattern of SURVEY.md §5.7,
not a resharding of any [P, N] matrix.

Multi-slice/DCN (the 50k-node stretch) uses the same program over a mesh whose
outer axis spans slices; nothing here is ICI-specific.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..backend.batch import DEFAULT_WEIGHTS, BatchResult, schedule_batch_core
from ..ops.schema import ExprTable, NodeTensors, PodBatch, TopoBatch, TopoCounts

AXIS = "nodes"

# NodeTensors fields sharded on their node (first) axis; vocab-level arrays
# (image sizes/spread, priority-class vocab) are replicated.
_REPLICATED_NT_FIELDS = ("image_sizes", "image_num_nodes", "class_prio")


def resolve_shard_map():
    """The shard_map entry point across the JAX rename: new JAX exposes
    ``jax.shard_map`` (with ``check_vma=``); older releases only ship
    ``jax.experimental.shard_map.shard_map`` (with ``check_rep=``).
    Returns ``(fn, check_kwarg_name)`` so callers pass the right spelling
    of the replication-check knob."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, "check_vma"
    from jax.experimental.shard_map import shard_map as fn  # noqa: F811

    return fn, "check_rep"


def make_node_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def _nt_specs() -> NodeTensors:
    import dataclasses

    fields = {}
    for f in dataclasses.fields(NodeTensors):
        fields[f.name] = P() if f.name in _REPLICATED_NT_FIELDS else P(AXIS)
    return NodeTensors(**fields)


def shard_node_tensors(nt: NodeTensors, mesh: Mesh) -> NodeTensors:
    """Place a (host/global) NodeTensors onto the mesh, node axis sharded."""
    import dataclasses

    specs = _nt_specs()
    out = {}
    for f in dataclasses.fields(NodeTensors):
        arr = getattr(nt, f.name)
        out[f.name] = jax.device_put(arr, NamedSharding(mesh, getattr(specs, f.name)))
    return NodeTensors(**out)


def shard_topo_counts(tc: TopoCounts, mesh: Mesh) -> TopoCounts:
    """Place TopoCounts onto the mesh: count matrices sharded on their node
    (second) axis, the term-key vector replicated."""
    return TopoCounts(
        sel_counts=jax.device_put(tc.sel_counts, NamedSharding(mesh, P(None, AXIS))),
        term_counts=jax.device_put(tc.term_counts, NamedSharding(mesh, P(None, AXIS))),
        term_key=jax.device_put(tc.term_key, NamedSharding(mesh, P())),
    )


def make_sharded_schedule_fn(mesh: Mesh, weights: Optional[Dict[str, float]] = None,
                             topo_enabled: bool = True,
                             spec_decode: bool = False,
                             topo_mode: Optional[str] = None,
                             host_key: int = 0,
                             vd_override: Optional[int] = None):
    """Compile schedule_batch over the mesh: node axis sharded, pods/exprs
    replicated, results replicated (winner slots are global indices).

    ``spec_decode`` runs the speculative decide/repair rounds instead of the
    P-step scan — supported under sharding for EVERY topology mode:
    topology-off, the hostname fast path (``topo_mode="host"`` + the
    hostname label's ``host_key`` slot), and the general domain-aggregating
    mode (``vd_override`` bounds the domain axis). In host mode the
    seg_exist carry slot holds the node-sharded [T, N] per-node term
    counts, so its out_spec shards with the node axis; the general mode's
    [T, Vd] domain table stays replicated (every shard applies identical
    psum'd updates)."""
    if topo_mode is None:
        topo_mode = "general" if topo_enabled else "off"
    wk = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    import dataclasses

    nt_spec = _nt_specs()
    pb_spec = jax.tree_util.tree_map(lambda _: P(), PodBatch(**{
        f.name: 0 for f in dataclasses.fields(PodBatch)
    }))
    et_spec = jax.tree_util.tree_map(lambda _: P(), ExprTable(op=0, key=0, val=0, bits=0))
    tc_spec = TopoCounts(sel_counts=P(None, AXIS), term_counts=P(None, AXIS), term_key=P())
    tb_spec = jax.tree_util.tree_map(lambda _: P(), TopoBatch(**{
        f.name: 0 for f in dataclasses.fields(TopoBatch)
    }))
    out_spec = BatchResult(
        node_idx=P(), best_score=P(), any_feasible=P(),
        static_masks={
            "NodeUnschedulable": P(None, AXIS), "NodeName": P(None, AXIS),
            "TaintToleration": P(None, AXIS), "NodeAffinity": P(None, AXIS),
        },
        fit_ok=P(None, AXIS), ports_ok=P(None, AXIS),
        spread_ok=P(None, AXIS), ipa_ok=P(None, AXIS),
        first_fail=P(None, AXIS),
        final_requested=P(AXIS), final_nonzero=P(AXIS), final_ports=P(AXIS),
        # evolved topo carry: sel_counts is node-sharded on its second axis
        # like tc.sel_counts. seg_exist depends on the mode: general mode
        # evolves a replicated [T, Vd] domain table (commit_update psums
        # every update so all shards agree); HOST mode's carry slot holds
        # the per-node [T, N] term counts — node-sharded like sel_counts.
        final_sel_counts=P(None, AXIS),
        final_seg_exist=P(None, AXIS) if topo_mode == "host" else P(),
        final_class_req=P(AXIS),
    )

    body = functools.partial(schedule_batch_core, weights_key=wk,
                             topo_enabled=topo_enabled, axis_name=AXIS,
                             num_shards=mesh.size, spec_decode=spec_decode,
                             topo_mode=topo_mode, host_key=host_key,
                             vd_override=vd_override)
    shard_map_fn, check_kw = resolve_shard_map()
    sharded = shard_map_fn(
        body, mesh=mesh,
        in_specs=(pb_spec, et_spec, nt_spec, tc_spec, tb_spec, P()),
        out_specs=out_spec,
        **{check_kw: False},
    )
    return jax.jit(sharded)
