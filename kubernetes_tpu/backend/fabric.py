"""Device-side HA fabric: one client fronting N DeviceService replicas.

PR 6 made the *scheduler* tier active-active — N replicas share one
DeviceService behind per-client sessions, fencing, and commit holds — but
every replica still talked to ONE device process: a single sidecar crash
dropped the whole batched path to the oracle fallback until restart. This
module covers the other half (ROADMAP item 3): multiple DeviceServices
behind one ``DeviceFabric``, so the degradation ladder becomes
replica-failover → surviving-replica → oracle instead of
single-process → oracle — the device tier's analog of the replicated
storage under the reference's apiserver (PAPER.md L0/L2: etcd quorum +
watch cache; a member loss is absorbed by the survivors, not by clients).

Design:

  * **Per-endpoint replicas.** Each endpoint gets its own transport client
    (``WireClient``/``GrpcClient``) and its own ``CircuitBreaker``
    (backend/circuit.py). The replica breaker does NOT gate calls to the
    active replica (the scheduler's own breaker owns whole-fabric
    degradation) — it rate-limits how often a DOWN endpoint is re-probed
    with the cheap Health verb (PR 4), exactly the half-open-probe reuse.
  * **Sticky primary/standby selection.** Every verb routes to the ACTIVE
    replica. A rejoining ex-primary is detected by the standby probe and
    becomes a healthy *standby* — it is never re-adopted mid-flight. It
    only becomes active again through a later failover, and the first
    contact then trips the epoch check (its epoch is not the one the
    client last synced), so it is re-seeded with a ``full=True`` resync
    before any incremental delta can land on its stale mirror.
  * **Failover rides the proven recovery machinery** (PRs 3/6) instead of
    inventing a replication protocol. On active loss the fabric marks the
    replica down, poisons the in-flight batch (flight event; the typed
    transient ``FailoverError`` makes the scheduler requeue its pods
    exactly like device death poisons the in-process ring), and promotes
    the first standby whose Health answers. Nothing is replayed: batch
    ids are idempotent per service, and the next delta push hits the
    standby's unknown epoch → ``StaleEpochError`` → the client's existing
    ``_full_resync`` seeds the standby under a fresh session (new
    sessionGen — a zombie commit from the dead primary's session can then
    only fence as a ``ConflictError``).
  * **All replicas down** → the original transport error propagates and
    the scheduler's breaker degrades to the sequential oracle; scheduling
    never stops. Heal is the scheduler's half-open probe calling
    ``health()`` here, which answers from (or fails over to) whichever
    replica recovered first.
  * **Permanent errors fail over too** (reason="permanent" on the
    failover counter): a single replica deterministically answering 4xx
    is the version-skewed-deploy failure this tier exists to absorb. The
    cost when the REQUEST is at fault (every replica rejects it) is one
    extra hop per attempt until the scheduler breaker opens — bounded,
    and distinguishable in telemetry by the reason label plus identical
    lastError strings across replicas in /debug/fabric.

Locking: the fabric lock guards only the selection state (active index,
failover counters, probe clock) for /debug readers — transport calls and
health probes always run OUTSIDE it (a slow replica must never wedge the
serving thread; the locktrace blocking pass enforces this). Probes of
maybe-dead replicas additionally ride a dedicated SINGLE-ATTEMPT probe
client (``probe_client_factory``; no retries, no backoff sleeps) so a
blackholed standby costs one connect timeout per window on the
scheduling thread, never the full retry budget.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, List, Optional

from ..testing import locktrace
from . import telemetry
from .circuit import CircuitBreaker
from .errors import (
    ConflictError,
    DeviceServiceError,
    FailoverError,
    PermanentDeviceError,
    StaleEpochError,
)

# how often a down standby is re-probed with the Health verb (also the
# per-replica breaker's reset timeout, so allow() admits one probe per
# window) — wire-tuned like the scheduler breaker's 5s default
DEFAULT_PROBE_INTERVAL_S = 5.0

# bounded failover journal for /debug/fabric
LOG_CAPACITY = 64


class _Replica:
    """One DeviceService endpoint: transport client plus health
    bookkeeping. Plain attributes only (single writer: the scheduling
    thread; /debug readers tolerate a torn snapshot of booleans)."""

    __slots__ = ("index", "endpoint", "client", "probe", "breaker",
                 "healthy", "epoch", "last_error", "last_batch_id")

    def __init__(self, index: int, endpoint: str, client,
                 now_fn, probe_interval_s: float, probe_client=None):
        self.index = index
        self.endpoint = endpoint
        self.client = client
        # Health probes of a maybe-dead replica run synchronously on the
        # scheduling thread: the dedicated probe client (no retry budget)
        # bounds a blackholed standby's cost to ONE connect timeout per
        # window instead of retries × timeout + backoff sleeps
        self.probe = probe_client if probe_client is not None else client
        # threshold 1: one failed call marks the replica down; the reset
        # timeout then meters Health re-probes (half-open = one probe)
        self.breaker = CircuitBreaker(failure_threshold=1,
                                      reset_timeout_s=probe_interval_s,
                                      now_fn=now_fn)
        self.healthy = True
        self.epoch: Optional[str] = None      # last epoch this replica answered
        self.last_error = ""
        self.last_batch_id: Optional[str] = None  # last batch it accepted


class DeviceFabric:
    """Client-side fabric over N DeviceService endpoints, presenting the
    single-client surface ``WireScheduler`` already speaks (apply_deltas /
    schedule_batch / health / heartbeat / sessions_dump + supports_*)."""

    def __init__(self, endpoints: List[str],
                 client_factory: Callable[[str, int], object],
                 probe_client_factory: Optional[Callable] = None,
                 metrics=None, now_fn=time.monotonic,
                 probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S):
        if not endpoints:
            raise ValueError("DeviceFabric needs at least one endpoint")
        self.now_fn = now_fn
        self.probe_interval_s = probe_interval_s
        self.metrics = metrics
        self.replicas = [
            _Replica(i, ep, client_factory(ep, i), now_fn, probe_interval_s,
                     probe_client=(probe_client_factory(ep, i)
                                   if probe_client_factory is not None
                                   else None))
            for i, ep in enumerate(endpoints)]
        first = self.replicas[0].client
        # capability flags mirror the underlying transport (all replicas
        # share one transport class by construction)
        self.supports_dra = getattr(first, "supports_dra", False)
        self.supports_health = getattr(first, "supports_health", False)
        self.supports_sessions = getattr(first, "supports_sessions", False)
        self._lock = locktrace.make_lock("DeviceFabric")
        self._active = 0
        self.failovers = 0
        self.log: deque = deque(maxlen=LOG_CAPACITY)
        self._last_probe = now_fn()
        if metrics is not None:
            metrics.fabric_active_replica.set(value=0)
            for rep in self.replicas:
                metrics.fabric_replica_health.set(rep.endpoint, value=1)

    # --------------------------------------------------------------- verbs

    def apply_deltas(self, payload: dict) -> dict:
        return self._call("apply_deltas", payload)

    def schedule_batch(self, payload: dict) -> dict:
        return self._call("schedule_batch", payload)

    def heartbeat(self, payload: dict) -> dict:
        return self._call("heartbeat", payload)

    def health(self) -> dict:
        return self._call("health", None)

    def sessions_dump(self) -> dict:
        # read-only introspection, invoked from the /debug SERVING thread
        # (WireScheduler.debug_sessions): it must never run the failover/
        # probe machinery — the scheduling thread is the single failover
        # writer. A transport error surfaces to the debug body (the
        # caller renders it), not as a demotion.
        return self.active_replica().client.sessions_dump()

    # ------------------------------------------------------------- routing

    def active_replica(self) -> _Replica:
        with self._lock:
            return self.replicas[self._active]

    def active_endpoint(self) -> str:
        return self.active_replica().endpoint

    def _call(self, verb: str, payload: Optional[dict]):
        rep = self.active_replica()
        fn = getattr(rep.client, verb)
        try:
            # transport IO runs outside the fabric lock — see module doc
            out = fn(payload) if payload is not None else fn()
        except (StaleEpochError, ConflictError):
            # protocol verdicts from a HEALTHY service (restart detected /
            # ownership lost): the client's own recovery paths handle
            # them; they are not replica loss
            raise
        except DeviceServiceError as exc:
            new, probe_out = self._replica_lost(rep, verb, payload, exc)
            if verb == "health":
                # the promotion probe's answer IS a health answer: the
                # scheduler's half-open probe should see the live standby,
                # not a failed fabric (the batch proceeds and the epoch
                # protocol re-seeds on the next push)
                return probe_out
            raise FailoverError(
                f"device replica {rep.endpoint} lost "
                f"({type(exc).__name__}: {exc}); promoted standby "
                f"{new.endpoint} — next push re-seeds it via epoch resync",
                from_endpoint=rep.endpoint,
                to_endpoint=new.endpoint) from exc
        self._note_success(rep, verb, payload, out)
        self._maybe_probe_standbys()
        return out

    def _note_success(self, rep: _Replica, verb: str,
                      payload: Optional[dict], out: dict) -> None:
        rep.breaker.record_success()
        if isinstance(out, dict):
            rep.epoch = out.get("epoch", rep.epoch)
        if verb == "schedule_batch" and payload:
            rep.last_batch_id = payload.get("batchId", rep.last_batch_id)
        if not rep.healthy:
            self._mark_health(rep, True)

    def _mark_health(self, rep: _Replica, up: bool) -> None:
        rep.healthy = up
        if self.metrics is not None:
            self.metrics.fabric_replica_health.set(rep.endpoint,
                                                   value=1 if up else 0)

    # ------------------------------------------------------------ failover

    def _replica_lost(self, rep: _Replica, verb: str,
                      payload: Optional[dict], exc: DeviceServiceError):
        """The active replica failed a call: mark it down, poison the
        in-flight batch, promote the first live standby. Returns
        ``(new_active, its_health_response)``; raises the ORIGINAL error
        when no standby answers (all replicas down — the scheduler's
        breaker owns the next rung of the ladder: oracle degrade)."""
        rep.breaker.record_failure(exc)
        rep.last_error = f"{type(exc).__name__}: {exc}"
        self._mark_health(rep, False)
        batch_id = (payload or {}).get("batchId")
        telemetry.event("replica_down", endpoint=rep.endpoint, verb=verb,
                        lastBatchId=rep.last_batch_id,
                        error=str(exc)[:200])
        if batch_id:
            # the in-flight batch dies with its replica — the wire twin of
            # the in-process ring's poison-on-device-death: the scheduler
            # requeues the pods (idempotent batch ids mean nothing is
            # replayed; a fresh batch retries them after the resync)
            telemetry.event("poison", batchId=batch_id,
                            endpoint=rep.endpoint,
                            pods=len((payload or {}).get("pods") or ()),
                            error=str(exc)[:200])
        promoted = self._promote_standby(rep)
        if promoted is None:
            raise exc
        new, probe_out = promoted
        reason = ("permanent" if isinstance(exc, PermanentDeviceError)
                  else "transient")
        if self.metrics is not None:
            self.metrics.fabric_failovers.inc(reason)
        # ordered strictly after the poison of the last in-flight batch:
        # the postmortem reads "batch died, THEN the fabric moved on"
        telemetry.event("failover", fromEndpoint=rep.endpoint,
                        endpoint=new.endpoint, batchId=batch_id,
                        lastBatchId=rep.last_batch_id, reason=reason)
        return new, probe_out

    def _promote_standby(self, dead: _Replica):
        """Probe standbys (rotation order from the active) with the cheap
        Health verb; the first that answers becomes active. Probes run
        outside the lock; only the index flip is guarded."""
        with self._lock:
            start = self._active
        n = len(self.replicas)
        for k in range(1, n):
            cand = self.replicas[(start + k) % n]
            if cand is dead or not cand.breaker.allow():
                continue
            try:
                out = cand.probe.health()
            except DeviceServiceError as probe_exc:
                cand.breaker.record_failure(probe_exc)
                cand.last_error = (f"{type(probe_exc).__name__}: "
                                   f"{probe_exc}")
                self._mark_health(cand, False)
                continue
            cand.breaker.record_success()
            cand.epoch = out.get("epoch", cand.epoch)
            self._mark_health(cand, True)
            with self._lock:
                self._active = cand.index
                self.failovers += 1
                self.log.append({"t": self.now_fn(),
                                 "from": dead.endpoint,
                                 "to": cand.endpoint,
                                 "error": dead.last_error})
            if self.metrics is not None:
                self.metrics.fabric_active_replica.set(value=cand.index)
            return cand, out
        return None

    def _maybe_probe_standbys(self) -> None:
        """Rate-limited rejoin detection: probe DOWN standbys with Health.
        A replica that answers becomes a healthy standby again — never
        the active (sticky selection): adoption happens only through a
        failover, whose epoch-mismatch resync re-seeds the stale mirror."""
        with self._lock:
            now = self.now_fn()
            if now - self._last_probe < self.probe_interval_s:
                return
            self._last_probe = now
            active = self._active
        down = [r for r in self.replicas
                if not r.healthy and r.index != active]
        for rep in down:
            if not rep.breaker.allow():
                continue
            try:
                out = rep.probe.health()
            except DeviceServiceError as exc:
                rep.breaker.record_failure(exc)
                rep.last_error = f"{type(exc).__name__}: {exc}"
                continue
            rep.breaker.record_success()
            restarted = (rep.epoch is not None
                         and out.get("epoch") != rep.epoch)
            rep.epoch = out.get("epoch", rep.epoch)
            self._mark_health(rep, True)
            telemetry.event("replica_rejoin", endpoint=rep.endpoint,
                            restarted=restarted,
                            lastBatchId=rep.last_batch_id)

    # --------------------------------------------------------------- debug

    def dump(self) -> dict:
        """/debug/fabric body: replica table + bounded failover journal."""
        with self._lock:
            active = self._active
            failovers = self.failovers
            log = list(self.log)
        replicas = []
        for rep in self.replicas:
            replicas.append({
                "endpoint": rep.endpoint,
                "active": rep.index == active,
                "healthy": rep.healthy,
                "epoch": rep.epoch,
                "lastBatchId": rep.last_batch_id,
                "lastError": rep.last_error,
                "breaker": rep.breaker.dump(),
            })
        return {
            "enabled": True,
            "active": self.replicas[active].endpoint,
            "activeIndex": active,
            "replicaCount": len(self.replicas),
            "failovers": failovers,
            "probeIntervalS": self.probe_interval_s,
            "replicas": replicas,
            "log": log,
        }
