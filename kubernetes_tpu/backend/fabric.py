"""Device-side HA fabric: one client fronting N DeviceService replicas.

PR 6 made the *scheduler* tier active-active — N replicas share one
DeviceService behind per-client sessions, fencing, and commit holds — but
every replica still talked to ONE device process: a single sidecar crash
dropped the whole batched path to the oracle fallback until restart. This
module covers the other half (ROADMAP item 3): multiple DeviceServices
behind one ``DeviceFabric``, so the degradation ladder becomes
replica-failover → surviving-replica → oracle instead of
single-process → oracle — the device tier's analog of the replicated
storage under the reference's apiserver (PAPER.md L0/L2: etcd quorum +
watch cache; a member loss is absorbed by the survivors, not by clients).

Design:

  * **Per-endpoint replicas.** Each endpoint gets its own transport client
    (``WireClient``/``GrpcClient``) and its own ``CircuitBreaker``
    (backend/circuit.py). The replica breaker does NOT gate calls to the
    active replica (the scheduler's own breaker owns whole-fabric
    degradation) — it rate-limits how often a DOWN endpoint is re-probed
    with the cheap Health verb (PR 4), exactly the half-open-probe reuse.
  * **Sticky primary/standby selection.** Every verb routes to the ACTIVE
    replica. A rejoining ex-primary is detected by the standby probe and
    becomes a healthy *standby* — it is never re-adopted mid-flight. It
    only becomes active again through a later failover, and the first
    contact then trips the epoch check (its epoch is not the one the
    client last synced), so it is re-seeded with a ``full=True`` resync
    before any incremental delta can land on its stale mirror.
  * **Failover rides the proven recovery machinery** (PRs 3/6) instead of
    inventing a replication protocol. On active loss the fabric marks the
    replica down, poisons the in-flight batch (flight event; the typed
    transient ``FailoverError`` makes the scheduler requeue its pods
    exactly like device death poisons the in-process ring), and promotes
    the first standby whose Health answers. Nothing is replayed: batch
    ids are idempotent per service, and the next delta push hits the
    standby's unknown epoch → ``StaleEpochError`` → the client's existing
    ``_full_resync`` seeds the standby under a fresh session (new
    sessionGen — a zombie commit from the dead primary's session can then
    only fence as a ``ConflictError``).
  * **Concurrent callers.** The pipelined wire transport keeps K batches
    in flight, so several lanes can observe the active's death at once.
    Failover is serialized by a single in-progress flag under the fabric
    lock: the FIRST failing call runs the promotion; concurrent failers
    wait for it to finish and raise ``FailoverError`` against the new
    active — every in-flight batch is poisoned, the promotion happens
    exactly once, and ``failovers`` counts one event.
  * **Warm standbys (background delta replication).** When enabled
    (``replication=True`` — WireScheduler's default with >1 endpoint),
    the fabric folds every delta push it successfully delivers to the
    active into a cumulative replication state (node name → last wire
    entry) and a background worker fans the DIRTY SUFFIX out to each
    healthy standby under its own replication session — asynchronous,
    off the scheduling thread's critical path, coalesced per node (a node
    that changed five times while a standby lagged ships once), so the
    standby's DeviceState mirror tracks the primary's. At promote, the
    client's epoch-mismatch full resync still runs — but the standby's
    device already holds matching rows, so the row-content/generation
    elision (PR 5/7) uploads only the dirty suffix: failover resync cost
    drops from O(cluster) to O(replication lag), asserted by the
    upload-byte telemetry, not wall time.
  * **Standby sessions stay warm.** Fabric heartbeats/sessions otherwise
    reach only the active, so a standby's lease for the scheduler client
    (and for the replicator itself) could silently expire and fence the
    first post-failover commit — or fence the replicator and drop the
    warm device at the promote-time ghost sweep. The replication worker
    therefore fans lease heartbeats out to standbys: the replicator's own
    session, plus the scheduler client's (sessionGen-stripped — the
    standby mints its own generation; what matters is the lease staying
    fresh so the post-failover resync joins a LIVE session whose node
    claims keep the warm DeviceState alive).
  * **All replicas down** → the original transport error propagates and
    the scheduler's breaker degrades to the sequential oracle; scheduling
    never stops. Heal is the scheduler's half-open probe calling
    ``health()`` here, which answers from (or fails over to) whichever
    replica recovered first.
  * **Permanent errors fail over too** (reason="permanent" on the
    failover counter): a single replica deterministically answering 4xx
    is the version-skewed-deploy failure this tier exists to absorb. The
    cost when the REQUEST is at fault (every replica rejects it) is one
    extra hop per attempt until the scheduler breaker opens — bounded,
    and distinguishable in telemetry by the reason label plus identical
    lastError strings across replicas in /debug/fabric.

Locking: the fabric lock guards only the selection/failover state (active
index, in-progress flag, counters, probe clock) and the replicator lock
(``FabricReplicator``) only the cumulative delta state + per-standby
dirty sets — transport calls, health probes, and replication pushes ALL
run outside every traced lock (a slow replica must never wedge a serving
thread; the locktrace blocking pass enforces this). The one
promote-vs-replication race — a replication push landing on a replica
just promoted to active, overwriting newer client content with the
replicator's older view — is closed without holding a lock across IO:
each replica carries a ``repl_idle`` event cleared around its push; the
replicator re-checks the active index under the fabric lock immediately
before clearing it, and the promotion flips the active index first and
then waits (bounded) for ``repl_idle`` before returning, so no new push
can start against the new active and a straggler normally finishes
before the scheduler client ever talks to it. The backstop for a push
hung PAST that bounded wait is server-side: replicator sessions are
flagged, and the service skips any replicated entry whose generation is
not newer than what a direct client session has already pushed for that
node — stale replication can cost a skipped row (repaired by the next
delta), never a backward overwrite.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..testing import locktrace
from . import telemetry
from .circuit import CircuitBreaker
from .errors import (
    ConflictError,
    DeviceServiceError,
    FailoverError,
    PermanentDeviceError,
    StaleEpochError,
)

API_VERSION = "ktpu/v1"

# how often a down standby is re-probed with the Health verb (also the
# per-replica breaker's reset timeout, so allow() admits one probe per
# window) — wire-tuned like the scheduler breaker's 5s default
DEFAULT_PROBE_INTERVAL_S = 5.0

# bounded failover journal for /debug/fabric
LOG_CAPACITY = 64

_REPL_IDS = itertools.count(1)


class _Replica:
    """One DeviceService endpoint: transport client plus health
    bookkeeping. Plain attributes only (single writer per field: the
    calling thread for health/epoch, the replication worker for repl_*;
    /debug readers tolerate a torn snapshot of booleans)."""

    __slots__ = ("index", "endpoint", "client", "probe", "breaker",
                 "healthy", "epoch", "last_error", "last_batch_id",
                 "repl_idle", "repl_needs_full", "repl_synced_seq",
                 "repl_dirty", "repl_removed", "repl_ns_dirty",
                 "repl_epoch", "repl_session_gen", "repl_backoff_until",
                 "repl_hb_at", "repl_pushes", "repl_last_error")

    def __init__(self, index: int, endpoint: str, client,
                 now_fn, probe_interval_s: float, probe_client=None):
        self.index = index
        self.endpoint = endpoint
        self.client = client
        # Health probes of a maybe-dead replica run synchronously on the
        # scheduling thread: the dedicated probe client (no retry budget)
        # bounds a blackholed standby's cost to ONE connect timeout per
        # window instead of retries × timeout + backoff sleeps
        self.probe = probe_client if probe_client is not None else client
        # threshold 1: one failed call marks the replica down; the reset
        # timeout then meters Health re-probes (half-open = one probe)
        self.breaker = CircuitBreaker(failure_threshold=1,
                                      reset_timeout_s=probe_interval_s,
                                      now_fn=now_fn)
        self.healthy = True
        self.epoch: Optional[str] = None      # last epoch this replica answered
        self.last_error = ""
        self.last_batch_id: Optional[str] = None  # last batch it accepted
        # ---- warm-standby replication (worker-owned unless noted) ----
        self.repl_idle = threading.Event()    # clear = push in flight
        self.repl_idle.set()
        self.repl_needs_full = True           # seed/reseed with full=True
        self.repl_synced_seq = 0              # primary seq last acked
        self.repl_dirty: set = set()          # node names pending (repl lock)
        self.repl_removed: set = set()        # removals pending (repl lock)
        self.repl_ns_dirty: set = set()       # namespaces pending (repl lock)
        self.repl_epoch: Optional[str] = None
        self.repl_session_gen: Optional[int] = None
        self.repl_backoff_until = 0.0
        self.repl_hb_at = 0.0
        self.repl_pushes = 0
        self.repl_last_error = ""


class DeviceFabric:
    """Client-side fabric over N DeviceService endpoints, presenting the
    single-client surface ``WireScheduler`` already speaks (apply_deltas /
    schedule_batch / health / heartbeat / sessions_dump + supports_*)."""

    def __init__(self, endpoints: List[str],
                 client_factory: Callable[[str, int], object],
                 probe_client_factory: Optional[Callable] = None,
                 metrics=None, now_fn=time.monotonic,
                 probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
                 replication: bool = False,
                 replication_worker: bool = True):
        if not endpoints:
            raise ValueError("DeviceFabric needs at least one endpoint")
        self.now_fn = now_fn
        self.probe_interval_s = probe_interval_s
        self.metrics = metrics
        self.replicas = [
            _Replica(i, ep, client_factory(ep, i), now_fn, probe_interval_s,
                     probe_client=(probe_client_factory(ep, i)
                                   if probe_client_factory is not None
                                   else None))
            for i, ep in enumerate(endpoints)]
        first = self.replicas[0].client
        # capability flags mirror the underlying transport (all replicas
        # share one transport class by construction)
        self.supports_dra = getattr(first, "supports_dra", False)
        self.supports_health = getattr(first, "supports_health", False)
        self.supports_sessions = getattr(first, "supports_sessions", False)
        self._lock = locktrace.make_lock("DeviceFabric")
        # serializes concurrent failovers (pipelined lanes can observe the
        # active's death at once): waiters park on this condition while the
        # first failer promotes; promotion probes run OUTSIDE the lock
        self._failover_cv = threading.Condition(self._lock)
        self._failover_inprogress = False
        self._active = 0
        self.failovers = 0
        self.log: deque = deque(maxlen=LOG_CAPACITY)
        self._last_probe = now_fn()
        # ---- warm-standby replication ----
        self.replication_enabled = bool(replication) and len(endpoints) > 1
        # worker=False: no background thread — replication happens only on
        # explicit replication_flush() calls (the unit tests' deterministic
        # mode; production keeps the worker)
        self._repl_worker_enabled = replication_worker
        # serializes whole flush ROUNDS (worker vs an explicit test/debug
        # flush). Deliberately a plain lock, NOT locktrace.make_lock: a
        # round contains transport IO by design, and no traced lock is
        # ever acquired while holding it except the fine-grained state
        # locks the round itself takes — it exists to keep two concurrent
        # rounds from splitting one dirty set, not to guard state
        self._repl_round_mutex = threading.Lock()
        self._repl_client_id = f"fabric-repl-{os.getpid():x}-{next(_REPL_IDS)}"
        self._repl_cv = threading.Condition(
            locktrace.make_lock("FabricReplicator"))
        self._repl_nodes: Dict[str, dict] = {}   # name -> last wire entry
        self._repl_namespaces: Dict[str, dict] = {}
        self._repl_seq = 0            # delta generations folded from primary
        self._repl_pending = False
        self._repl_stopped = False
        self._repl_thread: Optional[threading.Thread] = None
        self._client_hb: Optional[str] = None  # scheduler clientId to keep warm
        self.repl_rounds = 0
        if metrics is not None:
            metrics.fabric_active_replica.set(value=0)
            for rep in self.replicas:
                metrics.fabric_replica_health.set(rep.endpoint, value=1)

    def close(self) -> None:
        """Stop the replication worker and release transport clients that
        own resources (gRPC channels)."""
        with self._repl_cv:
            self._repl_stopped = True
            self._repl_cv.notify_all()
        for rep in self.replicas:
            for c in {id(rep.client): rep.client, id(rep.probe): rep.probe}.values():
                close = getattr(c, "close", None)
                if close is not None:
                    close()

    # --------------------------------------------------------------- verbs

    def apply_deltas(self, payload: dict) -> dict:
        return self._call("apply_deltas", payload)

    def schedule_batch(self, payload: dict) -> dict:
        return self._call("schedule_batch", payload)

    def heartbeat(self, payload: dict) -> dict:
        return self._call("heartbeat", payload)

    def health(self) -> dict:
        return self._call("health", None)

    def sessions_dump(self) -> dict:
        # read-only introspection, invoked from the /debug SERVING thread
        # (WireScheduler.debug_sessions): it must never run the failover/
        # probe machinery — the scheduling thread is the single failover
        # writer. A transport error surfaces to the debug body (the
        # caller renders it), not as a demotion.
        return self.active_replica().client.sessions_dump()

    # ------------------------------------------------------------- routing

    def active_replica(self) -> _Replica:
        with self._lock:
            return self.replicas[self._active]

    def active_endpoint(self) -> str:
        return self.active_replica().endpoint

    def _call(self, verb: str, payload: Optional[dict]):
        rep = self.active_replica()
        fn = getattr(rep.client, verb)
        try:
            # transport IO runs outside the fabric lock — see module doc
            out = fn(payload) if payload is not None else fn()
        except (StaleEpochError, ConflictError):
            # protocol verdicts from a HEALTHY service (restart detected /
            # ownership lost): the client's own recovery paths handle
            # them; they are not replica loss
            raise
        except DeviceServiceError as exc:
            new, probe_out = self._replica_lost(rep, verb, payload, exc)
            if verb == "health":
                # the promotion probe's answer IS a health answer: the
                # scheduler's half-open probe should see the live standby,
                # not a failed fabric (the batch proceeds and the epoch
                # protocol re-seeds on the next push)
                return probe_out
            raise FailoverError(
                f"device replica {rep.endpoint} lost "
                f"({type(exc).__name__}: {exc}); promoted standby "
                f"{new.endpoint} — next push re-seeds it via epoch resync",
                from_endpoint=rep.endpoint,
                to_endpoint=new.endpoint) from exc
        self._note_success(rep, verb, payload, out)
        self._maybe_probe_standbys()
        return out

    def _note_success(self, rep: _Replica, verb: str,
                      payload: Optional[dict], out: dict) -> None:
        rep.breaker.record_success()
        if isinstance(out, dict):
            rep.epoch = out.get("epoch", rep.epoch)
        if verb == "schedule_batch" and payload:
            rep.last_batch_id = payload.get("batchId", rep.last_batch_id)
        if self.replication_enabled:
            if verb == "apply_deltas" and payload:
                # the push the active just acknowledged is now part of the
                # primary's truth: fold it into the replication state and
                # wake the fan-out worker (off this thread's critical path)
                self._repl_fold(payload)
            elif verb == "heartbeat" and payload:
                # remember the scheduler client's identity so the worker
                # can keep ITS standby sessions warm too (satellite: a
                # silently expired standby lease would fence the first
                # post-failover commit)
                self._client_hb = payload.get("clientId") or self._client_hb
        if not rep.healthy:
            self._mark_health(rep, True)

    def _mark_health(self, rep: _Replica, up: bool) -> None:
        came_back = up and not rep.healthy
        rep.healthy = up
        if came_back:
            # a replica that was away holds an arbitrarily stale mirror:
            # the next replication push must re-seed it wholesale
            rep.repl_needs_full = True
        if self.metrics is not None:
            self.metrics.fabric_replica_health.set(rep.endpoint,
                                                   value=1 if up else 0)

    # ------------------------------------------------------------ failover

    def _replica_lost(self, rep: _Replica, verb: str,
                      payload: Optional[dict], exc: DeviceServiceError):
        """The active replica failed a call: mark it down, poison the
        in-flight batch, promote the first live standby. Returns
        ``(new_active, its_health_response)``; raises the ORIGINAL error
        when no standby answers (all replicas down — the scheduler's
        breaker owns the next rung of the ladder: oracle degrade).

        Concurrency: with the pipelined transport several lanes can fail
        on the same dead active at once. Exactly ONE runs the promotion;
        the rest wait for it and re-raise against the promoted standby —
        each caller's batch is still poisoned (flight event above), but
        the failover happens, and is counted, once."""
        rep.breaker.record_failure(exc)
        rep.last_error = f"{type(exc).__name__}: {exc}"
        self._mark_health(rep, False)
        batch_id = (payload or {}).get("batchId")
        telemetry.event("replica_down", endpoint=rep.endpoint, verb=verb,
                        lastBatchId=rep.last_batch_id,
                        error=str(exc)[:200])
        if batch_id:
            # the in-flight batch dies with its replica — the wire twin of
            # the in-process ring's poison-on-device-death: the scheduler
            # requeues the pods (idempotent batch ids mean nothing is
            # replayed; a fresh batch retries them after the resync)
            telemetry.event("poison", batchId=batch_id,
                            endpoint=rep.endpoint,
                            pods=len((payload or {}).get("pods") or ()),
                            error=str(exc)[:200])
        with self._lock:
            while self._failover_inprogress:
                # another lane is already promoting: wait it out (the cv
                # releases the lock), then judge against the result
                self._failover_cv.wait()
            cur = self.replicas[self._active]
            if cur is not rep and cur.healthy:
                # a concurrent lane already failed over: this batch just
                # dies against the new active (poisoned above, requeued by
                # the caller) — no second promotion, no double count
                return cur, None
            self._failover_inprogress = True
        try:
            promoted = self._promote_standby(rep)
        finally:
            with self._lock:
                self._failover_inprogress = False
                self._failover_cv.notify_all()
        if promoted is None:
            raise exc
        new, probe_out = promoted
        reason = ("permanent" if isinstance(exc, PermanentDeviceError)
                  else "transient")
        if self.metrics is not None:
            self.metrics.fabric_failovers.inc(reason)
        # ordered strictly after the poison of the last in-flight batch:
        # the postmortem reads "batch died, THEN the fabric moved on"
        telemetry.event("failover", fromEndpoint=rep.endpoint,
                        endpoint=new.endpoint, batchId=batch_id,
                        lastBatchId=rep.last_batch_id, reason=reason)
        return new, probe_out

    def _promote_standby(self, dead: _Replica):
        """Probe standbys (rotation order from the active) with the cheap
        Health verb; the first that answers becomes active. Probes run
        outside the lock; only the index flip is guarded. After the flip,
        wait for any in-flight replication push to the promoted replica to
        land — the replicator re-checks the active index before each push,
        so after this wait no stale replication content can ever overwrite
        what the scheduler client is about to resync."""
        with self._lock:
            start = self._active
        n = len(self.replicas)
        for k in range(1, n):
            cand = self.replicas[(start + k) % n]
            if cand is dead or not cand.breaker.allow():
                continue
            try:
                out = cand.probe.health()
            except DeviceServiceError as probe_exc:
                cand.breaker.record_failure(probe_exc)
                cand.last_error = (f"{type(probe_exc).__name__}: "
                                   f"{probe_exc}")
                self._mark_health(cand, False)
                continue
            cand.breaker.record_success()
            cand.epoch = out.get("epoch", cand.epoch)
            self._mark_health(cand, True)
            with self._lock:
                self._active = cand.index
                self.failovers += 1
                self.log.append({"t": self.now_fn(),
                                 "from": dead.endpoint,
                                 "to": cand.endpoint,
                                 "error": dead.last_error})
            # bounded wall-clock wait: a replication push that started
            # before the flip finishes its (probe-client, single-attempt)
            # call and sets the event; no NEW push can start — the worker
            # re-checks the active index under the fabric lock first
            cand.repl_idle.wait(timeout=10.0)
            if self.metrics is not None:
                self.metrics.fabric_active_replica.set(value=cand.index)
            return cand, out
        return None

    def _maybe_probe_standbys(self) -> None:
        """Rate-limited rejoin detection: probe DOWN standbys with Health.
        A replica that answers becomes a healthy standby again — never
        the active (sticky selection): adoption happens only through a
        failover, whose epoch-mismatch resync re-seeds the stale mirror."""
        with self._lock:
            now = self.now_fn()
            if now - self._last_probe < self.probe_interval_s:
                return
            self._last_probe = now
            active = self._active
        down = [r for r in self.replicas
                if not r.healthy and r.index != active]
        for rep in down:
            if not rep.breaker.allow():
                continue
            try:
                out = rep.probe.health()
            except DeviceServiceError as exc:
                rep.breaker.record_failure(exc)
                rep.last_error = f"{type(exc).__name__}: {exc}"
                continue
            rep.breaker.record_success()
            restarted = (rep.epoch is not None
                         and out.get("epoch") != rep.epoch)
            rep.epoch = out.get("epoch", rep.epoch)
            self._mark_health(rep, True)
            telemetry.event("replica_rejoin", endpoint=rep.endpoint,
                            restarted=restarted,
                            lastBatchId=rep.last_batch_id)

    # ------------------------------------------------- standby replication

    @staticmethod
    def _entry_name(entry: dict) -> Optional[str]:
        try:
            return entry["node"]["meta"]["name"]
        except (KeyError, TypeError):
            return None

    def _standby_targets(self) -> List[_Replica]:
        with self._lock:
            active = self._active
        return [r for r in self.replicas if r.index != active]

    def _repl_fold(self, payload: dict) -> None:
        """Fold one successfully-delivered delta push into the cumulative
        replication state (node name → newest wire entry) and mark the
        changed names dirty for every standby. Coalescing happens here: a
        node that changes five times while a standby lags ships ONCE. The
        caller is the scheduling thread — only dict/set work under the
        replicator lock, never IO."""
        targets = self._standby_targets()
        with self._repl_cv:
            full = bool(payload.get("full"))
            entries = payload.get("nodes") or ()
            pushed = set()
            for e in entries:
                name = self._entry_name(e)
                if name is None:
                    continue
                pushed.add(name)
                prev = self._repl_nodes.get(name)
                self._repl_nodes[name] = e
                if prev is None or prev.get("gen") != e.get("gen"):
                    for rep in targets:
                        rep.repl_dirty.add(name)
                        rep.repl_removed.discard(name)
            removed = list(payload.get("removed") or ())
            if full:
                # a full push IS the client's whole truth: names it omits
                # are gone (the server-side ghost sweep's replication twin)
                removed.extend(n for n in list(self._repl_nodes)
                               if n not in pushed)
            for name in removed:
                self._repl_nodes.pop(name, None)
                for rep in targets:
                    rep.repl_dirty.discard(name)
                    rep.repl_removed.add(name)
            for ns, labels in (payload.get("namespaces") or {}).items():
                self._repl_namespaces[ns] = dict(labels)
                for rep in targets:
                    rep.repl_ns_dirty.add(ns)
            self._repl_seq += 1
            self._repl_pending = True
            if (self._repl_worker_enabled
                    and (self._repl_thread is None
                         or not self._repl_thread.is_alive())):
                self._repl_thread = threading.Thread(
                    target=self._repl_run, name="ktpu-fabric-repl",
                    daemon=True)
                self._repl_thread.start()
            self._repl_cv.notify_all()

    def _repl_run(self) -> None:
        """Replication worker: fan the dirty suffix out to standbys when
        signaled; wake periodically for lease keep-warm heartbeats (gated
        by the injected clock, so FakeClock tests stay deterministic)."""
        while True:
            with self._repl_cv:
                if not self._repl_pending and not self._repl_stopped:
                    self._repl_cv.wait(timeout=0.5)
                if self._repl_stopped:
                    return
                self._repl_pending = False
            try:
                self.replication_flush()
            except Exception:  # noqa: BLE001 — the worker must survive surprises
                import logging

                logging.getLogger(__name__).exception(
                    "standby replication round failed")

    def replication_flush(self) -> int:
        """Run ONE replication round synchronously: push the pending dirty
        suffix (or a full seed) to every healthy standby, send keep-warm
        heartbeats, refresh the lag gauges. Called by the worker thread —
        and directly by tests that want deterministic replication without
        racing the wall clock. Returns the number of delta pushes made."""
        if not self.replication_enabled:
            return 0
        with self._repl_round_mutex:
            self.repl_rounds += 1
            pushes = 0
            now = self.now_fn()
            for rep in self._standby_targets():
                if not rep.healthy or now < rep.repl_backoff_until:
                    continue
                pushes += self._replicate_to(rep)
                self._repl_keep_warm(rep, now)
            self._update_repl_lag()
            return pushes

    def _replicate_to(self, rep: _Replica) -> int:
        """Push the pending dirty suffix (or a full seed) to one standby.
        State snapshot under the replicator lock; the transport call runs
        with NO traced lock held. The promote race is closed by the
        repl_idle event + active re-check (see _promote_standby)."""
        with self._repl_cv:
            full = rep.repl_needs_full
            if (not full and not rep.repl_dirty and not rep.repl_removed
                    and not rep.repl_ns_dirty
                    and rep.repl_synced_seq == self._repl_seq):
                return 0
            if full:
                entries = list(self._repl_nodes.values())
                removed: List[str] = []
                namespaces = {ns: dict(l)
                              for ns, l in self._repl_namespaces.items()}
                backup = None
            else:
                entries = [self._repl_nodes[n] for n in rep.repl_dirty
                           if n in self._repl_nodes]
                removed = [n for n in rep.repl_removed]
                namespaces = {ns: dict(self._repl_namespaces[ns])
                              for ns in rep.repl_ns_dirty
                              if ns in self._repl_namespaces}
                backup = (set(rep.repl_dirty), set(rep.repl_removed),
                          set(rep.repl_ns_dirty))
            rep.repl_dirty.clear()
            rep.repl_removed.clear()
            rep.repl_ns_dirty.clear()
            target_seq = self._repl_seq
        payload = {"apiVersion": API_VERSION, "nodes": entries,
                   "removed": removed, "namespaces": namespaces,
                   "clientId": self._repl_client_id, "replicator": True}
        if full:
            payload["full"] = True
        elif rep.repl_epoch:
            payload["expectEpoch"] = rep.repl_epoch
        if rep.repl_session_gen is not None:
            payload["sessionGen"] = rep.repl_session_gen
        # the promote race guard: no push may start once this replica is
        # the active (its truth now comes from the scheduler client)
        with self._lock:
            if self.replicas[self._active] is rep:
                self._repl_restore(rep, backup, full)
                return 0
            rep.repl_idle.clear()
        try:
            out = rep.probe.apply_deltas(payload)
        except StaleEpochError as exc:
            # the standby restarted under the replicator: reseed wholesale
            rep.repl_needs_full = True
            rep.repl_epoch = exc.epoch or None
            rep.repl_session_gen = None
            self._repl_signal()
            return 0
        except ConflictError:
            # the replicator's lease was fenced (it lagged past the TTL),
            # or the service fenced a LAPPED push (a direct client
            # full-resynced since our last contact — our incremental view
            # may name nodes the resync swept): rejoin under a fresh
            # session and reseed wholesale
            rep.repl_session_gen = None
            rep.repl_needs_full = True
            self._repl_signal()
            return 0
        except DeviceServiceError as exc:
            rep.repl_last_error = f"{type(exc).__name__}: {exc}"
            rep.repl_backoff_until = self.now_fn() + self.probe_interval_s
            self._repl_restore(rep, backup, full)
            return 0
        finally:
            rep.repl_idle.set()
        rep.repl_epoch = out.get("epoch", rep.repl_epoch)
        rep.repl_session_gen = out.get("sessionGen", rep.repl_session_gen)
        rep.repl_needs_full = False
        rep.repl_synced_seq = target_seq
        rep.repl_pushes += 1
        rep.repl_last_error = ""
        kind = "full" if full else "delta"
        if self.metrics is not None or telemetry.get() is not None:
            # payload volume as canonical JSON — a transport-independent
            # APPROXIMATION (gRPC framing/template dedup differs); the
            # O(dirty) promote evidence rides DeviceState upload bytes,
            # this counter only shows full-seed vs dirty-suffix shape.
            # Computed only when someone is listening (an O(cluster)
            # serialization per full seed otherwise).
            nbytes = len(json.dumps(payload).encode())
            if self.metrics is not None:
                self.metrics.standby_resync_bytes.inc(kind,
                                                      value=float(nbytes))
            telemetry.event("replication", endpoint=rep.endpoint,
                            seq=target_seq, nodes=len(entries),
                            removed=len(removed), full=full, bytes=nbytes)
        return 1

    def _repl_restore(self, rep: _Replica, backup, full: bool) -> None:
        """Give a failed round's dirty snapshot back (union — new dirt may
        have accrued meanwhile). A failed FULL push keeps needs_full."""
        with self._repl_cv:
            if full:
                rep.repl_needs_full = True
            elif backup is not None:
                dirty, removed, ns_dirty = backup
                rep.repl_dirty |= dirty
                rep.repl_removed |= removed
                rep.repl_ns_dirty |= ns_dirty

    def _repl_signal(self) -> None:
        with self._repl_cv:
            self._repl_pending = True
            self._repl_cv.notify_all()

    def _repl_keep_warm(self, rep: _Replica, now: float) -> None:
        """Lease keep-warm heartbeats to a standby: the replicator's own
        session (whose node claims keep the warm DeviceState alive through
        the promote-time ghost sweep) and the scheduler client's session
        (sessionGen-stripped — the standby owns its generation; a live
        lease is what prevents the first post-failover commit from being
        fenced). Rate-limited on the injected clock."""
        if now - rep.repl_hb_at < self.probe_interval_s:
            return
        rep.repl_hb_at = now
        for cid in (self._repl_client_id, self._client_hb):
            if not cid:
                continue
            payload = {"apiVersion": API_VERSION, "clientId": cid}
            if cid == self._repl_client_id:
                payload["replicator"] = True
                if rep.repl_session_gen is not None:
                    payload["sessionGen"] = rep.repl_session_gen
            try:
                out = rep.probe.heartbeat(payload)
            except ConflictError:
                if cid == self._repl_client_id:
                    rep.repl_session_gen = None
                continue
            except DeviceServiceError as exc:
                rep.repl_last_error = f"{type(exc).__name__}: {exc}"
                rep.repl_backoff_until = (self.now_fn()
                                          + self.probe_interval_s)
                return
            if cid == self._repl_client_id:
                rep.repl_session_gen = out.get("sessionGen",
                                               rep.repl_session_gen)

    def _update_repl_lag(self) -> None:
        if self.metrics is None:
            return
        with self._repl_cv:
            seq = self._repl_seq
        with self._lock:
            active = self._active
        for rep in self.replicas:
            lag = 0 if rep.index == active else max(
                0, seq - rep.repl_synced_seq)
            self.metrics.standby_replication_lag.set(rep.endpoint,
                                                     value=lag)

    def replication_lag(self, rep: _Replica) -> int:
        """Delta generations ``rep``'s mirror lags the primary stream."""
        with self._repl_cv:
            return max(0, self._repl_seq - rep.repl_synced_seq)

    # --------------------------------------------------------------- debug

    def dump(self) -> dict:
        """/debug/fabric body: replica table + bounded failover journal +
        the warm-standby replication state."""
        with self._lock:
            active = self._active
            failovers = self.failovers
            log = list(self.log)
        with self._repl_cv:
            repl_seq = self._repl_seq
        replicas = []
        for rep in self.replicas:
            replicas.append({
                "endpoint": rep.endpoint,
                "active": rep.index == active,
                "healthy": rep.healthy,
                "epoch": rep.epoch,
                "lastBatchId": rep.last_batch_id,
                "lastError": rep.last_error,
                "breaker": rep.breaker.dump(),
                "replication": {
                    "syncedSeq": rep.repl_synced_seq,
                    "lag": (0 if rep.index == active
                            else max(0, repl_seq - rep.repl_synced_seq)),
                    "needsFull": rep.repl_needs_full,
                    "pushes": rep.repl_pushes,
                    "lastError": rep.repl_last_error,
                },
            })
        return {
            "enabled": True,
            "active": self.replicas[active].endpoint,
            "activeIndex": active,
            "replicaCount": len(self.replicas),
            "failovers": failovers,
            "probeIntervalS": self.probe_interval_s,
            "replication": {
                "enabled": self.replication_enabled,
                "seq": repl_seq,
                "clientId": self._repl_client_id,
                "rounds": self.repl_rounds,  # ktpu: unguarded-ok(monotonic int counter; /debug introspection tolerates a torn read)
            },
            "replicas": replicas,
            "log": log,
        }
