from .device_state import DeviceState, caps_for_cluster  # noqa: F401
from .batch import build_schedule_batch_fn, schedule_batch  # noqa: F401
from .tpu_scheduler import TPUScheduler  # noqa: F401
