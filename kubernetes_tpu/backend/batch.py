"""The batched scheduling step — this framework's flagship compiled program.

One device call schedules a whole pod micro-batch against the node snapshot:

  1. STATIC phase (once per batch): selector-VM evaluation + the filter masks
     and score components that cannot change intra-batch (labels, taints,
     affinity, images — node properties no pod commit can alter).
  2. COMMIT phase: ``lax.scan`` over the batch in queue order. Each step
     computes the *dynamic* predicates (resource fit, ports) against the
     evolving carry, normalizes scores over that pod's feasible set, picks the
     winner (masked argmax + seeded uniform tie-break), and commits the pod's
     resources/ports to its node — the reference's assume (schedule_one.go:734)
     replayed inside the compiled program, which is what makes a K-pod batch
     conflict-free without host round-trips.

The scan's per-step work is O(N·R); the expensive [P,N]-shaped work stays in
the vectorized static phase. Sequential semantic parity: the winner for pod k
is chosen against exactly the state the reference's serial loop would see.

SPMD: the same program runs under ``shard_map`` with the node axis sharded
across a mesh (parallel/mesh.py). ``axis_name`` threads the three reduction
points through collectives — normalize-max (pmax), winner selection
(pmax + argmin-of-axis tie-break), and valid-node count (psum). Per scan step
that is a handful of scalar collectives over ICI — the P1/P7-style node-axis
sharding of SURVEY.md §2.7/§5.7, far cheaper than resharding score matrices.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import filters, scores
from ..ops.schema import ExprTable, NodeTensors, PodBatch
from ..ops.select import NEG_INF

# default plugin weights on the batched path (default_plugins.go:32-51; the
# spread/interpod components join in the sig-count extension)
DEFAULT_WEIGHTS = {
    "NodeResourcesBalancedAllocation": 1.0,
    "ImageLocality": 1.0,
    "NodeResourcesFit": 1.0,
    "NodeAffinity": 2.0,
    "TaintToleration": 3.0,
}


class BatchResult(NamedTuple):
    node_idx: jax.Array      # [P] int32 chosen GLOBAL slot, -1 = unschedulable
    best_score: jax.Array    # [P] float32
    any_feasible: jax.Array  # [P] bool
    static_masks: Dict[str, jax.Array]  # plugin name -> [P, N] (for diagnosis)
    fit_ok: jax.Array        # [P, N] resource fit at decision time
    ports_ok: jax.Array      # [P, N] port availability at decision time


def _pod_port_bits(pb: PodBatch, words: int) -> jax.Array:
    """[P, W] uint32: each pod's wanted-port ids as a bitset (for commit)."""
    P, MP = pb.port_ids.shape
    word_idx = (pb.port_ids >> 5).astype(jnp.int32)
    bit = jnp.where(pb.port_ids > 0, jnp.uint32(1) << (pb.port_ids & 31).astype(jnp.uint32), 0)
    out = jnp.zeros((P, words), jnp.uint32)
    # ids are deduplicated at encode time, so add == bitwise-or here
    return out.at[jnp.arange(P)[:, None], word_idx].add(bit)


def _gmax(x, axis_name):
    return x if axis_name is None else lax.pmax(x, axis_name)


def _gmin(x, axis_name):
    return x if axis_name is None else lax.pmin(x, axis_name)


def _gsum(x, axis_name):
    return x if axis_name is None else lax.psum(x, axis_name)


def _normalize(raw: jax.Array, feasible: jax.Array, reverse: bool, axis_name=None) -> jax.Array:
    """DefaultNormalizeScore over one pod's (global) feasible set."""
    masked = jnp.where(feasible, raw, 0.0)
    mx = _gmax(jnp.max(masked), axis_name)
    scaled = jnp.floor(raw * 100.0 / jnp.maximum(mx, 1.0))
    if reverse:
        return jnp.where(mx == 0, 100.0, 100.0 - scaled)
    return jnp.where(mx == 0, 0.0, scaled)


def schedule_batch_core(
    pb: PodBatch,
    et: ExprTable,
    nt: NodeTensors,
    key: jax.Array,
    weights_key: Tuple[Tuple[str, float], ...],
    axis_name: Optional[str] = None,
) -> BatchResult:
    """The traceable body; nt's node axis may be a shard (axis_name set)."""
    weights = dict(weights_key)
    N = nt.capacity  # local shard size under shard_map
    if axis_name is None:
        slot_offset = jnp.int32(0)
    else:
        slot_offset = (lax.axis_index(axis_name) * N).astype(jnp.int32)

    # ---- static phase -----------------------------------------------------
    expr_match = filters.eval_exprs(et, nt)
    if axis_name is not None:
        # OP_NODE_NAME compares against global slot ids: shift the local iota
        n_idx = jnp.arange(N, dtype=jnp.int32)[None, :] + slot_offset
        name_mask = (pb.node_name[:, None] == -1) | (pb.node_name[:, None] == n_idx)
    else:
        name_mask = filters.filter_node_name(pb, nt)
    static_masks = {
        "NodeUnschedulable": filters.filter_unschedulable(pb, nt),
        "NodeName": name_mask,
        "TaintToleration": filters.filter_taints(pb, nt),
        "NodeAffinity": filters.filter_node_affinity(pb, et, nt, expr_match),
    }
    static_ok = nt.valid[None, :] & pb.valid[:, None]
    for m in static_masks.values():
        static_ok = static_ok & m

    taint_raw = scores.score_taint_toleration(pb, nt)            # [P, N]
    affinity_raw = scores.score_node_affinity(pb, et, nt, expr_match)
    total_nodes = jnp.maximum(_gsum(jnp.sum(nt.valid), axis_name), 1)
    image_score = scores.score_image_locality(pb, nt, total_nodes=total_nodes)

    jitter = jax.random.uniform(key, (pb.capacity, N), jnp.float32, 0.0, 0.5)
    if axis_name is not None:
        # decorrelate jitter across shards
        jitter = jax.random.uniform(
            jax.random.fold_in(key, lax.axis_index(axis_name)),
            (pb.capacity, N), jnp.float32, 0.0, 0.5,
        )

    # ---- commit phase -----------------------------------------------------
    pod_bits = _pod_port_bits(pb, nt.port_bits.shape[1])
    alloc_f = nt.allocatable.astype(jnp.float32)                  # [N, R]

    def step(carry, xs):
        req_dyn, nz_dyn, port_dyn = carry
        (p_req, p_nz, p_static_ok, p_taint, p_aff, p_img, p_bits, p_jitter, p_valid) = xs

        free = nt.allocatable - req_dyn                           # [N, R]
        fit_ok = jnp.all((p_req[None, :] <= free) | (p_req[None, :] == 0), axis=-1)
        conflict = jnp.any(port_dyn & p_bits[None, :], axis=-1)
        ports_ok = ~conflict
        feasible = p_static_ok & fit_ok & ports_ok

        # resource scores against the evolving requested state
        nz_req = nz_dyn.astype(jnp.float32) + p_nz[None, :].astype(jnp.float32)
        cap0, cap1 = alloc_f[:, 0], alloc_f[:, 1]
        r0, r1 = nz_req[:, 0], nz_req[:, 1]
        la0 = jnp.where((cap0 == 0) | (r0 > cap0), 0.0, jnp.floor((cap0 - r0) * 100.0 / jnp.maximum(cap0, 1.0)))
        la1 = jnp.where((cap1 == 0) | (r1 > cap1), 0.0, jnp.floor((cap1 - r1) * 100.0 / jnp.maximum(cap1, 1.0)))
        least_alloc = jnp.floor((la0 + la1) / 2.0)
        f0 = jnp.where(cap0 == 0, 1.0, jnp.minimum(1.0, r0 / jnp.maximum(cap0, 1.0)))
        f1 = jnp.where(cap1 == 0, 1.0, jnp.minimum(1.0, r1 / jnp.maximum(cap1, 1.0)))
        balanced = jnp.floor((1.0 - jnp.abs(f0 - f1) / 2.0) * 100.0)

        total = (
            weights["NodeResourcesFit"] * least_alloc
            + weights["NodeResourcesBalancedAllocation"] * balanced
            + weights["TaintToleration"] * _normalize(p_taint, feasible, True, axis_name)
            + weights["NodeAffinity"] * _normalize(p_aff, feasible, False, axis_name)
            + weights["ImageLocality"] * p_img
        )
        eff = jnp.where(feasible, total + p_jitter, NEG_INF)
        local_idx = jnp.argmax(eff).astype(jnp.int32)
        local_best = eff[local_idx]
        any_feasible = _gmax(jnp.any(feasible), axis_name) & p_valid

        if axis_name is None:
            mine = jnp.bool_(True)
            global_idx = local_idx
            best = total[local_idx]
        else:
            global_best = _gmax(local_best, axis_name)
            axis = lax.axis_index(axis_name).astype(jnp.int32)
            winner_axis = _gmin(jnp.where(local_best >= global_best, axis, jnp.int32(2**30)), axis_name)
            mine = axis == winner_axis
            global_idx = _gsum(jnp.where(mine, local_idx + slot_offset, 0), axis_name).astype(jnp.int32)
            best = _gsum(jnp.where(mine, total[local_idx], 0.0), axis_name)

        commit = any_feasible & mine
        req_dyn = req_dyn.at[local_idx].add(jnp.where(commit, p_req, 0))
        nz_dyn = nz_dyn.at[local_idx].add(jnp.where(commit, p_nz, 0))
        port_dyn = port_dyn.at[local_idx].set(
            jnp.where(commit, port_dyn[local_idx] | p_bits, port_dyn[local_idx])
        )
        out_idx = jnp.where(any_feasible, global_idx, -1)
        return (req_dyn, nz_dyn, port_dyn), (out_idx, best, any_feasible, fit_ok, ports_ok)

    xs = (
        pb.req, pb.nonzero_req, static_ok, taint_raw, affinity_raw, image_score,
        pod_bits, jitter, pb.valid,
    )
    carry0 = (nt.requested, nt.nonzero_requested, nt.port_bits)
    _, (node_idx, best, any_feasible, fit_ok, ports_ok) = lax.scan(step, carry0, xs)

    return BatchResult(
        node_idx=node_idx,
        best_score=best,
        any_feasible=any_feasible,
        static_masks=static_masks,
        fit_ok=fit_ok,
        ports_ok=ports_ok,
    )


@functools.partial(jax.jit, static_argnames=("weights_key",))
def schedule_batch(
    pb: PodBatch,
    et: ExprTable,
    nt: NodeTensors,
    key: jax.Array,
    weights_key: Tuple[Tuple[str, float], ...] = tuple(sorted(DEFAULT_WEIGHTS.items())),
) -> BatchResult:
    return schedule_batch_core(pb, et, nt, key, weights_key)


def build_schedule_batch_fn(weights: Dict[str, float] = None):
    """Bind plugin weights statically; returns fn(pb, et, nt, key) -> BatchResult."""
    wk = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))

    def fn(pb, et, nt, key):
        return schedule_batch(pb, et, nt, key, weights_key=wk)

    return fn
