"""The batched scheduling step — this framework's flagship compiled program.

One device call schedules a whole pod micro-batch against the node snapshot:

  1. STATIC phase (once per batch): selector-VM evaluation + the filter masks
     and score components that cannot change intra-batch (labels, taints,
     affinity, images — node properties no pod commit can alter), plus the
     existing-term domain tables for inter-pod affinity (ops/topology.py).
  2. COMMIT phase: ``lax.scan`` over the batch in queue order. Each step
     computes the *dynamic* predicates (resource fit, ports, topology spread,
     inter-pod affinity) against the evolving carry, normalizes scores over
     that pod's feasible set, picks the winner (masked argmax + seeded uniform
     tie-break), and commits the pod's resources/ports/pod-set memberships to
     its node — the reference's assume (schedule_one.go:734) replayed inside
     the compiled program, which is what makes a K-pod batch conflict-free
     (including anti-affinity conflicts) without host round-trips.

The scan's per-step work is O(N·R + C·(N+Vd)); the expensive [P,N]-shaped work
stays in the vectorized static phase. Sequential semantic parity: the winner
for pod k is chosen against exactly the state the reference's serial loop
would see.

SPMD: the same program runs under ``shard_map`` with the node axis sharded
across a mesh (parallel/mesh.py). ``axis_name`` threads the reduction points
through collectives — normalize-max (pmax), winner selection (pmax +
argmin-of-axis tie-break), valid-node count (psum), and the per-step segment
tables (psum of small [C,Vd] partials) — the P1/P7-style node-axis sharding
of SURVEY.md §2.7/§5.7.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import filters, pallas_step, scores, topology
from ..ops.topology import INT_MAX, _gmax, _gmin, _gsum
from ..ops.schema import ExprTable, NodeTensors, PodBatch, TopoBatch, TopoCounts
from ..ops.select import NEG_INF


def _tb_dict(tb: TopoBatch) -> dict:
    """TopoBatch as the field dict the compiled programs consume (one
    definition shared by the scan xs and the speculative host path)."""
    return {f.name: getattr(tb, f.name) for f in dataclasses.fields(tb)}


def pallas_mode(nt: NodeTensors, axis_name, topo_enabled: bool) -> Optional[str]:
    """'compiled' | 'interpret' | None. KTPU_PALLAS=0 disables, =interpret
    forces the interpreter lowering (CPU tests of the kernel path). Read
    OUTSIDE jit and passed in as a static argument — env changes must
    retrace, not be swallowed by the jit cache."""
    import os

    flag = os.environ.get("KTPU_PALLAS", "auto")
    if flag == "0":
        return None
    if not pallas_step.shapes_supported(
        nt.capacity, nt.allocatable.shape[1], nt.port_bits.shape[1],
        axis_name, topo_enabled,
    ):
        return None
    if flag == "interpret":
        return "interpret"
    return "compiled" if pallas_step.compile_supported() else None

# ---------------------------------------------------------------------------
# DRA claim-feasibility mask (resource.k8s.io structured parameters)
#
# One vmapped predicate over the pod axis: every pod row carries its merged
# class+claim selectors as (key column, op, operand kind, operand) int32
# quadruples; the node axis carries the device-attribute table DeviceState
# syncs from node-published slices ([N, A] kind/value cells). The semantics
# are api/dra.py's DeviceSelector.matches, evaluated for all (pod, node)
# pairs in one device call — claim-bearing pods stay on the batched path
# instead of falling back to the sequential oracle.

from ..api.dra import OP_EQ, OP_GE, OP_GT, OP_LE, OP_LT, OP_NE  # noqa: E402


@jax.jit
def claim_feasibility_mask(sel_key: jax.Array, sel_op: jax.Array,
                           sel_kind: jax.Array, sel_val: jax.Array,
                           attr_kind: jax.Array, attr_val: jax.Array) -> jax.Array:
    """[P, N] bool: node attribute table satisfies every selector of each pod.

    sel_* : [P, S] int32 selector rows, op == -1 padding (always matches);
    attr_kind/attr_val : [N, A] device-attribute cells (kind 0 = absent,
    1 = int, 2 = interned string id). Single source of truth for the
    predicate: api/dra.py (host) — this is its vectorized transcription."""

    def one_pod(keys, ops, okind, oval):
        ak = attr_kind[:, keys]                      # [N, S]
        av = attr_val[:, keys]
        present = ak > 0
        same = present & (ak == okind[None, :])
        num = present & (ak == 1) & (okind[None, :] == 1)
        ov = oval[None, :]
        ok = jnp.where(ops[None, :] == OP_EQ, same & (av == ov), False)
        ok = jnp.where(ops[None, :] == OP_NE, same & (av != ov), ok)
        ok = jnp.where(ops[None, :] == OP_GE, num & (av >= ov), ok)
        ok = jnp.where(ops[None, :] == OP_GT, num & (av > ov), ok)
        ok = jnp.where(ops[None, :] == OP_LE, num & (av <= ov), ok)
        ok = jnp.where(ops[None, :] == OP_LT, num & (av < ov), ok)
        return jnp.all(jnp.where(ops[None, :] >= 0, ok, True), axis=1)  # [N]

    return jax.vmap(one_pod)(sel_key, sel_op, sel_kind, sel_val)


# ---------------------------------------------------------------------------
# gang all-or-nothing verdicts (PodGroup / Coscheduling)
#
# One device call per batch carrying gangs: gathers each gang's member rows
# out of the batch program's outputs (node_idx = the program's per-member
# choices, first_fail == 0 = decision-time feasibility) and runs the greedy
# distinct-node assigner (ops/gang.py). The host commit reads three small
# arrays instead of walking [P, N] masks per gang.


@jax.jit
def gang_verdicts(node_idx: jax.Array, first_fail: jax.Array,
                  member_idx: jax.Array, member_valid: jax.Array):
    """``member_idx`` [G, M] int32 rows into the batch pod axis (-1 pad),
    ``member_valid`` [G, M] bool. Returns (placed_all [G] bool — the batch
    program placed every member, the commit verdict; kernel_ok [G] bool —
    a distinct-node cover exists on the decision-time masks; assign [G, M]
    int32 — the greedy assignment, equal to the program's choices whenever
    they are distinct and feasible)."""
    from ..ops.gang import assign_gangs

    p = node_idx.shape[0]
    safe = jnp.clip(member_idx, 0, p - 1)
    feasible = (first_fail[safe] == 0) & member_valid[..., None]
    prefer = jnp.where(member_valid, node_idx[safe], jnp.int32(-1))
    assign, kernel_ok = assign_gangs(feasible, prefer, member_valid)
    placed_all = jnp.all((node_idx[safe] >= 0) | ~member_valid, axis=1)
    return placed_all, kernel_ok, assign


# default plugin weights on the batched path (default_plugins.go:32-51)
DEFAULT_WEIGHTS = {
    "NodeResourcesBalancedAllocation": 1.0,
    "ImageLocality": 1.0,
    "NodeResourcesFit": 1.0,
    "NodeAffinity": 2.0,
    "TaintToleration": 3.0,
    "PodTopologySpread": 2.0,
    "InterPodAffinity": 2.0,
}


class BatchResult(NamedTuple):
    node_idx: jax.Array      # [P] int32 chosen GLOBAL slot, -1 = unschedulable
    best_score: jax.Array    # [P] float32
    any_feasible: jax.Array  # [P] bool
    static_masks: Dict[str, jax.Array]  # plugin name -> [P, N] (for diagnosis)
    fit_ok: jax.Array        # [P, N] resource fit at decision time
    ports_ok: jax.Array      # [P, N] port availability at decision time
    spread_ok: jax.Array     # [P, N] PodTopologySpread filter at decision time
    ipa_ok: jax.Array        # [P, N] InterPodAffinity (all three checks)
    # [P, N] int8: 0 = feasible, else 1-based index into the filter config
    # order (tpu_scheduler._ATTRIBUTION_ORDER) of the first failing plugin.
    # Diagnosis on the host is ONE device→host read of this array instead of
    # eight mask reads — each read is a full relay round-trip on this TPU.
    first_fail: jax.Array
    # the scan's evolved carry: the post-batch dynamic node state. The host
    # adopts these (DeviceState.adopt_commits) so the next sync uploads
    # nothing for commit-only changes — and the async pipeline dispatches
    # batch k+1 directly on them (still-unmaterialized device futures) while
    # the host commits batch k.
    final_requested: Optional[jax.Array] = None      # [N, R] int32
    final_nonzero: Optional[jax.Array] = None        # [N, R] int32
    final_ports: Optional[jax.Array] = None          # [N, W] uint32
    # evolved topology carry (None on the pallas / topo-disabled paths)
    final_sel_counts: Optional[jax.Array] = None     # same shape as tc.sel_counts
    final_seg_exist: Optional[jax.Array] = None      # [T, Vd] int32
    # evolved priority-class table (preemption screen input), None on pallas
    final_class_req: Optional[jax.Array] = None      # [N, C, R] int32
    # evolved adaptive-sampling rotation start (None when sampling disabled)
    final_sample_start: Optional[jax.Array] = None   # [] int32
    # PACKED RESULT BLOCK: one contiguous [P, 1 + N/4] int32 array carrying
    # everything the host commit needs per pod — column 0 is node_idx, the
    # rest is the [P, N] int8 first_fail table bitcast to int32 words. The
    # scheduler issues copy_to_host_async() on THIS array at dispatch, so
    # landing a batch is a single already-overlapped transfer instead of
    # independent node_idx/first_fail materializations (each a full relay
    # round-trip on the axon TPU tunnel). None on the sharded core path
    # (mesh.py), whose callers keep the per-array reads.
    packed: Optional[jax.Array] = None               # [P, 1 + ceil(N/4)] int32


def pack_result_block(node_idx: jax.Array, first_fail: jax.Array,
                      slice_words: Optional[jax.Array] = None,
                      quota_words: Optional[jax.Array] = None) -> jax.Array:
    """[P, 1 + ceil(N/4) (+extras)] int32: node_idx in column 0, the int8
    first_fail rows bitcast into int32 words after it, then the optional
    trailing verdict columns in fixed order — slice words (see _slice_plan)
    when the batch carried slice gangs, quota words (ops/quota.py) when it
    carried screened namespaces. Traced into the batch program
    (schedule_batch's jit), so the packing is free relative to a transfer:
    one fused device buffer replaces independent node_idx/first_fail/
    verdict host reads."""
    p, n = first_fail.shape
    pad = (-n) % 4
    if pad:
        first_fail = jnp.pad(first_fail, ((0, 0), (0, pad)))
    words = lax.bitcast_convert_type(
        first_fail.reshape(p, (n + pad) // 4, 4), jnp.int32)
    cols = [node_idx[:, None], words]
    if slice_words is not None:
        cols.append(slice_words[:, None])
    if quota_words is not None:
        cols.append(quota_words[:, None])
    return jnp.concatenate(cols, axis=1)


def unpack_result_block(packed, n_nodes: int, quota_col: bool = False):
    """(node_idx [P] int32, first_fail [P, N] int8, slice_words [P] int32 or
    None, quota_words [P] int32 or None) from one materialized packed block.
    The np.asarray here is THE blocking device read of a batch commit;
    everything after is host-side reinterpretation (the int32→int8 view
    matches lax.bitcast_convert_type byte order on both CPU and TPU —
    pinned by tests/test_kernel_parity.py). Trailing-column presence is
    inferred from the block width — two extras mean slice THEN quota (the
    pack order); exactly one is the quota column iff the dispatcher passed
    quota args (``quota_col``, threaded from the dispatch site), else the
    slice column. Verdict-free batches pay nothing."""
    arr = np.asarray(packed)
    node_idx = arr[:, 0]
    ff_words = (n_nodes + 3) // 4
    extras = arr.shape[1] - 1 - ff_words
    slice_words = quota_words = None
    if extras >= 2:
        slice_words = arr[:, 1 + ff_words]
        quota_words = arr[:, 2 + ff_words]
    elif extras == 1:
        if quota_col:
            quota_words = arr[:, 1 + ff_words]
        else:
            slice_words = arr[:, 1 + ff_words]
    ff = np.ascontiguousarray(arr[:, 1:1 + ff_words]).view(np.int8)
    return (node_idx, ff.reshape(arr.shape[0], -1)[:, :n_nodes],
            slice_words, quota_words)


def _pod_port_bits(pb: PodBatch, words: int) -> jax.Array:
    """[P, W] uint32: each pod's wanted-port ids as a bitset (for commit)."""
    P, MP = pb.port_ids.shape
    word_idx = (pb.port_ids >> 5).astype(jnp.int32)
    # np.uint32, not jnp.uint32: an in-trace jax scalar becomes a captured
    # device-buffer constant, which the axon relay re-fetches every loop
    # iteration (see ops/select.py NEG_INF note)
    bit = jnp.where(pb.port_ids > 0, np.uint32(1) << (pb.port_ids & 31).astype(jnp.uint32), 0)
    out = jnp.zeros((P, words), jnp.uint32)
    # ids are deduplicated at encode time, so add == bitwise-or here
    return out.at[jnp.arange(P)[:, None], word_idx].add(bit)


def _normalize(raw: jax.Array, feasible: jax.Array, reverse: bool,
               axis_name=None, axis=None) -> jax.Array:
    """DefaultNormalizeScore over a pod's (global) feasible set. ``axis``
    batches it: per-row max instead of the global one (the speculative
    path's [P, N] form) — ONE implementation for both programs, whose
    outputs must match bit for bit."""
    masked = jnp.where(feasible, raw, 0.0)
    if axis is None:
        mx = _gmax(jnp.max(masked), axis_name)
    else:
        # batched form over a LOCAL node shard: per-row max, then the
        # cross-shard elementwise pmax (the speculative path under shard_map)
        mx = _gmax(jnp.max(masked, axis=axis, keepdims=True), axis_name)
    scaled = jnp.floor(raw * 100.0 / jnp.maximum(mx, 1.0))
    if reverse:
        return jnp.where(mx == 0, 100.0, 100.0 - scaled)
    return jnp.where(mx == 0, 0.0, scaled)


def _resource_scores(alloc2: jax.Array, nz_total: jax.Array):
    """(LeastAllocated, BalancedAllocation) over the first two resource
    columns — shared by the scan step ([N, 2] inputs) and the speculative
    rounds ([P, N, 2] via broadcasting); formulas per SURVEY §8."""
    cap0, cap1 = alloc2[..., 0], alloc2[..., 1]
    r0, r1 = nz_total[..., 0], nz_total[..., 1]
    la0 = jnp.where((cap0 == 0) | (r0 > cap0), 0.0,
                    jnp.floor((cap0 - r0) * 100.0 / jnp.maximum(cap0, 1.0)))
    la1 = jnp.where((cap1 == 0) | (r1 > cap1), 0.0,
                    jnp.floor((cap1 - r1) * 100.0 / jnp.maximum(cap1, 1.0)))
    least_alloc = jnp.floor((la0 + la1) / 2.0)
    f0 = jnp.where(cap0 == 0, 1.0, jnp.minimum(1.0, r0 / jnp.maximum(cap0, 1.0)))
    f1 = jnp.where(cap1 == 0, 1.0, jnp.minimum(1.0, r1 / jnp.maximum(cap1, 1.0)))
    balanced = jnp.floor((1.0 - jnp.abs(f0 - f1) / 2.0) * 100.0)
    return least_alloc, balanced


def _speculative_core(pb, nt, weights, static_ok, static_ff, taint_raw,
                      affinity_raw, image_score, pod_bits, jitter,
                      sel0, seg0, host=None, gen=None,
                      axis_name=None, slot_offset=None,
                      ports_enabled: bool = True) -> BatchResult:
    """Speculative decode for non-topology batches (ROADMAP r3 perf 2).

    The scan commits one pod per step — P dependent steps whose per-step
    latency dominates device time at large batches. This path replaces it
    with a few vectorized decide/commit rounds while reproducing the scan's
    sequential semantics EXACTLY:

    each round, every unplaced pod scores all nodes against the current
    state and picks its argmax; the per-node winners (lowest pod index) form
    a tentative set whose picks are pairwise-DISTINCT nodes. A pod is
    FINALIZABLE this round when it either fails (no feasible node — more
    commits can only shrink feasibility, so its sequential turn fails too),
    or wins its node AND no node committed by an earlier winner now beats
    its choice (commits can RAISE a node's score — balanced-allocation —
    so this stability check guards the argmax). The round then finalizes
    only the PREFIX of pods before the first active non-finalizable index:
    every finalized pod's visible state is exactly the commits of
    lower-index pods — the scan's sequential semantics, bit for bit. The
    next round's first active pod always finalizes (it wins its node by
    index-minimality and has no earlier rivals), so each round retires ≥1
    pod and the while_loop terminates in ≤P rounds (typically ~P/(first-
    conflict index) rounds: distinct jitter spreads identical pods).

    ``host`` (optional) extends the rounds to the HOSTNAME topology fast
    path (ops/topology.py *_host): every topology table is [*, N] node-
    local there, so the same rival-mix trick yields each pod's exact
    sequential view of spread/inter-pod-affinity state. Keys: the TopoBatch
    field dict, hostkey_ok [N], affinity_ok [P, N] (the NodeAffinity static
    mask the spread filter's eligibility uses).

    ``gen`` (optional, exclusive with ``host``) extends them to the GENERAL
    domain-aggregating mode: sel_counts stays node-local (rival-mix), and
    the domain segment sums recompute per pod from the mixed counts
    (vmapped segment sums over small [P, C, Vd] tables), so every
    sel-derived quantity is each pod's exact sequential view. The
    seg_exist table ([T, Vd], domain-level) cannot be rival-mixed; instead
    a winner whose view could be touched by an earlier winner's TERM commit
    (the committing pod carries a term that interacts with this pod —
    rare: intra-batch anti-affinity/symmetric-score coupling) is
    conservatively DEFERRED to the next round, where the committed tables
    are ground truth. Keys: tb dict, affinity_ok, vd, dom_t [T, N],
    label_val [N, L], valid [N]."""
    P = pb.capacity
    N = nt.capacity  # LOCAL shard size under shard_map
    alloc = nt.allocatable
    alloc_f = alloc.astype(jnp.float32)
    iota_p = jnp.arange(P, dtype=jnp.int32)
    iota_n = jnp.arange(N, dtype=jnp.int32)
    # ---- sharding seams (SURVEY §5.7: per-shard work + tiny collectives).
    # The rounds shard exactly like the scan: node-axis state is local, the
    # per-pod [P] decision vectors (choice/accepted/prefix cut) are made
    # globally consistent through elementwise pmax/pmin/psum, so every shard
    # runs the same number of rounds and finalizes the same prefix. The
    # HOSTNAME topology mode shards too — its tables are [*, N] node-local,
    # so rival-mixing is shard-local and only the per-pod reductions
    # (spread min-match, IPA totals, score normalization) psum/pmax across
    # shards. The general domain-aggregating mode keeps the scan on a mesh
    # (its segment tables are domain-global).
    # every topology mode shards: node-axis state is local; domain tables
    # psum to a replicated global view (_seg_pc); per-pod decisions are
    # made globally consistent below
    if slot_offset is None:
        slot_offset = np.int32(0)
    shard_axis = (lax.axis_index(axis_name).astype(jnp.int32)
                  if axis_name is not None else np.int32(0))

    def _gany_pods(x_bool):
        """[P] bool: any() across shards (elementwise)."""
        if axis_name is None:
            return x_bool
        return _gmax(x_bool.astype(jnp.int32), axis_name) > 0

    def _gpick(local_vals, mine, dtype=jnp.float32):
        """[P] owner-shard values → globally consistent [P] (one owner per
        pod: psum of the masked value)."""
        if axis_name is None:
            return local_vals
        return _gsum(jnp.where(mine, local_vals, jnp.zeros((), dtype)),
                     axis_name)

    def _gdom_of_choice(dom_table, local_choice, mine):
        """[T, P]: domain id of each pod's CHOSEN node. dom_table's node
        axis is shard-local, so the owner shard gathers and the result
        psums to every shard (the general mode's term-commit scatter and
        deferral matrices must see the same global domains everywhere)."""
        T = dom_table.shape[0]
        local = jnp.take_along_axis(
            dom_table,
            jnp.broadcast_to(local_choice[None, :], (T, local_choice.shape[0])),
            axis=1)
        if axis_name is None:
            return local
        return _gsum(jnp.where(mine[None, :], local, 0), axis_name)

    def _global_argmax(eff):
        """Per-pod argmax over the GLOBAL node axis: (choice in global slot
        ids, local column, mine[P] = this shard owns the winner). Ties
        resolve to the lowest shard then the local argmax — with the global
        jitter table this reproduces the single-device pick exactly."""
        local_idx = jnp.argmax(eff, axis=1).astype(jnp.int32)
        if axis_name is None:
            return local_idx, local_idx, jnp.ones((P,), bool)
        local_best = jnp.take_along_axis(eff, local_idx[:, None], 1)[:, 0]
        global_best = _gmax(local_best, axis_name)
        winner_axis = _gmin(
            jnp.where(local_best >= global_best, shard_axis, np.int32(2 ** 30)),
            axis_name)
        mine = winner_axis == shard_axis
        choice = _gsum(jnp.where(mine, local_idx + slot_offset, 0),
                       axis_name).astype(jnp.int32)
        return choice, local_idx, mine

    if axis_name is None:
        is_nom = iota_n[None, :] == pb.nominated[:, None]      # [P, N]
    else:
        is_nom = (iota_n[None, :] + slot_offset) == pb.nominated[:, None]
    w_fit = np.float32(weights["NodeResourcesFit"])
    w_bal = np.float32(weights["NodeResourcesBalancedAllocation"])
    w_taint = np.float32(weights["TaintToleration"])
    w_aff = np.float32(weights["NodeAffinity"])
    w_img = np.float32(weights["ImageLocality"])
    w_spread = np.float32(weights["PodTopologySpread"])
    w_ipa = np.float32(weights["InterPodAffinity"])
    def _mix_gather(base_table, delta_table, rows, rival):
        """Per-pod gathered counts with this round's earlier-winner column
        deltas applied on rival nodes — THE rival-mix formula, defined once
        for the host filters/scores and the gen segment paths."""
        base = base_table[rows]                                  # [P, C, N]
        if rival is None:
            return base
        return base + delta_table[rows] * rival[:, None, :]

    def _spread_norm(raw, base_mask, ignored, has_cons):
        """Spread score normalization (scoring.go:232-271), shared by the
        host and general batched paths (must stay bit-identical). Under
        shard_map the per-pod max/min reduce over the GLOBAL node axis."""
        mx = _gmax(jnp.max(jnp.where(base_mask, raw, -jnp.inf),
                           axis=1, keepdims=True), axis_name)
        mn = _gmin(jnp.min(jnp.where(base_mask, raw, jnp.inf),
                           axis=1, keepdims=True), axis_name)
        any_base = _gany_pods(jnp.any(base_mask, axis=1, keepdims=True))
        norm = jnp.where(mx == 0, 100.0,
                         jnp.floor(100.0 * (mx + mn - raw) / jnp.maximum(mx, 1.0)))
        norm = jnp.where(ignored | ~any_base, 0.0, norm)
        return jnp.where(has_cons, norm, 0.0)

    def _ipa_norm(raw, feasible):
        """IPA score normalization (clamped min/max), shared likewise."""
        mx = jnp.maximum(
            _gmax(jnp.max(jnp.where(feasible, raw, -jnp.inf),
                          axis=1, keepdims=True), axis_name),
            0.0)
        mn = jnp.minimum(
            _gmin(jnp.min(jnp.where(feasible, raw, jnp.inf),
                          axis=1, keepdims=True), axis_name),
            0.0)
        diff = mx - mn
        return jnp.where(
            diff > 0, jnp.floor(100.0 * (raw - mn) / jnp.maximum(diff, 1.0)), 0.0)

    if host is not None:
        tbx, hostkey_ok, affinity_ok = (
            host["tb"], host["hostkey_ok"], host["affinity_ok"])
        sig_mask_f = tbx["pod_sig_mask"].astype(jnp.int32)      # [P, S]
        term_mask_f = tbx["pod_term_mask"].astype(jnp.int32)    # [P, T]
        hk_f = hostkey_ok.astype(jnp.int32)                     # [N]
    if gen is not None:
        tbx, affinity_ok = gen["tb"], gen["affinity_ok"]
        vd = gen["vd"]
        dom_t = gen["dom_t"]                                    # [T, N]
        label_val = gen["label_val"]                            # [N, L]
        valid_n = gen["valid"]                                  # [N]
        sig_mask_f = tbx["pod_sig_mask"].astype(jnp.int32)      # [P, S]
        term_mask_f = tbx["pod_term_mask"].astype(jnp.int32)    # [P, T]

        def _dom_of(keys):
            # [P, C, N]: domain id of node n under each constraint's key
            return label_val.T[keys]                            # gather rows

        def _seg_pc(values, dom):
            """[P, C, N] values segment-summed by [P, C, N] domain ids →
            [P, C, Vd] (the per-pod batched _seg_sum). Under shard_map the
            node axis is a local slice, so the per-domain sums psum across
            shards — the result is the GLOBAL domain table, replicated."""
            seg = jax.vmap(jax.vmap(
                lambda v, d: jax.ops.segment_sum(v, d, num_segments=vd)))(
                    values, dom)
            return _gsum(seg, axis_name)


    def topo_eval(sel_view, term_view, rival, active):
        """Host-mode spread/IPA filters from a (possibly per-pod mixed)
        view: sel_view/term_view = (base [S|T, N], round-delta [S|T, N]);
        rival [P, N] selects where the delta applies (None = base only)."""
        sel_base, sel_d = sel_view
        term_base, term_d = term_view


        valid_n = nt.valid
        # ---- spread filter (topology.spread_filter_host)
        elig = valid_n[None, :] & affinity_ok & hostkey_ok[None, :] \
            & active[:, None]                                    # [P, N]
        # NOTE: the scan's elig has no `active` term — it is per-pod anyway;
        # masking by active only skips work for done pods (their rows are
        # never read) and keeps reductions well-defined.
        cnt_sf = _mix_gather(sel_base, sel_d, tbx["sf_sig"], rival)           # [P, C, N]
        # global reductions over the (possibly sharded) node axis
        minm = _gmin(jnp.min(jnp.where(elig[:, None, :], cnt_sf, INT_MAX),
                             axis=2), axis_name)
        ndom = _gsum(jnp.sum(elig.astype(jnp.int32), axis=1), axis_name)  # [P]
        any_pres = ndom > 0
        minm = jnp.where(any_pres[:, None], minm, 0)
        minm = jnp.where((tbx["sf_min_domains"] >= 0)
                         & (ndom[:, None] < tbx["sf_min_domains"]), 0, minm)
        ok_c = hostkey_ok[None, None, :] & (
            cnt_sf + tbx["sf_self"][:, :, None].astype(jnp.int32)
            - minm[:, :, None] <= tbx["sf_skew"][:, :, None])
        spread_ok = jnp.all(
            jnp.where(tbx["sf_valid"][:, :, None], ok_c, True), axis=1)

        # ---- IPA filter (topology.ipa_filter_host)
        cnt_ia = _mix_gather(sel_base, sel_d, tbx["ia_sig"], rival)           # [P, A, N]
        exist = hostkey_ok[None, None, :] & (cnt_ia > 0)
        ia_valid = tbx["ia_valid"]
        pods_exist = jnp.all(
            jnp.where(ia_valid[:, :, None], exist, True), axis=1)
        all_keys = jnp.all(
            jnp.where(ia_valid[:, :, None], hostkey_ok[None, None, :], True),
            axis=1)
        tot_mask = (ia_valid[:, :, None] & valid_n[None, None, :]
                    & hostkey_ok[None, None, :])
        total = _gsum(jnp.sum(jnp.where(tot_mask, cnt_ia, 0), axis=(1, 2)),
                      axis_name)  # [P], global over shards
        first_ok = (total == 0) & tbx["ia_self_all"]
        has_terms = jnp.any(ia_valid, axis=1)
        aff_ok = (~has_terms[:, None]) | (
            all_keys & (pods_exist | first_ok[:, None]))
        cnt_an = _mix_gather(sel_base, sel_d, tbx["ianti_sig"], rival)        # [P, A, N]
        viol = jnp.any(tbx["ianti_valid"][:, :, None]
                       & hostkey_ok[None, None, :] & (cnt_an > 0), axis=1)
        anti_ok = ~viol
        # existing-term anti check: [P,T]x[T,N] matmuls keep the [P,T,N]
        # tensor virtual (T can be large)
        m = tbx["term_filter_match"].astype(jnp.int32)           # [P, T]
        viol_cnt = m @ (term_base * hk_f[None, :])
        if rival is not None:
            viol_cnt = viol_cnt + (m @ (term_d * hk_f[None, :])) * rival
        exist_ok = viol_cnt == 0
        ipa_ok = aff_ok & anti_ok & exist_ok
        return spread_ok, ipa_ok

    def topo_scores(sel_view, term_view, rival, feasible):
        """Host-mode spread/IPA scores (topology.spread_score_host /
        ipa_score_host) against the same view, normalized per pod over its
        feasible set."""
        sel_base, sel_d = sel_view
        term_base, term_d = term_view


        # spread score
        ignored = tbx["ss_require_all"][:, None] & ~hostkey_ok[None, :]
        base_mask = feasible & ~ignored                          # [P, N]
        n_base = _gsum(jnp.sum(base_mask.astype(jnp.int32), axis=1),
                       axis_name)                                # [P] global
        w = jnp.log(n_base.astype(jnp.float32) + 2.0)[:, None]   # [P, 1]
        cnt_ss = _mix_gather(sel_base, sel_d, tbx["ss_sig"], rival).astype(jnp.float32)        # [P, C, N]
        contrib = jnp.where(
            tbx["ss_valid"][:, :, None] & hostkey_ok[None, None, :],
            cnt_ss * w[:, :, None]
            + (tbx["ss_skew"][:, :, None].astype(jnp.float32) - 1.0),
            0.0)
        raw = jnp.floor(jnp.sum(contrib, axis=1) + 0.5)          # [P, N]
        spread_score = _spread_norm(
            raw, base_mask, ignored, jnp.any(tbx["ss_valid"], axis=1)[:, None])

        # IPA score
        cnt_ip = _mix_gather(sel_base, sel_d, tbx["ip_sig"], rival).astype(jnp.float32)        # [P, PT, N]
        pref = jnp.sum(
            jnp.where(tbx["ip_valid"][:, :, None] & hostkey_ok[None, None, :],
                      tbx["ip_w"][:, :, None].astype(jnp.float32) * cnt_ip,
                      0.0),
            axis=1)                                              # [P, N]
        tsw = tbx["term_score_w"]                                # [P, T] f32
        hk_ff = hk_f.astype(jnp.float32)
        sym = tsw @ (term_base.astype(jnp.float32) * hk_ff[None, :])
        if rival is not None:
            sym = sym + (tsw @ (term_d.astype(jnp.float32)
                                * hk_ff[None, :])) * rival
        raw_ip = pref + sym
        return spread_score, _ipa_norm(raw_ip, feasible)

    def topo_eval_gen(sel_view, seg_base, rival, active):
        """General-mode spread/IPA filters, batched over pods: every
        sel-derived quantity recomputes from the (rival-mixed) per-pod
        counts, matching topology.spread_filter/ipa_filter exactly. The
        seg_exist check (existing pods' anti-affinity vs the incoming pod)
        is evaluated against the ROUND-START table; rounds where that could
        diverge defer the affected winners (term-interaction deferral in
        body())."""
        sel_base, sel_d = sel_view

        # ---- spread filter (topology.spread_filter)
        dom_sf = _dom_of(tbx["sf_key"])                          # [P, C, N]
        has_key = dom_sf > 0
        has_all = jnp.all(jnp.where(tbx["sf_valid"][:, :, None], has_key, True),
                          axis=1)                                # [P, N]
        elig = valid_n[None, :] & affinity_ok & has_all & active[:, None]
        cnts = _mix_gather(sel_base, sel_d, tbx["sf_sig"], rival)
        add = jnp.where(elig[:, None, :] & has_key, cnts, 0)
        seg = _seg_pc(add, dom_sf)                               # [P, C, Vd]
        pres = _seg_pc(jnp.broadcast_to(
            elig[:, None, :], dom_sf.shape).astype(jnp.int32), dom_sf) > 0
        minm = jnp.min(jnp.where(pres, seg, INT_MAX), axis=2)    # [P, C]
        any_pres = jnp.any(pres, axis=2)
        minm = jnp.where(any_pres, minm, 0)
        ndom = jnp.sum(pres.astype(jnp.int32), axis=2)
        minm = jnp.where((tbx["sf_min_domains"] >= 0)
                         & (ndom < tbx["sf_min_domains"]), 0, minm)
        cnt_at = jnp.take_along_axis(seg, dom_sf, axis=2)        # [P, C, N]
        ok_c = has_key & (cnt_at + tbx["sf_self"][:, :, None].astype(jnp.int32)
                          - minm[:, :, None] <= tbx["sf_skew"][:, :, None])
        spread_ok = jnp.all(
            jnp.where(tbx["sf_valid"][:, :, None], ok_c, True), axis=1)

        # ---- IPA filter checks 1+2 (topology.ipa_filter)
        dom_ia = _dom_of(tbx["ia_key"])
        ia_has_key = dom_ia > 0
        ia_valid = tbx["ia_valid"]
        cnts_ia = _mix_gather(sel_base, sel_d, tbx["ia_sig"], rival)
        add_ia = jnp.where(valid_n[None, None, :] & ia_has_key, cnts_ia, 0)
        seg_ia = _seg_pc(add_ia, dom_ia)                         # [P, A, Vd]
        cnt_at_ia = jnp.take_along_axis(seg_ia, dom_ia, axis=2)
        exist = cnt_at_ia > 0
        pods_exist = jnp.all(jnp.where(ia_valid[:, :, None], exist, True), axis=1)
        all_keys = jnp.all(jnp.where(ia_valid[:, :, None], ia_has_key, True),
                           axis=1)
        total = jnp.sum(jnp.where(ia_valid[:, :, None], seg_ia, 0), axis=(1, 2))
        first_ok = (total == 0) & tbx["ia_self_all"]
        has_terms = jnp.any(ia_valid, axis=1)
        aff_ok = (~has_terms[:, None]) | (
            all_keys & (pods_exist | first_ok[:, None]))

        dom_an = _dom_of(tbx["ianti_key"])
        an_has_key = dom_an > 0
        cnts_an = _mix_gather(sel_base, sel_d, tbx["ianti_sig"], rival)
        add_an = jnp.where(valid_n[None, None, :] & an_has_key, cnts_an, 0)
        seg_an = _seg_pc(add_an, dom_an)
        an_cnt = jnp.take_along_axis(seg_an, dom_an, axis=2)
        viol = jnp.any(tbx["ianti_valid"][:, :, None] & an_has_key
                       & (an_cnt > 0), axis=1)
        anti_ok = ~viol

        # ---- IPA check 3 against the ROUND-START seg_exist (deferral
        # covers the divergence window)
        exist_at = jnp.where(dom_t > 0,
                             jnp.take_along_axis(seg_base, dom_t, axis=1), 0)  # [T,N]
        m = tbx["term_filter_match"].astype(jnp.int32)           # [P, T]
        viol_cnt = m @ exist_at
        exist_ok = viol_cnt == 0
        ipa_ok = aff_ok & anti_ok & exist_ok
        return spread_ok, ipa_ok, exist_at

    def topo_scores_gen(sel_view, exist_at, rival, feasible):
        """General-mode spread/IPA scores (topology.spread_score/ipa_score),
        batched; the symmetric existing-term score uses the round-start
        exist_at (deferral covers divergence)."""
        sel_base, sel_d = sel_view

        # spread score
        dom_ss = _dom_of(tbx["ss_key"])                          # [P, C, N]
        has_key = dom_ss > 0
        ss_valid = tbx["ss_valid"]
        has_all = jnp.all(jnp.where(ss_valid[:, :, None], has_key, True), axis=1)
        require_all = tbx["ss_require_all"][:, None]             # [P, 1]
        ignored = require_all & ~has_all                         # [P, N]
        base_mask = feasible & ~ignored
        pres = _seg_pc(jnp.broadcast_to(
            base_mask[:, None, :], dom_ss.shape).astype(jnp.int32), dom_ss) > 0
        sz = jnp.sum(pres.astype(jnp.int32), axis=2)             # [P, C]
        n_base = _gsum(jnp.sum(base_mask.astype(jnp.int32), axis=1),
                       axis_name)                                # [P] global
        sz = jnp.where(tbx["ss_hostname"], n_base[:, None], sz)
        w = jnp.log(sz.astype(jnp.float32) + 2.0)                # [P, C]
        elig = (valid_n[None, :] & affinity_ok
                & jnp.where(require_all, has_all, True))         # [P, N]
        cnts = _mix_gather(sel_base, sel_d, tbx["ss_sig"], rival)
        add = jnp.where(elig[:, None, :] & has_key, cnts, 0)
        seg = _seg_pc(add, dom_ss)
        cnt_at = jnp.take_along_axis(seg, dom_ss, axis=2)
        cnt = jnp.where(tbx["ss_hostname"][:, :, None], cnts, cnt_at) \
            .astype(jnp.float32)
        contrib = jnp.where(
            ss_valid[:, :, None] & has_key,
            cnt * w[:, :, None]
            + (tbx["ss_skew"][:, :, None].astype(jnp.float32) - 1.0),
            0.0)
        raw = jnp.floor(jnp.sum(contrib, axis=1) + 0.5)          # [P, N]
        spread_score = _spread_norm(
            raw, base_mask, ignored, jnp.any(ss_valid, axis=1)[:, None])

        # IPA score
        dom_ip = _dom_of(tbx["ip_key"])
        ip_has_key = dom_ip > 0
        cnts_ip = _mix_gather(sel_base, sel_d, tbx["ip_sig"], rival)
        add_ip = jnp.where(valid_n[None, None, :] & ip_has_key, cnts_ip, 0)
        seg_ip = _seg_pc(add_ip, dom_ip)
        cnt_at_ip = jnp.take_along_axis(seg_ip, dom_ip, axis=2).astype(jnp.float32)
        pref = jnp.sum(
            jnp.where(tbx["ip_valid"][:, :, None] & ip_has_key,
                      tbx["ip_w"][:, :, None].astype(jnp.float32) * cnt_at_ip,
                      0.0),
            axis=1)
        sym = tbx["term_score_w"] @ exist_at.astype(jnp.float32)  # [P, N]
        raw_ip = pref + sym
        return spread_score, _ipa_norm(raw_ip, feasible)

    def components(req_dyn, nz_dyn, port_dyn):
        """State-dependent per-(pod,node) pieces: (fit, ports, la, balanced)."""
        free = alloc[None, :, :] - req_dyn[None, :, :]          # broadcast [P]
        fit = jnp.all((pb.req[:, None, :] <= free) | (pb.req[:, None, :] == 0),
                      axis=-1)                                   # [P, N]
        if ports_enabled:
            conflict = jnp.any(port_dyn[None, :, :] & pod_bits[:, None, :],
                               axis=-1)
            ports = ~conflict
        else:
            # no pod in the batch wants a host port: the [P, N, W] conflict
            # tensor (the single largest intermediate in the round) is a
            # constant — skip it at trace time
            ports = jnp.ones(fit.shape, bool)
        nz = nz_dyn[None, :, :2].astype(jnp.float32) \
            + pb.nonzero_req[:, None, :2].astype(jnp.float32)    # [P, N, 2]
        least_alloc, balanced = _resource_scores(alloc_f[None, :, :2], nz)
        return fit, ports, least_alloc, balanced

    def assemble(fit, ports, least_alloc, balanced, active,
                 sel_view=None, term_view=None, rival=None):
        """(eff incl. jitter+nominated boost, feasible, total, spread_ok,
        ipa_ok) from the components — per-pod DefaultNormalizeScore over the
        feasible set; host mode adds the topology filters to feasibility and
        the topology scores to the total (same order as the scan step)."""
        feasible = static_ok & fit & ports & active[:, None]
        exist_at = None
        if host is not None:
            spread_ok, ipa_ok = topo_eval(sel_view, term_view, rival, active)
            feasible = feasible & spread_ok & ipa_ok
        elif gen is not None:
            spread_ok, ipa_ok, exist_at = topo_eval_gen(
                sel_view, term_view[0], rival, active)
            feasible = feasible & spread_ok & ipa_ok
        else:
            spread_ok = ipa_ok = None
        taint_n = _normalize(jnp.broadcast_to(taint_raw, feasible.shape),
                             feasible, True, axis_name=axis_name, axis=1)
        aff_n = _normalize(jnp.broadcast_to(affinity_raw, feasible.shape),
                           feasible, False, axis_name=axis_name, axis=1)
        total = (w_fit * least_alloc + w_bal * balanced + w_taint * taint_n
                 + w_aff * aff_n + w_img * image_score)
        if host is not None:
            sp_s, ip_s = topo_scores(sel_view, term_view, rival, feasible)
            total = total + w_spread * sp_s + w_ipa * ip_s
        elif gen is not None:
            sp_s, ip_s = topo_scores_gen(sel_view, exist_at, rival, feasible)
            total = total + w_spread * sp_s + w_ipa * ip_s
        eff = jnp.where(feasible, total + jitter + is_nom * np.float32(1e7),
                        NEG_INF)
        return eff, feasible, total, spread_ok, ipa_ok

    def body(carry):
        (req_dyn, nz_dyn, port_dyn, sel_dyn, term_dyn, done, out_idx, best,
         anyf_out, fit_out, ports_out, spread_out, ipa_out, ff_out,
         _progress) = carry
        active = ~done & pb.valid
        fit, ports, la, bal = components(req_dyn, nz_dyn, port_dyn)
        eff, feasible, total, _sp, _ip = assemble(
            fit, ports, la, bal, active,
            sel_view=(sel_dyn, None), term_view=(term_dyn, None))
        any_f = _gany_pods(jnp.any(feasible, axis=1))           # [P]
        choice, local_choice, mine = _global_argmax(eff)        # [P] global ids
        failing = active & ~any_f

        # ---- tentative winners: lowest pod index per chosen node (each
        # node lives on exactly one shard, so the per-node min and the
        # winner check run on the owner shard; the accepted vector is then
        # made globally consistent)
        contender = active & any_f
        win = jnp.full((N,), P, jnp.int32).at[local_choice].min(
            jnp.where(contender & mine, iota_p, P))
        accepted = _gany_pods(contender & mine & (win[local_choice] == iota_p))

        # ---- exact stability: rebuild each winner i's SEQUENTIAL view.
        # The only nodes whose state differs at i's sequential turn are the
        # RIVALS (nodes committed this round by winners j<i, each carrying
        # exactly its own delta — picks are distinct; in host mode the
        # topology tables are node-local too, so the same rival masking
        # covers sel_counts/term_counts). Mixing post-commit components on
        # rival nodes with round-start components elsewhere, then re-running
        # the per-pod normalization (whose max couples every node's score to
        # the feasible SET), reproduces the scan's exact eff surface for pod
        # i; the winner finalizes only if its argmax is unmoved.
        # local one-hot: only the shard owning a winner's node applies its
        # delta (mine); rival columns are node-local so each shard mixes
        # exactly its own nodes' post-commit state
        onehot = ((iota_n[None, :] == local_choice[:, None])
                  & accepted[:, None] & mine[:, None])           # [P, N_local]
        d_req = jnp.sum(onehot[:, :, None] * pb.req[:, None, :], axis=0)
        d_nz = jnp.sum(onehot[:, :, None] * pb.nonzero_req[:, None, :], axis=0)
        committed_any = jnp.any(onehot, axis=0)                  # [N]
        if ports_enabled:
            d_ports = jnp.sum(
                jnp.where(onehot[:, :, None], pod_bits[:, None, :], 0),
                axis=0).astype(jnp.uint32)
            port_mixed = port_dyn | d_ports
        else:
            port_mixed = port_dyn
        fit2, ports2, la2, bal2 = components(
            req_dyn + d_req, nz_dyn + d_nz, port_mixed)
        rival = committed_any[None, :] & (win[None, :] < iota_p[:, None])
        topo_on = host is not None or gen is not None
        if topo_on:
            onehot_i = onehot.astype(jnp.int32)
            csig = jnp.einsum("ps,pn->sn", sig_mask_f, onehot_i)
            cterm = (jnp.einsum("pt,pn->tn", term_mask_f, onehot_i)
                     if host is not None else None)
        else:
            csig = cterm = None
        fit_mix = jnp.where(rival, fit2, fit)
        ports_mix = jnp.where(rival, ports2, ports)
        eff_mix, feas_mix, tot_mix, sp_mix, ip_mix = assemble(
            fit_mix, ports_mix,
            jnp.where(rival, la2, la), jnp.where(rival, bal2, bal), active,
            sel_view=(sel_dyn, csig), term_view=(term_dyn, cterm),
            rival=rival.astype(jnp.int32) if topo_on else None)
        choice_mix, _local_mix, _mine_mix = _global_argmax(eff_mix)
        chosen_feas_mix = _gany_pods(
            mine & jnp.take_along_axis(feas_mix, local_choice[:, None], 1)[:, 0])
        # ~chosen_feas_mix guards the degenerate all-infeasible mix (IPA's
        # first-pod rule can flip globally): argmax over an all-NEG_INF row
        # returns 0, which would read as "stable" for a pod whose round-
        # start choice was slot 0. An infeasible-in-mix winner defers and
        # re-evaluates (usually failing) next round.
        unstable = accepted & ((choice_mix != choice) | ~chosen_feas_mix)
        if gen is not None:
            # seg_exist deferral: the mixed view evaluates existing-term
            # state against the ROUND-START table, so a winner i whose
            # filters/scores could be touched by an earlier winner j's TERM
            # commit must wait a round. add_term[t, j] = does accepted j's
            # commit add term t at a keyed domain; interaction = pod i's
            # anti-match or symmetric-score weight on that term.
            dcol = _gdom_of_choice(dom_t, local_choice, mine)    # [T, P]
            add_term = (term_mask_f.T * (dcol > 0)
                        * accepted[None, :].astype(jnp.int32))   # [T, P]
            m_int = tbx["term_filter_match"].astype(jnp.int32)   # [P, T]
            w_abs = jnp.abs(tbx["term_score_w"])                 # [P, T]
            interacts = ((m_int @ add_term) > 0) | (
                (w_abs @ add_term.astype(jnp.float32)) > 0)      # [P(i), P(j)]
            j_lt_i = iota_p[None, :] < iota_p[:, None]
            deferred = jnp.any(interacts & j_lt_i, axis=1)
            unstable = unstable | (accepted & deferred)
        # decision-time rows for the outputs: mixed values ARE each pod's
        # sequential view (for failing pods rival is empty, so mix ==
        # round-start — exact either way)
        ff_mix = static_ff
        ff_mix = jnp.where((ff_mix == 0) & ~ports_mix, np.int8(5), ff_mix)
        ff_mix = jnp.where((ff_mix == 0) & ~fit_mix, np.int8(6), ff_mix)
        if host is not None or gen is not None:
            ff_mix = jnp.where((ff_mix == 0) & ~sp_mix, np.int8(7), ff_mix)
            ff_mix = jnp.where((ff_mix == 0) & ~ip_mix, np.int8(8), ff_mix)

        # ---- strict prefix finalization: a pod may finalize only when every
        # lower-index active pod finalizes too, so each finalized pod's
        # visible state is exactly the commits of lower-index pods (the
        # scan's sequential contract). A failing pod's recorded masks are
        # round-start state, so it may only finalize BEFORE the round's
        # first winner (otherwise its decision-time state would include
        # same-round commits the masks don't show) — it retries next round,
        # where it is first and exact. The cut lands at the first active
        # non-finalizable index.
        a_min = jnp.min(jnp.where(accepted, iota_p, P))
        failing = failing & (iota_p < a_min)
        finalizable = failing | (accepted & ~unstable)
        blocked = active & ~finalizable
        cut = jnp.min(jnp.where(blocked, iota_p, P))
        in_prefix = iota_p < cut
        failing = failing & in_prefix
        accepted = accepted & ~unstable & in_prefix

        # ---- apply the finalized prefix (local one-hot: owner shard only)
        onehot = ((iota_n[None, :] == local_choice[:, None])
                  & accepted[:, None] & mine[:, None])
        req_dyn = req_dyn + jnp.sum(onehot[:, :, None] * pb.req[:, None, :], axis=0)
        nz_dyn = nz_dyn + jnp.sum(onehot[:, :, None] * pb.nonzero_req[:, None, :],
                                  axis=0)
        if ports_enabled:
            port_dyn = port_dyn | jnp.sum(
                jnp.where(onehot[:, :, None], pod_bits[:, None, :], 0),
                axis=0).astype(jnp.uint32)
        if host is not None:
            onehot_i = onehot.astype(jnp.int32)
            sel_dyn = sel_dyn + jnp.einsum("ps,pn->sn", sig_mask_f, onehot_i)
            term_dyn = term_dyn + jnp.einsum("pt,pn->tn", term_mask_f, onehot_i)
        elif gen is not None:
            onehot_i = onehot.astype(jnp.int32)
            sel_dyn = sel_dyn + jnp.einsum("ps,pn->sn", sig_mask_f, onehot_i)
            # seg_exist: each finalized pod's terms land at its node's
            # domains (topology.commit_update's dom_col scatter, batched)
            T = dom_t.shape[0]
            # the [T, Vd] seg table is REPLICATED: every shard must apply
            # the identical scatter. Reuse the deferral block's dcol — its
            # inputs (local_choice, mine) are unchanged, and recomputing
            # would pay the [T, P] gather + cross-shard psum twice per round
            add_f = (term_mask_f.T * (dcol > 0)
                     * accepted[None, :].astype(jnp.int32))      # [T, P]
            t_iota = jnp.arange(T, dtype=jnp.int32)[:, None]
            term_dyn = term_dyn.at[t_iota, dcol].add(add_f)
        final = accepted | failing
        out_idx = jnp.where(accepted, choice, out_idx)
        best_sel = _gpick(
            jnp.take_along_axis(tot_mix, local_choice[:, None], 1)[:, 0], mine)
        best = jnp.where(final, best_sel, best)
        anyf_out = jnp.where(final, accepted, anyf_out)
        fit_out = jnp.where(final[:, None], fit_mix, fit_out)
        ports_out = jnp.where(final[:, None], ports_mix, ports_out)
        if host is not None or gen is not None:
            spread_out = jnp.where(final[:, None], sp_mix, spread_out)
            ipa_out = jnp.where(final[:, None], ip_mix, ipa_out)
        ff_out = jnp.where(final[:, None], ff_mix, ff_out)
        done = done | final
        progressed = jnp.any(final)
        return (req_dyn, nz_dyn, port_dyn, sel_dyn, term_dyn, done, out_idx,
                best, anyf_out, fit_out, ports_out, spread_out, ipa_out,
                ff_out, progressed)

    def cond(carry):
        done, progressed = carry[5], carry[14]
        return jnp.any(~done & pb.valid) & progressed

    ones_pn = jnp.ones((P, N), bool)
    init = (
        nt.requested, nt.nonzero_requested, nt.port_bits,
        sel0, seg0,                               # topo tables (host mode)
        ~pb.valid,                                # invalid pods start done
        jnp.full((P,), -1, jnp.int32),            # out_idx
        jnp.zeros((P,), jnp.float32),             # best
        jnp.zeros((P,), bool),                    # any_feasible
        ones_pn, ones_pn,                         # fit_out, ports_out
        ones_pn, ones_pn,                         # spread_out, ipa_out
        static_ff,                                # ff_out
        np.True_,
    )
    (f_req, f_nz, f_port, f_sel, f_term, _done, node_idx, best, anyf,
     fit_out, ports_out, spread_out, ipa_out, ff_out, _p) = lax.while_loop(
        cond, body, init)

    committed = node_idx >= 0
    if axis_name is None:
        in_window = committed
        local_commit = jnp.where(committed, node_idx, 0)
    else:
        # node_idx carries GLOBAL slot ids; each shard scatters only the
        # winners inside its own slot window (same as the scan path)
        in_window = committed & (node_idx >= slot_offset) \
            & (node_idx < slot_offset + N)
        local_commit = jnp.where(in_window, node_idx - slot_offset, 0)
    f_class = nt.class_req.at[local_commit, pb.prio_class].add(
        jnp.where(in_window[:, None], pb.req, 0))
    return BatchResult(
        node_idx=node_idx, best_score=best, any_feasible=anyf,
        static_masks={}, fit_ok=fit_out, ports_ok=ports_out,
        spread_ok=spread_out, ipa_ok=ipa_out, first_fail=ff_out,
        final_requested=f_req, final_nonzero=f_nz, final_ports=f_port,
        final_sel_counts=f_sel, final_seg_exist=f_term, final_class_req=f_class,
    )


def schedule_batch_core(
    pb: PodBatch,
    et: ExprTable,
    nt: NodeTensors,
    tc: TopoCounts,
    tb: TopoBatch,
    key: jax.Array,
    weights_key: Tuple[Tuple[str, float], ...],
    topo_enabled: bool = True,
    axis_name: Optional[str] = None,
    num_shards: int = 1,
    pallas: Optional[str] = None,
    topo_carry: Optional[Tuple[jax.Array, jax.Array]] = None,
    sample_k: Optional[jax.Array] = None,
    sample_start: Optional[jax.Array] = None,
    topo_mode: Optional[str] = None,
    vd_override: Optional[int] = None,
    host_key: int = 0,
    spec_decode: bool = False,
    ports_enabled: bool = True,
    extra_mask: Optional[jax.Array] = None,
    dra_mask: Optional[jax.Array] = None,
    slice_mask: Optional[jax.Array] = None,
) -> BatchResult:
    """The traceable body; nt's node axis may be a shard (axis_name set).

    ``extra_mask`` (optional [P, N] bool) is a host-computed static
    feasibility pre-pass ANDed into the static filter phase — today the
    volume-bindability screen (ops/volume_mask.py). Attributed as
    "VolumeBinding" in the first-fail table (id 9); the reference would
    blame an earlier plugin when e.g. ports ALSO fail on the same node —
    a documented attribution-precision divergence, not a placement one.
    ``dra_mask`` (optional [P, N] bool) is the claim-feasibility screen
    (claim_feasibility_mask above — usually a still-unmaterialized device
    array), attributed as "DynamicResources" (id 10); claims allocate at
    node granularity, so the mask is exact per batch and the host Reserve
    re-verifies allocation at commit.
    ``topo_enabled`` is a trace-time flag: batches with no spread constraints,
    no affinity terms and no registered count rows compile a program with the
    whole topology path dead-code-eliminated (the common fast case).

    ``topo_mode``: None derives from topo_enabled ("general"/"off").
    "host" = every involved topology key is kubernetes.io/hostname — the
    per-step segment scatters collapse to per-node count reads
    (ops/topology.py hostname fast path); the seg_exist carry slot then
    holds the per-node term-count table [T, N]. ``vd_override`` shrinks the
    general path's domain axis to the involved keys' actual vocab size."""
    weights = dict(weights_key)
    if topo_mode is None:
        topo_mode = "general" if topo_enabled else "off"
    topo_enabled = topo_mode != "off"
    N = nt.capacity  # local shard size under shard_map
    # (the `key` arg is retained for signature stability; the tie-break
    # jitter is a seeded hash now — see ops/tiebreak.py — so no PRNG key is
    # derived in-program anymore)
    if axis_name is None:
        slot_offset = np.int32(0)
    else:
        slot_offset = (lax.axis_index(axis_name) * N).astype(jnp.int32)

    # ---- static phase -----------------------------------------------------
    expr_match = filters.eval_exprs(et, nt)
    if axis_name is not None:
        # OP_NODE_NAME compares against global slot ids: shift the local iota
        n_idx = jnp.arange(N, dtype=jnp.int32)[None, :] + slot_offset
        name_mask = (pb.node_name[:, None] == -1) | (pb.node_name[:, None] == n_idx)
    else:
        name_mask = filters.filter_node_name(pb, nt)
    static_masks = {
        "NodeUnschedulable": filters.filter_unschedulable(pb, nt),
        "NodeName": name_mask,
        "TaintToleration": filters.filter_taints(pb, nt),
        "NodeAffinity": filters.filter_node_affinity(pb, et, nt, expr_match),
    }
    static_ok = nt.valid[None, :] & pb.valid[:, None]
    for m in static_masks.values():
        static_ok = static_ok & m
    if extra_mask is not None:
        static_ok = static_ok & extra_mask
    if dra_mask is not None:
        static_ok = static_ok & dra_mask
    if slice_mask is not None:
        # slice-gang members are pinned to their planned torus window (a
        # one-hot row; all-False when the plan rejected the gang) — ANDing
        # into static_ok covers the scan, speculative and pallas paths alike
        static_ok = static_ok & slice_mask

    # static half of the first-failing-plugin table (ids follow the filter
    # config order in tpu_scheduler._ATTRIBUTION_ORDER; 0 = passes). Reverse
    # assignment order makes the earliest failing plugin win.
    static_ff = jnp.zeros(static_ok.shape, jnp.int8)
    if slice_mask is not None:
        static_ff = jnp.where(~slice_mask, np.int8(11), static_ff)
    if dra_mask is not None:
        static_ff = jnp.where(~dra_mask, np.int8(10), static_ff)
    if extra_mask is not None:
        static_ff = jnp.where(~extra_mask, np.int8(9), static_ff)
    for sid, name in ((4, "NodeAffinity"), (3, "TaintToleration"),
                      (2, "NodeName"), (1, "NodeUnschedulable")):
        static_ff = jnp.where(~static_masks[name], np.int8(sid), static_ff)

    taint_raw = scores.score_taint_toleration(pb, nt)            # [P, N]
    affinity_raw = scores.score_node_affinity(pb, et, nt, expr_match)
    total_nodes = jnp.maximum(_gsum(jnp.sum(nt.valid), axis_name), 1)
    image_score = scores.score_image_locality(pb, nt, total_nodes=total_nodes)

    # value-id domain capacity: the involved keys' vocab size when the
    # caller computed it, else the full per-key vocab padding
    vd = vd_override if vd_override else int(et.bits.shape[1]) * 32
    if topo_mode == "general":
        topo_static = topology.make_static(
            tc.term_counts, tc.term_key, nt.label_val, nt.valid, vd, axis_name
        )
    elif topo_mode == "host":
        hostkey_ok = nt.label_val[:, host_key] > 0  # [N] node has a hostname

    # seeded tie-break jitter (SURVEY §8; replaces the threefry uniform draw,
    # which was the single most expensive block of the program on CPU): a
    # per-(pod-seed, node-NAME-hash) integer hash, identical to the oracle's
    # _select_host key (ops/tiebreak.py). Name-keyed values are the same on
    # every shard layout, so sharded-vs-single-device parity is automatic.
    from ..ops.tiebreak import jitter_table

    jitter = jitter_table(pb.tie_seed, nt.name_hash)

    # ---- commit phase -----------------------------------------------------
    pod_bits = _pod_port_bits(pb, nt.port_bits.shape[1])
    alloc_f = nt.allocatable.astype(jnp.float32)                  # [N, R]
    ones_pn = jnp.ones((N,), bool)

    if spec_decode:
        # vectorized decide/repair rounds instead of the P-step scan —
        # unsampled batches in every topology mode single-shard, and the
        # topology-OFF mode under shard_map too (VERDICT r3 item 6: the
        # flagship program must not silently fall back to the scan on a
        # real mesh); sequential parity proven per-round by the
        # prefix-stability acceptance
        assert topo_mode in ("off", "host", "general") and sample_k is None
        host_args = gen_args = None
        if topo_mode == "host":
            seg0 = tc.term_counts                      # [T, N] per-node counts
            host_args = {
                "tb": _tb_dict(tb),
                "hostkey_ok": hostkey_ok,
                "affinity_ok": static_masks["NodeAffinity"],
            }
        elif topo_mode == "general":
            seg0 = topo_static.seg_exist0              # [T, Vd] domain counts
            gen_args = {
                "tb": _tb_dict(tb),
                "affinity_ok": static_masks["NodeAffinity"],
                "vd": vd,
                "dom_t": topo_static.dom_t,
                "label_val": nt.label_val,
                "valid": nt.valid,
            }
        else:
            seg0 = jnp.zeros((tc.term_counts.shape[0], 1), jnp.int32)
        sel0_, seg0_ = (tc.sel_counts, seg0) if topo_carry is None else topo_carry
        result = _speculative_core(
            pb, nt, weights, static_ok, static_ff, taint_raw,
            affinity_raw, image_score, pod_bits, jitter, sel0_, seg0_,
            host=host_args, gen=gen_args,
            axis_name=axis_name, slot_offset=slot_offset,
            ports_enabled=ports_enabled)
        return result._replace(static_masks=static_masks)

    if pallas is not None:
        # fused Pallas step: the whole per-pod dynamic computation + commit
        # in one VMEM-resident kernel (ops/pallas_step.py). No sampling
        # emulation here — returning full-evaluation results as if sampled
        # would silently drop the rotation carry.
        assert sample_k is None, "pallas path has no sampling emulation"
        interp = pallas == "interpret"
        alloc_t = nt.allocatable.T
        wvec = np.asarray([[
            weights["NodeResourcesFit"],
            weights["NodeResourcesBalancedAllocation"],
            weights["TaintToleration"],
            weights["NodeAffinity"],
            weights["ImageLocality"],
            0.0, 0.0, 0.0,
        ]], jnp.float32)

        def pstep(carry, xs):
            req_t, nz_t, port_t = carry
            (p_req, p_nz, p_static_ok, _p_affok, p_taint, p_aff, p_img, p_bits,
             p_jitter, p_valid, p_sff) = xs["row"]
            out = pallas_step.fused_step(
                alloc_t, req_t, nz_t, port_t,
                p_req[:, None], p_nz[:, None], p_bits[:, None],
                p_static_ok[None, :], p_taint[None, :], p_aff[None, :],
                p_img[None, :], p_jitter[None, :],
                p_valid.astype(jnp.int32).reshape(1, 1), wvec,
                interpret=interp,
            )
            req_t, nz_t, port_t, idx, best, anyf, fit, ports_ok = out
            ff = p_sff
            ff = jnp.where((ff == 0) & ~ports_ok[0], np.int8(5), ff)
            ff = jnp.where((ff == 0) & ~fit[0], np.int8(6), ff)
            return (req_t, nz_t, port_t), (
                idx[0, 0], best[0, 0], anyf[0, 0] > 0,
                fit[0], ports_ok[0], ones_pn, ones_pn, ff,
            )

        rows = (
            pb.req, pb.nonzero_req, static_ok, static_masks["NodeAffinity"],
            taint_raw, affinity_raw, image_score, pod_bits, jitter, pb.valid,
            static_ff,
        )
        carry0 = (nt.requested.T, nt.nonzero_requested.T, nt.port_bits.T)
        (f_req_t, f_nz_t, f_port_t), (node_idx, best, any_feasible, fit_ok, ports_ok, spread_ok, ipa_ok, first_fail) = lax.scan(
            pstep, carry0, {"row": rows})
        return BatchResult(
            node_idx=node_idx, best_score=best, any_feasible=any_feasible,
            static_masks=static_masks, fit_ok=fit_ok, ports_ok=ports_ok,
            spread_ok=spread_ok, ipa_ok=ipa_ok, first_fail=first_fail,
            final_requested=f_req_t.T, final_nonzero=f_nz_t.T,
            final_ports=f_port_t.T,
        )

    def step(carry, xs):
        # free_dyn = allocatable - requested is carried directly (the sub
        # would otherwise be a full [N, R] pass per step) and the fit test
        # folds the `req == 0 always fits` rule into a per-pod sentinel
        # (p_req_gate), halving the fit chain's [N, R] passes. The nonzero-
        # requested carry holds only the two scored columns; the full [N, R]
        # tensor is rebuilt in ONE post-scan scatter (like f_class below).
        free_dyn, nz2_dyn, port_dyn, sel_counts, seg_exist, samp_start = carry
        row = xs["row"]
        (p_req, p_req_gate, p_nz, p_static_ok, p_affinity_ok, p_taint, p_aff,
         p_img, p_bits, p_jitter, p_valid, p_sff, p_nom) = row

        fit_ok = jnp.all(free_dyn >= p_req_gate[None, :], axis=-1)
        if ports_enabled:
            conflict = jnp.any(port_dyn & p_bits[None, :], axis=-1)
            ports_ok = ~conflict
        else:
            # no pod in the batch wants a host port: skip the [N, Wport]
            # conflict pass AND the carry update below — the port carry then
            # passes through the scan unchanged (aliased, zero traffic)
            ports_ok = ones_pn

        if topo_mode == "host":
            tbx = xs["tb"]
            spread_ok = topology.spread_filter_host(
                tbx, sel_counts, hostkey_ok, nt.valid, p_affinity_ok, axis_name)
            ipa_aff_ok, ipa_anti_ok, ipa_exist_ok, exist_at = topology.ipa_filter_host(
                tbx, sel_counts, seg_exist, hostkey_ok, nt.valid, axis_name)
            ipa_ok = ipa_aff_ok & ipa_anti_ok & ipa_exist_ok
        elif topo_enabled:
            tbx = xs["tb"]
            spread_ok = topology.spread_filter(
                tbx, sel_counts, nt.label_val, nt.valid, p_affinity_ok, vd, axis_name)
            ipa_aff_ok, ipa_anti_ok, ipa_exist_ok, exist_at = topology.ipa_filter(
                tbx, sel_counts, seg_exist, topo_static.dom_t, nt.label_val,
                nt.valid, vd, axis_name)
            ipa_ok = ipa_aff_ok & ipa_anti_ok & ipa_exist_ok
        else:
            spread_ok = ones_pn
            ipa_ok = ones_pn

        feasible = p_static_ok & fit_ok & ports_ok & spread_ok & ipa_ok

        if sample_k is not None:
            # adaptive-sampling emulation (schedule_one.go:525-545 +
            # nextStartNodeIndex rotation :475-478): only the first K
            # feasible nodes in rotated slot order are eligible; the start
            # rotates past every examined node, exactly like the host's
            # early-exit loop. The reference iterates its snapshot list;
            # the device iterates slots — same-distribution sampling with a
            # different (documented) node order.
            iota_n = jnp.arange(N, dtype=jnp.int32)
            perm = (samp_start + iota_n) % N          # rotated order -> slot
            f_rot = jnp.take(feasible, perm)
            c = jnp.cumsum(f_rot.astype(jnp.int32))
            elig_rot = f_rot & (c <= sample_k)
            # scatter-back of a rotation == gather by the inverse rotation
            # (a per-step scatter costs ~200µs on TPU; a gather fuses)
            eligible = jnp.take(elig_rot, (iota_n - samp_start) % N)
            reached = jnp.any(c >= sample_k)
            kth_pos = jnp.argmax(c >= sample_k).astype(jnp.int32)
            processed = jnp.where(reached, kth_pos + 1, np.int32(N))
            # invalid pods examine nothing (no rotation burn)
            samp_start = jnp.where(p_valid, (samp_start + processed) % N, samp_start)
            # the nominated node is always examined (schedule_one.go:394
            # fast path — without this, a preemptor's rotating window
            # usually misses the node its victims were evicted from)
            if axis_name is None:
                eligible = eligible | (iota_n == p_nom)
            else:
                eligible = eligible | (iota_n + slot_offset == p_nom)
            feasible = feasible & eligible

        # resource scores against the evolving requested state (shared
        # formula with the speculative path: _resource_scores)
        nz_req = (nz2_dyn + p_nz[None, :2]).astype(jnp.float32)
        least_alloc, balanced = _resource_scores(alloc_f[:, :2], nz_req)

        total = (
            weights["NodeResourcesFit"] * least_alloc
            + weights["NodeResourcesBalancedAllocation"] * balanced
            + weights["TaintToleration"] * _normalize(p_taint, feasible, True, axis_name)
            + weights["NodeAffinity"] * _normalize(p_aff, feasible, False, axis_name)
            + weights["ImageLocality"] * p_img
        )
        if topo_mode == "host":
            total = total + weights["PodTopologySpread"] * topology.spread_score_host(
                tbx, sel_counts, hostkey_ok, nt.valid, p_affinity_ok, feasible, axis_name)
            total = total + weights["InterPodAffinity"] * topology.ipa_score_host(
                tbx, sel_counts, exist_at, hostkey_ok, feasible, axis_name)
        elif topo_enabled:
            total = total + weights["PodTopologySpread"] * topology.spread_score(
                tbx, sel_counts, nt.label_val, nt.valid, p_affinity_ok, feasible, vd, axis_name)
            total = total + weights["InterPodAffinity"] * topology.ipa_score(
                tbx, sel_counts, exist_at, nt.label_val, nt.valid, feasible, vd, axis_name)

        # nominated-node fast path (schedule_one.go:394-403): when the
        # nominated node is feasible it wins outright — the reference
        # schedules there without scoring the rest
        if axis_name is None:
            is_nom = jnp.arange(N, dtype=jnp.int32) == p_nom
        else:
            is_nom = (jnp.arange(N, dtype=jnp.int32) + slot_offset) == p_nom
        eff = jnp.where(feasible, total + p_jitter + is_nom * np.float32(1e7), NEG_INF)
        local_idx = jnp.argmax(eff).astype(jnp.int32)
        local_best = eff[local_idx]
        any_feasible = _gmax(jnp.any(feasible), axis_name) & p_valid

        if axis_name is None:
            mine = np.True_
            global_idx = local_idx
            best = total[local_idx]
        else:
            global_best = _gmax(local_best, axis_name)
            axis = lax.axis_index(axis_name).astype(jnp.int32)
            winner_axis = _gmin(jnp.where(local_best >= global_best, axis, np.int32(2**30)), axis_name)
            mine = axis == winner_axis
            global_idx = _gsum(jnp.where(mine, local_idx + slot_offset, 0), axis_name).astype(jnp.int32)
            best = _gsum(jnp.where(mine, total[local_idx], 0.0), axis_name)

        commit = any_feasible & mine
        # one-hot elementwise commits instead of scatters: each dynamic
        # scatter costs ~200µs of fixed overhead per scan step on this TPU,
        # while the [N,·] masked adds fuse into the surrounding step
        onehot_n = (jnp.arange(N, dtype=jnp.int32) == local_idx) & commit  # [N]
        free_dyn = free_dyn - onehot_n[:, None] * p_req[None, :]
        nz2_dyn = nz2_dyn + onehot_n[:, None] * p_nz[None, :2]
        if ports_enabled:
            port_dyn = jnp.where(onehot_n[:, None], port_dyn | p_bits[None, :],
                                 port_dyn)
        if topo_mode == "host":
            sel_counts, seg_exist = topology.commit_update_host(
                sel_counts, seg_exist, local_idx, any_feasible, mine,
                tbx["pod_sig_mask"], tbx["pod_term_mask"])
        elif topo_enabled:
            sel_counts, seg_exist = topology.commit_update(
                sel_counts, seg_exist, topo_static.dom_t, local_idx,
                any_feasible, mine, tbx["pod_sig_mask"], tbx["pod_term_mask"], axis_name)
        out_idx = jnp.where(any_feasible, global_idx, -1)
        ff = p_sff
        ff = jnp.where((ff == 0) & ~ports_ok, np.int8(5), ff)
        ff = jnp.where((ff == 0) & ~fit_ok, np.int8(6), ff)
        if topo_enabled:
            ff = jnp.where((ff == 0) & ~spread_ok, np.int8(7), ff)
            ff = jnp.where((ff == 0) & ~ipa_ok, np.int8(8), ff)
        return (free_dyn, nz2_dyn, port_dyn, sel_counts, seg_exist, samp_start), (
            out_idx, best, any_feasible, fit_ok, ports_ok, spread_ok, ipa_ok, ff,
        )

    # `req == 0 always fits` as a sentinel so fit is one compare+reduce
    req_gate = jnp.where(pb.req == 0, jnp.int32(-(2 ** 30)), pb.req)
    rows = (
        pb.req, req_gate, pb.nonzero_req, static_ok, static_masks["NodeAffinity"],
        taint_raw, affinity_raw, image_score, pod_bits, jitter, pb.valid,
        static_ff, pb.nominated,
    )
    xs = {"row": rows}
    if topo_mode == "host":
        xs["tb"] = _tb_dict(tb)
        seg_exist0 = tc.term_counts  # [T, N]: per-node term counts ARE the carry
    elif topo_enabled:
        xs["tb"] = _tb_dict(tb)
        seg_exist0 = topo_static.seg_exist0
    else:
        seg_exist0 = jnp.zeros((tc.term_counts.shape[0], 1), jnp.int32)
    sel0, seg0 = (tc.sel_counts, seg_exist0) if topo_carry is None else topo_carry
    start0 = (jnp.asarray(sample_start, jnp.int32) if sample_start is not None
              else jnp.zeros((), jnp.int32))
    carry0 = (nt.allocatable - nt.requested, nt.nonzero_requested[:, :2],
              nt.port_bits, sel0, seg0, start0)
    final_carry, (node_idx, best, any_feasible, fit_ok, ports_ok, spread_ok, ipa_ok, first_fail) = lax.scan(
        step, carry0, xs)
    f_free, f_nz2, f_port, f_sel, f_seg, f_start = final_carry
    f_req = nt.allocatable - f_free

    # evolve the priority-class table by the batch's commits in ONE post-scan
    # scatter (no carry needed — nothing in-scan reads it); under shard_map
    # each shard scatters only the winners inside its slot window. The full
    # [N, R] nonzero-requested tensor is rebuilt the same way — in-scan only
    # the two scored columns are carried.
    committed = node_idx >= 0
    if axis_name is None:
        in_window = committed
        local_commit = jnp.where(committed, node_idx, 0)
    else:
        in_window = committed & (node_idx >= slot_offset) & (node_idx < slot_offset + N)
        local_commit = jnp.where(in_window, node_idx - slot_offset, 0)
    f_class = nt.class_req.at[local_commit, pb.prio_class].add(
        jnp.where(in_window[:, None], pb.req, 0))
    f_nz = nt.nonzero_requested.at[local_commit].add(
        jnp.where(in_window[:, None], pb.nonzero_req, 0))

    return BatchResult(
        node_idx=node_idx,
        best_score=best,
        any_feasible=any_feasible,
        static_masks=static_masks,
        fit_ok=fit_ok,
        ports_ok=ports_ok,
        spread_ok=spread_ok,
        ipa_ok=ipa_ok,
        first_fail=first_fail,
        final_requested=f_req,
        final_nonzero=f_nz,
        final_ports=f_port,
        final_sel_counts=f_sel,
        final_seg_exist=f_seg,
        final_class_req=f_class,
        final_sample_start=f_start if sample_k is not None else None,
    )


# per-pod slice verdict word (the packed block's optional trailing column):
# bit 0 = pod is a slice-gang member, bit 1 = its gang's torus plan was
# feasible, bits 2+ = planned node slot + 1 (0 = none). The commit side
# combines bit 1 with the member's own node_idx — the mask pins members to
# their planned window, so "every member landed" IS the contiguity verdict,
# with zero extra device dispatch.
SLICE_MEMBER_BIT = 1
SLICE_PLAN_OK_BIT = 2
SLICE_TARGET_SHIFT = 2


def _slice_plan(pb: PodBatch, nt: NodeTensors, slice_members,
                slice_grid: Tuple[int, int]):
    """(slice_mask [P, N] bool, slice_words [P] int32): run the torus
    planner (ops/slice.py) inside the batch jit and lower its per-gang
    targets to the per-pod form the core and the packed block consume.
    Non-members get an all-True mask row and a zero word; members of a
    rejected gang get an all-False row (all-or-nothing by construction)."""
    from ..ops.slice import plan_slices

    member_idx, member_valid = slice_members
    targets, ok = plan_slices(nt, pb.req, member_idx, member_valid,
                              slice_grid)
    p = pb.valid.shape[0]
    n = nt.capacity
    midx = member_idx.reshape(-1)
    act = member_valid.reshape(-1)
    tgt = targets.reshape(-1)
    okf = jnp.broadcast_to(ok[:, None], member_idx.shape).reshape(-1)
    rows = jnp.where(act, midx, p)  # padding entries scatter to a spill row
    row_mask = jnp.where((okf & (tgt >= 0))[:, None],
                         jnp.arange(n, dtype=jnp.int32)[None, :]
                         == tgt[:, None], False)
    mask = jnp.ones((p + 1, n), bool).at[rows].set(row_mask)[:p]
    word = (np.int32(SLICE_MEMBER_BIT)
            | jnp.where(okf, np.int32(SLICE_PLAN_OK_BIT), 0)
            | ((tgt + 1) << SLICE_TARGET_SHIFT)).astype(jnp.int32)
    words = jnp.zeros(p + 1, jnp.int32).at[rows].set(
        jnp.where(act, word, 0))[:p]
    return mask, words


@functools.partial(jax.jit, static_argnames=(
    "weights_key", "topo_enabled", "pallas", "topo_mode", "vd_override",
    "host_key", "spec_decode", "ports_enabled", "slice_grid"))
def schedule_batch(
    pb: PodBatch,
    et: ExprTable,
    nt: NodeTensors,
    tc: TopoCounts,
    tb: TopoBatch,
    key: jax.Array,
    weights_key: Tuple[Tuple[str, float], ...] = tuple(sorted(DEFAULT_WEIGHTS.items())),
    topo_enabled: bool = True,
    pallas: Optional[str] = None,
    topo_carry: Optional[Tuple[jax.Array, jax.Array]] = None,
    sample_k: Optional[jax.Array] = None,
    sample_start: Optional[jax.Array] = None,
    topo_mode: Optional[str] = None,
    vd_override: Optional[int] = None,
    host_key: int = 0,
    spec_decode: bool = False,
    ports_enabled: bool = True,
    extra_mask: Optional[jax.Array] = None,
    dra_mask: Optional[jax.Array] = None,
    slice_members=None,
    slice_grid: Optional[Tuple[int, int]] = None,
    quota_ns: Optional[jax.Array] = None,
    quota_req: Optional[jax.Array] = None,
    quota_used: Optional[jax.Array] = None,
    quota_limit: Optional[jax.Array] = None,
) -> BatchResult:
    # slice gangs plan in-jit, ahead of the core: the plan pins members via
    # slice_mask and its verdict words ride the packed block's extra column
    if slice_members is not None and slice_grid is not None:
        slice_mask, slice_words = _slice_plan(pb, nt, slice_members,
                                              slice_grid)
    else:
        slice_mask = slice_words = None
    res = schedule_batch_core(pb, et, nt, tc, tb, key, weights_key, topo_enabled,
                              pallas=pallas, topo_carry=topo_carry,
                              sample_k=sample_k, sample_start=sample_start,
                              topo_mode=topo_mode, vd_override=vd_override,
                              host_key=host_key, spec_decode=spec_decode,
                              ports_enabled=ports_enabled,
                              extra_mask=extra_mask, dra_mask=dra_mask,
                              slice_mask=slice_mask)
    # namespace-quota screen over the core's winners, in-jit and post-core:
    # it replays the batch order against the synced usage/limit tensors and
    # its verdict words ride the packed block — zero extra dispatch
    if quota_ns is not None and quota_used is not None:
        from ..ops.quota import quota_screen

        quota_words = quota_screen(res.node_idx, quota_ns, quota_req,
                                   quota_used, quota_limit)
    else:
        quota_words = None
    # fuse the host-commit payload into one block here (inside the jit), so
    # every single-device variant — scan, speculative rounds, pallas —
    # returns it; the sharded core entry (parallel/mesh.py) bypasses this
    # wrapper and keeps packed=None
    return res._replace(packed=pack_result_block(
        res.node_idx, res.first_fail, slice_words=slice_words,
        quota_words=quota_words))


def spec_decode_eligible(sample_k) -> bool:
    """Speculative decode covers every single-shard unsampled program
    (topology off, hostname fast path, and the general domain-aggregating
    mode); only sampling forces the scan. KTPU_SPEC=1 forces it, =0 forces
    the scan; auto enables it on accelerators only — the rounds trade ~10x
    more memory traffic for ~100x fewer dependent steps, a win on HBM (TPU)
    and a loss on host RAM (measured 2.2x slower on CPU, where the scan's
    step latency is cheap)."""
    import os

    flag = os.environ.get("KTPU_SPEC", "auto")
    if flag == "0":
        return False
    if sample_k is not None:
        return False
    if flag == "auto":
        import jax

        return jax.default_backend() != "cpu"
    return True


def build_schedule_batch_fn(weights: Dict[str, float] = None):
    """Bind plugin weights statically; returns
    fn(pb, et, nt, tc, tb, key, topo_enabled=True, topo_carry=None,
    sample_k=None, sample_start=None, topo_mode=None, vd_override=None,
    host_key=0) -> BatchResult."""
    wk = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))

    def fn(pb, et, nt, tc, tb, key, topo_enabled=True, topo_carry=None,
           sample_k=None, sample_start=None, topo_mode=None, vd_override=None,
           host_key=0, ports_enabled=True, extra_mask=None, dra_mask=None,
           slice_members=None, slice_grid=None, quota_ns=None, quota_req=None,
           quota_used=None, quota_limit=None):
        spec = spec_decode_eligible(sample_k)
        # the pallas fused step has no sampling emulation yet; the
        # speculative path replaces it where both apply (fewer device steps).
        # The fused kernel has no extra-mask/dra-mask/slice/quota input
        # either — volume, claim, slice and quota-screened batches take the
        # XLA path.
        mode = (None if (sample_k is not None or spec or extra_mask is not None
                         or dra_mask is not None or slice_members is not None
                         or quota_ns is not None)
                else pallas_mode(nt, None, topo_enabled))
        kw = dict(weights_key=wk, topo_enabled=topo_enabled, pallas=mode,
                  topo_carry=topo_carry, sample_k=sample_k,
                  sample_start=sample_start, topo_mode=topo_mode,
                  vd_override=vd_override, host_key=host_key,
                  spec_decode=spec, ports_enabled=ports_enabled,
                  extra_mask=extra_mask, dra_mask=dra_mask,
                  slice_members=slice_members, slice_grid=slice_grid,
                  quota_ns=quota_ns, quota_req=quota_req,
                  quota_used=quota_used, quota_limit=quota_limit)
        out = schedule_batch(pb, et, nt, tc, tb, key, **kw)
        from . import telemetry

        if telemetry.get() is not None:
            # cost ledger: AOT-lower the exact signature just dispatched and
            # keep its flops/bytes once per (program, bucket sig) — this is
            # the one place the batch program's full kwargs exist. Sig
            # mirrors _run_batch_fn's compile-ledger bucket.
            sig = (f"{getattr(pb, 'capacity', '?')}/"
                   f"{topo_mode or ('general' if topo_enabled else 'off')}")
            telemetry.cost_probe("schedule_batch", sig, schedule_batch,
                                 (pb, et, nt, tc, tb, key), kw)
        return out

    return fn
