"""TPUScheduler: the batched execution backend wired into the scheduler.

Replaces the per-pod findNodesThatFitPod/prioritizeNodes middle of the cycle
(schedule_one.go:364,:605) with one compiled device call per pod micro-batch;
queue, cache, assume, bind, and failure handling are the same host machinery
as the sequential path (the BASELINE.json north star, minus the gRPC hop —
the control plane here is in-process Python rather than a Go sidecar peer).

Flow per batch cycle:
  1. drain up to `batch_size` pods from the queue in queue order;
  2. update the cache snapshot; delta-sync the device mirror;
  3. split batch-supported pods from fallback pods (features the kernel
     doesn't cover yet go through the sequential oracle path — graceful
     degradation, SURVEY.md §5.3 build mapping);
  4. one `schedule_batch` call: static masks + in-scan sequential commit;
  5. host: assume + bind winners in order; losers get reference-shaped
     Diagnosis (first-failing-plugin per node, reconstructed from the masks
     in filter config order) and re-queue with backoff.
"""

from __future__ import annotations

import dataclasses
import os
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from ..api.types import Pod
from ..framework.interface import CycleState, Status
from ..framework.plugins.coscheduling import gang_precheck_status, pod_group_key
from ..framework.plugins.quota import quota_precheck_status
from ..framework.types import Diagnosis, QueuedPodInfo
from ..metrics import latency_ledger
from ..ops.encode import CapacityError
from ..scheduler.scheduler import Scheduler
from .batch import BatchResult, build_schedule_batch_fn
from .device_state import DeviceState, caps_for_cluster
from .errors import PermanentDeviceError

# filter config order for failure attribution (default_plugins.go filter order)
_ATTRIBUTION_ORDER = (
    ("NodeUnschedulable", "node(s) were unschedulable"),
    ("NodeName", "node(s) didn't match the requested node name"),
    ("TaintToleration", "node(s) had untolerated taint"),
    ("NodeAffinity", "node(s) didn't match Pod's node affinity/selector"),
    ("NodePorts", "node(s) didn't have free ports for the requested pod ports"),
    ("NodeResourcesFit", "Insufficient resources"),
    ("PodTopologySpread", "node(s) didn't match pod topology spread constraints"),
    ("InterPodAffinity", "node(s) didn't match pod affinity/anti-affinity rules"),
    ("VolumeBinding", "node(s) didn't satisfy volume placement"),
    ("DynamicResources", "cannot allocate all claims"),
    ("SlicePacking", "node(s) outside the gang's planned torus slice"),
)


# _DecayedFit/BatchSizer moved to backend/sizer.py when the wire path
# gained the same in-flight ring shape (WireScheduler's pipelined
# transport); re-exported here for the existing call sites and tests.
from .sizer import BatchSizer, _DecayedFit  # noqa: F401  (re-export)


@dataclasses.dataclass
class _Inflight:
    """One dispatched-but-uncommitted batch (SURVEY §2.7 P3: the device
    computes batch k+1 while the host commits batch k). The result's arrays
    are unmaterialized device futures until the commit touches them."""

    qps: List[QueuedPodInfo]
    result: BatchResult
    pod_cycle: int
    t0: float  # batch pop time — the attempt-latency clock
    host_pb: dict  # encoder's host copy of req/nonzero_req/port_ids
    pb: object = None  # device PodBatch — preemption screen input on failures
    mode_info: tuple = ()  # (topo_mode, vd_bucket, host_key): carry-shape id
    batch_id: str = ""  # flight-recorder identity (in-process: "b<counter>")
    bucket: int = 0  # padded pod capacity the program ran at
    # encoder.reclaim_gen at dispatch: a winner slot released after this
    # (node removed / tombstone reused) gets a typed rejection at commit
    # instead of a ghost placement (None = guard by cache existence only)
    reclaim_gen: Optional[int] = None
    # the DeviceState instance this batch was computed on: a commit (worker
    # or inline) finding a DIFFERENT live device poisons the batch instead
    # of committing foreign-device results against a rebuilt mirror — the
    # race-free form of "clear the whole ring on device death"
    device: object = None
    # now_fn timestamp when the async dispatch returned — the dispatch
    # profiler's dwell clock starts here (0.0 = unset: dwell collapses
    # into the wait window)
    t_submit: float = 0.0
    # whether the batch program ran the namespace-quota screen — the
    # packed block then carries a quota verdict column, and the unpack
    # must know which a SINGLE trailing column is (slice vs quota)
    quota_col: bool = False


def _default_full_batch() -> bool:
    """Whether the adaptive percentageOfNodesToScore default (0) evaluates
    the FULL node batch (accelerators) or the reference's adaptive sample
    (CPU). KTPU_FULL_BATCH=1/0 overrides the platform choice."""
    import os

    env = os.environ.get("KTPU_FULL_BATCH", "")
    if env in ("0", "1"):
        return env == "1"
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — no backend: behave like the reference
        return False


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the batched kernels compile once per
    (bucket, batch) shape per machine, not per process — first-run warmup is
    the dominant cost otherwise (§5.4: persist nothing beyond compiled-
    executable caches)."""
    import os

    if getattr(_enable_compilation_cache, "_done", False):
        return
    _enable_compilation_cache._done = True
    cache_dir = os.environ.get(
        "KTPU_COMPILE_CACHE", os.path.expanduser("~/.cache/kubernetes_tpu_xla")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 — older jax without the knob
        pass


class TPUScheduler(Scheduler):
    def __init__(self, *args, batch_size: int = 128, comparer_every_n: int = 0,
                 batch_deadline_ms: Optional[float] = None,
                 relay_breaker_threshold: Optional[int] = None,
                 relay_probe_interval_s: Optional[float] = None, **kwargs):
        super().__init__(*args, **kwargs)
        import os

        _enable_compilation_cache()
        self.batch_size = batch_size
        # in-process relay breaker (PR 3 carryover): repeated device-commit
        # failures (a dead TPU relay) stop burning a rebuild+dispatch per
        # cycle — pods take the oracle path while the breaker is open. The
        # probe cadence is the relay's OWN: probing in-process costs one
        # local dispatch (microseconds of host work), not a wire round trip,
        # so the half-open interval defaults to 0.5s instead of the wire
        # breaker's 5s — a healed relay is re-adopted ~10x sooner.
        from .circuit import CircuitBreaker

        if relay_breaker_threshold is None:
            relay_breaker_threshold = int(os.environ.get(
                "KTPU_RELAY_BREAKER_THRESHOLD", "3"))
        if relay_probe_interval_s is None:
            relay_probe_interval_s = float(os.environ.get(
                "KTPU_RELAY_PROBE_S", "0.5"))
        self.relay_breaker = CircuitBreaker(
            failure_threshold=relay_breaker_threshold,
            reset_timeout_s=relay_probe_interval_s, now_fn=self.now_fn,
            on_state_change=self._relay_state_change)
        self.relay_degraded_pods = 0
        # degraded-window accounting for the IN-PROCESS breaker: the wire
        # path accrues scheduler_degraded_seconds_total on its own breaker
        # (backend/service.py); before this, a relay-breaker-open window on
        # the in-process backend was invisible to the SLO metric
        self._relay_degraded_since: Optional[float] = None
        # scripted device-fault hook (soak workloads / chaos rigs): called
        # with the op name ("commit") before each batch materialization;
        # a returned exception is raised through the real relay-death path
        # (breaker count, ring poison, backoffQ requeue, device rebuild) —
        # the in-process analog of testing/faults.FaultPlan on the wire
        self.relay_fault_fn: Optional[Callable[[str], Optional[BaseException]]] = None
        if batch_deadline_ms is None:
            # ON by default (VERDICT r3 item 4): the iso-p99 contract needs
            # pop→commit bounded, so the sizer cuts batches to fit ~2 cycles
            # in the deadline. 500ms keeps ≥90% of uncapped throughput on
            # the CPU fallback (scan step ~1.8ms/pod) and never binds on
            # accelerators (per-pod cost far below the budget). "0" disables.
            batch_deadline_ms = float(os.environ.get("KTPU_BATCH_DEADLINE_MS", "500"))
        self.sizer = BatchSizer(batch_size, batch_deadline_ms / 1000.0)
        # device/host comparer (SURVEY.md §5.2 mapping of the cache drift
        # detector): every Nth device commit, re-check the placement with
        # the scalar oracle filters; 0 disables
        self.comparer_every_n = comparer_every_n
        self.comparer_checks = 0
        self.comparer_mismatches = 0
        self.device: Optional[DeviceState] = None
        # high-water mark of encoder.slot_reuses already exported to the
        # scheduler_device_slot_reuse_total counter (device rebuilds reset
        # the encoder counter; the metric stays cumulative)
        self._slot_reuses_seen = 0
        self._batchable_cache: Dict[str, bool] = {}
        self.schedule_batch_fn = build_schedule_batch_fn()
        self.batch_counter = 0
        self.fallback_scheduled = 0
        self.batch_scheduled = 0
        # run_until_settled sets this when it gives up with pods still
        # pending (ADVICE r2: harness consumers must be able to distinguish
        # settled from abandoned)
        self.settle_abandoned = False
        # adaptive-sampling rotation start: a device scalar chained from the
        # previous batch's evolved carry (schedule_one.go:475 rotation)
        self._start_carry = None
        # §5.1 profiling: KTPU_PROFILE_DIR=<dir> captures a JAX profiler
        # trace of the first KTPU_PROFILE_BATCHES (default 4) batch cycles —
        # the per-cycle XLA trace-dump analog of scheduler_perf -cpuprofile
        self._profile_dir = os.environ.get("KTPU_PROFILE_DIR", "")
        self._profile_batches = int(os.environ.get("KTPU_PROFILE_BATCHES", "4"))
        self._profiling = False
        # async pipeline (SURVEY §2.7 P3 analog), generalized to a bounded
        # multi-batch in-flight RING: up to ``pipeline_depth`` dispatched
        # batches ride the device at once (oldest commits first), so the
        # host work of landing batch k overlaps the device execution of
        # k+1..k+K instead of just the dispatch of k+1. KTPU_PIPELINE=0
        # forces the synchronous path; KTPU_PIPELINE_DEPTH sets K (default
        # 2 — deeper rings add pop→commit latency per batch, which the
        # deadline sizer then pays for in smaller batches).
        if os.environ.get("KTPU_PIPELINE", "1") == "0":
            self.pipeline_depth = 0
        else:
            # depth 0 is a valid setting: synchronous, same as KTPU_PIPELINE=0
            self.pipeline_depth = max(0, int(os.environ.get(
                "KTPU_PIPELINE_DEPTH", "2")))
        self._inflight: Deque[_Inflight] = deque()
        self.pipelined_batches = 0
        # ---- commit data plane (backend/commit_plane.py) ----
        # The commit WORKER lands ring-overflow batches on its own thread,
        # overlapping batch K's host commit with batch K+1's encode/
        # dispatch/device execution. The device mutex (owned by the commit
        # plane so the per-class static lock pass analyzes the classes that
        # own state, while KTPU_LOCKTRACE traces the protocol end to end)
        # serializes the two owners' device-touching phases: the scheduling
        # thread's sync/encode/dispatch vs the worker's adopt/judge/
        # reconcile. PLATFORM-AWARE default (the _default_full_batch rule):
        # on an accelerator the device executes off-host and the worker's
        # overlap is free; on the CPU fallback "device compute" is host CPU
        # time, so a second thread only contends with XLA (measured ~18%
        # slower on the 2-core bench box) — commits stay inline there.
        # KTPU_COMMIT_WORKER=1/0 overrides either way.
        self.commit_worker = None
        worker_env = os.environ.get("KTPU_COMMIT_WORKER", "")
        if worker_env in ("0", "1"):
            want_worker = worker_env == "1"
        else:
            try:
                want_worker = jax.default_backend() != "cpu"
            except Exception:  # noqa: BLE001 — no backend: stay inline
                want_worker = False
        if self.pipeline_depth and want_worker:
            from .commit_plane import CommitWorker

            self.commit_worker = CommitWorker(self._commit_inflight)
        # worker-owned snapshot for commit-side reconciles: the scheduling
        # thread keeps self.snapshot; sharing one Snapshot object across
        # threads would let reconcile iterate node_info_map mid-update
        from ..cache import Snapshot

        self._commit_snapshot = Snapshot()
        # carry gate for the async pipeline: the pipelined encode rides the
        # device carry only while (a) no EXTERNAL node-truth change arrived
        # since the last full sync (Scheduler.external_change_seq) and (b)
        # no host-rejected commit invalidated a device row (_chain_dirty).
        # The has_dirty cache walk the synchronous pipeline uses cannot
        # distinguish the worker's own in-progress commits from external
        # changes, so the worker mode gates on events instead.
        self._chain_ext_seq = -1
        self._chain_dirty = False
        # volume-bindability pre-pass (ops/volume_mask.py): lets PVC-bearing
        # pods ride the batched path with a [P, N] static screen + exact
        # host verify of the chosen node at commit (VERDICT r4 item 4)
        from ..ops.volume_mask import VolumeMaskBuilder

        self._volume_masks = VolumeMaskBuilder(self.store)
        # claim-feasibility pre-pass (backend/claim_mask.py): resource.k8s.io
        # claim-bearing pods ride the batched path with a [P, N] device mask
        # over the node attribute table + exact Reserve verify at commit
        from .claim_mask import ClaimMaskBuilder

        self._claim_masks = ClaimMaskBuilder(self.store)
        # continuous rebalancing (controllers/rebalance.py): opt-in via
        # enable_rebalancer(); driven from _periodic_housekeeping so it
        # only ever runs on the scheduling thread, in commit-idle gaps
        self.rebalancer = None

    def _relay_state_change(self, _old: str, new: str) -> None:
        """Relay breaker transition: publish the circuit gauge and accrue
        scheduler_degraded_seconds_total over the open→closed window (the
        in-process mirror of WireScheduler's degraded accounting). A
        half-open probe neither closes nor restarts the window — only a
        successful close books the seconds."""
        from .circuit import STATE_VALUES

        self.smetrics.backend_circuit_state.set(value=STATE_VALUES[new])
        now = self.now_fn()
        if new == "open" and self._relay_degraded_since is None:
            self._relay_degraded_since = now
        elif new == "closed" and self._relay_degraded_since is not None:
            self.smetrics.degraded_seconds.inc(
                value=now - self._relay_degraded_since)
            self._relay_degraded_since = None

    # ------------------------------------------------------------- device mgmt

    def _sync_slot_reuse_metric(self) -> None:
        """Export the encoder's slot-reuse count delta into the cumulative
        scheduler_device_slot_reuse_total counter."""
        if self.device is None:
            return
        reuses = self.device.encoder.slot_reuses
        if reuses < self._slot_reuses_seen:  # fresh device: counter reset
            self._slot_reuses_seen = 0
        if reuses > self._slot_reuses_seen:
            self.smetrics.device_slot_reuse.inc(
                value=reuses - self._slot_reuses_seen)
            self._slot_reuses_seen = reuses

    def _ensure_device(self) -> None:
        """Build or grow the device mirror. Always called on the scheduling
        thread; drains (commit-worker flush included) happen OUTSIDE the
        device mutex — the worker needs the mutex to finish its commits —
        and the rebuild+sync run under it."""
        n = max(self.cache.node_count(), 1)
        with self.commit_plane.device_mutex:
            device = self.device
            needs_grow = device is not None and device.caps.nodes < n
        if device is None:
            with self.commit_plane.device_mutex:
                if self.device is None:
                    self.device = DeviceState(
                        caps_for_cluster(n, batch=self.batch_size),
                        ns_labels_fn=self.store.ns_labels)
                    self.device.sync(self.snapshot)
            return
        if not needs_grow:
            return
        # preserve every previously-grown axis; only widen the node axis
        # (and the hostname value vocab that must cover it)
        self._drain_inflight()  # old-device results must commit first
        if self.device is None:  # the drain's commit killed the device
            self._ensure_device()
            return
        with self.commit_plane.device_mutex:
            caps = self.device.caps
            nodes = caps.nodes
            while nodes < n:
                nodes *= 2
            caps = dataclasses.replace(
                caps, nodes=nodes,
                value_words=max(caps.value_words, (nodes + 2 + 31) // 32),
            )
            self.device = DeviceState(caps, ns_labels_fn=self.store.ns_labels)
            self.device.sync(self.snapshot)

    # CapacityError.dimension → Capacities field(s) to double (exact names
    # raised by ops/encode.py; "value vocab for 'key'" handled by prefix)
    _GROW_FIELDS = {
        "nodes": ("nodes",),
        "pods": ("pods",),
        "resources": ("resources",),
        "label_keys": ("label_keys",),
        "taints": ("taints",),
        "tolerations": ("tolerations",),
        "exprs": ("exprs",),
        "sel_exprs": ("sel_exprs",),
        "terms": ("terms",),
        "term_exprs": ("term_exprs",),
        "pref_terms": ("pref_terms",),
        "ports": ("ports",),
        "ports vocab": ("port_words",),
        "image vocab": ("image_words", "images"),
        "containers": ("containers",),
        "sigs": ("sigs",),
        "ex_terms": ("ex_terms",),
        "spread_cons": ("spread_cons",),
        "ipa_terms": ("ipa_terms",),
        "ipa_pref": ("ipa_pref",),
        "prio_classes": ("prio_classes",),
        "superpods": ("superpods",),
        "sp_slots": ("sp_slots",),
    }

    def _resync_grown(self, err: CapacityError) -> None:
        """Grow exactly the offending capacity axis and rebuild the mirror.
        Callers raise CapacityError OUTSIDE the device mutex (the drain
        below must let the commit worker take it)."""
        self._drain_inflight()
        if self.device is None:  # the drain's commit killed the device
            self._ensure_device()
            return
        fields = self._GROW_FIELDS.get(err.dimension)
        if fields is None and err.dimension.startswith("value vocab"):
            fields = ("value_words",)
        if fields is None:
            # typed per backend/errors.py: deterministic, never retried
            raise PermanentDeviceError(
                f"unknown capacity dimension {err.dimension!r}") from err
        with self.commit_plane.device_mutex:
            caps = self.device.caps
            updates = {}
            for f in fields:
                v = getattr(caps, f)
                while v < err.needed:
                    v *= 2
                updates[f] = v
            self.device = DeviceState(dataclasses.replace(caps, **updates),
                                      ns_labels_fn=self.store.ns_labels)
            self.device.sync(self.snapshot)

    # ------------------------------------------------------------- batch support

    def _topo_mode_info(self) -> tuple:
        """(topo_mode, vd_bucket, host_key) for the CURRENT sig-table state +
        last-encoded batch: selects the hostname fast path or a compact
        domain axis (ops/topology.py). Also the carry-shape identity the
        pipelined chain must match on."""
        if not self.device.topo_enabled:
            return ("off", None, 0)
        summary = getattr(self.device.sig_table, "last_topo_summary", None)
        if summary is None:
            return ("general", None, 0)
        if summary["hostname_only"]:
            from ..framework.plugins.podtopologyspread import HOSTNAME_KEY

            host_slot = self.device.encoder.key_slot(HOSTNAME_KEY)
            # the fast path treats every node as its own domain — only valid
            # when hostname label values are actually node-unique (a
            # --hostname-override collision must fall back to the general
            # domain-aggregating path)
            valid = self.device._mirror["valid"]
            vals = self.device._mirror["label_val"][valid, host_slot]
            if len(np.unique(vals)) == len(vals):
                return ("host", None, host_slot)
        vd = 64
        while vd < summary["vd_needed"]:
            vd *= 2
        return ("general", vd, 0)

    def batch_supported(self, pod: Pod) -> bool:
        """Features the batched kernel covers today; the rest take the
        sequential oracle path (config fallback knob, SURVEY.md §7).
        Topology spread and inter-pod affinity run on device via the
        sig-count kernels (ops/topology.py). Volume-bearing pods ride the
        batch too when their claims are screenable: a host-vectorized
        [P, N] bindability mask joins the static filter phase
        (ops/volume_mask.py) and the commit path re-runs the exact volume
        filters on the chosen node (VERDICT r4 item 4). Unscreenable claims
        (missing PVC, immediate-unbound) keep the oracle fallback.
        resource.k8s.io claim-bearing pods likewise ride the batch behind
        the claim-feasibility mask (backend/claim_mask.py) as long as every
        claim object resolves; a not-yet-materialized claim keeps the
        oracle path, whose PreFilter parks the pod until the resourceclaim
        controller catches up."""
        # a non-default plugin set would diverge from the compiled program's
        # semantics: only batch pods whose profile IS the default set
        if not self._framework_batchable(self.framework_for_pod(pod)):
            return False
        if pod.spec.volumes:
            if os.environ.get("KTPU_VOLUME_BATCH", "1") == "0":
                return False
            if not self._volume_masks.batchable(pod):
                return False
        if pod.spec.resource_claims:
            if os.environ.get("KTPU_DRA_BATCH", "1") == "0":
                return False
            if not self._claim_masks.batchable(pod):
                return False
        return True

    def _framework_batchable(self, fwk) -> bool:
        """True iff the profile's filter/score plugin sets and weights match
        what the compiled batch program implements (the default set). Custom
        profiles fall back to the sequential oracle path wholesale."""
        cached = self._batchable_cache.get(fwk.profile_name)
        if cached is not None:
            return cached
        from ..framework.registry import DEFAULT_PLUGINS

        ok = True
        for point in ("pre_filter", "filter", "pre_score", "score"):
            have = [(p.name(), w) for p, w in fwk.points.get(point, [])]
            want = list(DEFAULT_PLUGINS.get(point, []))
            if have != want:
                ok = False
                break
        self._batchable_cache[fwk.profile_name] = ok
        return ok

    # ------------------------------------------------------------- the batch cycle

    def schedule_batch_cycle(self) -> int:
        """Schedule up to one micro-batch; returns pods processed.

        Queue order is preserved across the batch/fallback split: pods are
        walked in pop order, consecutive batch-supported pods accumulate into
        one device call, and hitting a fallback pod first flushes the
        accumulated batch — so a high-priority fallback pod never loses its
        turn to lower-priority batched pods (reference strict-serial order)."""
        if self.informer_factory is not None:
            # the batched loop must pump the shared-informer bus exactly like
            # schedule_one does — without this the cmd-binary topology
            # (setup() wires a SharedInformerFactory) never delivers pod/node
            # events to the batched frontends and the queue stays empty.
            # Coalesced: a pump delivering a whole commit's worth of bind
            # confirmations fires ONE queue-move scan, not one per pod.
            with self.queue.coalesce_moves():
                self.informer_factory.pump()
        self._periodic_housekeeping()
        qps = self.queue.pop_batch(self.sizer.target())
        if not qps:
            # nothing new to overlap with: land the in-flight batch so its
            # failures requeue before the caller judges settlement
            self._drain_inflight()
            return 0
        # Attempt-latency clock for every pod in this batch: pop → commit.
        # Batching trades per-pod latency for throughput; the p99 of this
        # histogram is the iso-latency evidence BASELINE.md demands.
        t_pop = self.now_fn()
        pod_cycle = self.queue.scheduling_cycle

        buffer: List[QueuedPodInfo] = []
        # relay breaker: while OPEN, the device path is presumed dead —
        # every pod takes the sequential oracle path and no device state is
        # touched (no rebuild+dispatch burned per cycle). allow() past the
        # (relay-tuned, cheap) probe interval admits the next batch as the
        # half-open probe.
        relay_ok = self.relay_breaker.allow()
        if self._relay_degraded_since is not None:
            # streaming accrual while the breaker stays open (the wire
            # service's periodic-sample pattern): consumers see degraded
            # seconds grow DURING the outage, not only after the close
            now = self.now_fn()
            self.smetrics.degraded_seconds.inc(
                value=now - self._relay_degraded_since)
            self._relay_degraded_since = now
        if relay_ok:
            self._ensure_device()
        for qp in qps:
            pod = self.store.get_pod(qp.pod.key())
            if pod is None or pod.spec.node_name or not self._responsible_for(pod):
                latency_ledger.close_skipped(qp.pod.key(), pod)
                continue  # skipPodSchedule
            qp.pod = pod
            fwk = self.framework_for_pod(pod)
            # host-side namespace-quota gate (QuotaAdmission's PreFilter —
            # the compiled program does not model tenant quota): an
            # over-quota pod fails here without spending a device slot.
            # Usually PreEnqueue already parked it; this closes the race
            # where usage grew between enqueue and pop.
            quota_st = quota_precheck_status(fwk, pod)
            if quota_st is not None:
                self.metrics.inc("schedule_attempts")
                self._fail(fwk, qp, quota_st, pod_cycle,
                           Diagnosis(unschedulable_plugins={"QuotaAdmission"}))
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t_pop)
                continue
            # host-side gang quorum gate (Coscheduling's PreFilter, which
            # the compiled program does not model): a member whose gang
            # cannot reach quorum — or sits in rejection backoff — fails
            # here without spending a device slot
            gang_st = gang_precheck_status(fwk, pod)
            if gang_st is not None:
                self.metrics.inc("schedule_attempts")
                self._fail(fwk, qp, gang_st, pod_cycle,
                           Diagnosis(unschedulable_plugins={"Coscheduling"}))
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t_pop)
                continue
            batchable = self.batch_supported(pod)
            if relay_ok and batchable:
                buffer.append(qp)
                continue
            if not relay_ok and batchable:
                # only pods the breaker actually diverted count as degraded
                # (the permanent oracle-fallback population is not relay
                # impact)
                self.relay_degraded_pods += 1
                from . import telemetry

                telemetry.event("degrade", pod=pod.key(),
                                reason="relay breaker open")
            # fallback pod: flush what's queued first (strict pop order) and
            # land it, then give the sequential path a fresh snapshot
            self._flush_batch(buffer, pod_cycle, t_pop)
            buffer = []
            self._drain_inflight()
            self.cache.update_snapshot(self.snapshot)
            self._schedule_fallback(qp, pod_cycle)
        self._flush_batch(buffer, pod_cycle, t_pop)
        return len(qps)

    def _periodic_housekeeping(self, now: Optional[float] = None) -> None:
        """The 1s sweep (assume expiry, permit timeouts) mutates waiting-pod
        and plugin ledger state the commit worker's Reserve/Permit phases
        also touch: land the in-flight commits first so the sweep judges
        settled state instead of racing a half-committed batch. ONE clock
        read feeds both this gate and the base sweep — two reads straddling
        the tick boundary would skip the flush yet still run the sweep,
        iterating waiting_pods while the worker parks into it."""
        if now is None:
            now = self.now_fn()
        if (self.commit_worker is not None
                and now - self._last_cleanup >= 1.0
                and not self.commit_worker.idle()):
            self.commit_worker.flush()
        super()._periodic_housekeeping(now)
        if self.rebalancer is not None:
            # after the sweep (settled ledgers), gated internally on the
            # score interval + commit-plane idleness
            self.rebalancer.maybe_run(now)

    def enable_rebalancer(self, **kwargs):
        """Attach the background Rebalancer (controllers/rebalance.py) —
        a second consumer of the device backend, scored and executed from
        housekeeping's idle gaps. Returns it for knob access."""
        from ..controllers.rebalance import Rebalancer

        self.rebalancer = Rebalancer(self, now_fn=kwargs.pop(
            "now_fn", self.now_fn), **kwargs)
        return self.rebalancer

    def _maybe_profile(self) -> None:
        """Start/stop a JAX profiler capture window over the first N batch
        cycles when KTPU_PROFILE_DIR is set (view with xprof/tensorboard)."""
        if not self._profile_dir:
            return
        if not self._profiling and self.batch_counter == 0:
            try:
                jax.profiler.start_trace(self._profile_dir)
                self._profiling = True
            except Exception:  # noqa: BLE001 — profiling must never break scheduling
                self._profile_dir = ""
        elif self._profiling and self.batch_counter >= self._profile_batches:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a torn profiler trace must not kill the batch path
                pass
            self._profiling = False
            self._profile_dir = ""

    def _flush_batch(self, batched: List[QueuedPodInfo], pod_cycle: int,
                     t_pop: Optional[float] = None) -> None:
        if not batched:
            return
        from ..utils import tracing

        # one scheduling.cycle span per in-process batch: the device.* phase
        # spans below (and the overlapped commit of the PREVIOUS batch, which
        # lands inside this cycle by pipelining design) parent under it
        with tracing.span("scheduling.cycle", batch=len(batched)):
            self._flush_batch_traced(batched, pod_cycle, t_pop)

    def _flush_batch_traced(self, batched: List[QueuedPodInfo], pod_cycle: int,
                            t_pop: Optional[float] = None) -> None:
        from ..utils import tracing

        self._maybe_profile()
        t0 = self.now_fn()
        t_pop = t_pop if t_pop is not None else t0
        mutex = self.commit_plane.device_mutex
        with tracing.span("device.encode.pipelined", batch=len(batched)):
            with mutex:
                enc = self._try_pipelined_encode(batched)
                device = self.device  # instance the encode ran against
        extra_mask = None
        dra_mask = None
        if enc is not None:
            pb, et, tb, extra_mask, dra_mask = enc
            t_sync = t0  # nothing to upload: the in-flight carry IS the state
        else:
            # the drain lands the PREVIOUS batch (its commit spans are its
            # own); only sync+encode below belong to THIS batch's spans
            self._drain_inflight()
            self._ensure_device()  # the drain's commit may have killed it
            # carry-gate baseline: capture BEFORE the snapshot update — an
            # external event racing in after this reads as a changed seq on
            # the next pipelined probe (conservative break, never a miss)
            ext_seq = self.external_change_seq()
            self.cache.update_snapshot(self.snapshot)
            for _attempt in range(8):
                try:
                    with mutex:
                        with tracing.span("device.sync"):
                            self.device.sync(self.snapshot)
                        self._sync_slot_reuse_metric()
                        t_sync = self.now_fn()
                        pods = [qp.pod for qp in batched]
                        bucket = self.sizer.bucket_for(len(pods))
                        from ..ops.tiebreak import seeds_for

                        with tracing.span("device.encode", batch=len(batched)):
                            pb, et = self.device.encoder.encode_pods(
                                pods, capacity=bucket,
                                tie_seeds=seeds_for(batched))
                            tb = self.device.sig_table.encode_topo(
                                pods, capacity=bucket)
                            extra_mask = self._volume_masks.build(
                                batched, self.snapshot, self.device.encoder,
                                self.device.caps.nodes, bucket)
                            dra_mask = self._claim_masks.build(
                                batched, self.device, bucket)
                        device = self.device
                    break
                except CapacityError as e:
                    # outside the mutex: the grow path drains, and the
                    # commit worker needs the mutex to finish its commits
                    self._resync_grown(e)
            else:
                for qp in batched:  # capacities refuse to converge
                    self._schedule_fallback(qp, pod_cycle)
                return
            self._chain_ext_seq = ext_seq
            self._chain_dirty = False
        t_enc = self.now_fn()
        self.batch_counter += 1
        from . import telemetry

        batch_id = f"b{self.batch_counter}"
        bucket = int(pb.capacity)
        # scalar seed, not an eager jax.random.PRNGKey: the key derivation is
        # traced into the program (an eager PRNGKey costs two relay
        # round-trips per batch once the session has synchronized)
        key = np.int32(self.batch_counter)
        prev = self._inflight[-1] if self._inflight else None
        # cross-batch topology carry: batch k+1 starts from the NEWEST
        # in-flight batch's evolved sel_counts/seg_exist instead of the
        # (stale, pre-k) host tables — the ring chains carries end to end.
        # Only valid on the pipelined path — after a drain the host recounts
        # and device.tc is the truth again (prev is None then).
        carry = None
        if prev is not None and prev.result.final_sel_counts is not None:
            carry = (prev.result.final_sel_counts, prev.result.final_seg_exist)
        # percentageOfNodesToScore: an EXPLICIT percentage gets the exact
        # rotating-window emulation (schedule_one.go:525-545 parity). The
        # adaptive default (0) is PLATFORM-AWARE:
        #   * accelerators run FULL-batch evaluation — the reference's
        #     adaptive mode exists to bound per-cycle CPU time by examining
        #     fewer nodes, but on TPU the masked full evaluation is cheaper
        #     than the emulated early-exit (SURVEY §2.7 P2) and it unlocks
        #     the speculative-decode program. Documented divergence per
        #     SURVEY §7 hard-part 3.
        #   * the CPU fallback keeps the reference's adaptive sampling
        #     (50 − N/125 floored at 5%): on CPU the scan step cost is real
        #     host time exactly as in the reference, so the reference's own
        #     bound applies — and the default config then reproduces
        #     reference placement semantics on CPU (VERDICT r3 weak #7).
        n_valid = self.cache.node_count()
        if self.percentage_of_nodes_to_score:
            k = self.num_feasible_nodes_to_find(n_valid)
        elif _default_full_batch():
            k = n_valid
        else:
            k = self.num_feasible_nodes_to_find(n_valid)
        if k < n_valid:
            sample_k = np.int32(k)
            sample_start = (self._start_carry if self._start_carry is not None
                            else np.int32(0))
        else:
            sample_k = None
            sample_start = None
        with mutex:
            if self.device is not device:
                # a worker-side poison killed (or a rebuild replaced) the
                # device between encode and dispatch: the encoded batch
                # references dead arrays — requeue it via backoffQ exactly
                # like a poisoned in-flight batch, never dispatch it
                with self.queue.coalesce_moves():
                    for qp in batched:
                        fwk = self.framework_for_pod(qp.pod)
                        self._fail(fwk, qp, Status.error(
                            "device replaced while batch encoding"),
                            pod_cycle)
                return
            host_pb = device.encoder.last_host_pb
            mode_info = self._topo_mode_info()
            topo_mode, vd_bucket, host_key = mode_info
            telemetry.event("encode", batchId=batch_id, bucket=bucket,
                            pods=len(batched), pipelined=enc is not None)
            # slice gangs plan in-jit (ops/slice.py): hand the batch program
            # the bucketed member index so verdicts ride the packed block
            slice_members, slice_grid = self._slice_batch_args(batched,
                                                               device)
            # namespace-quota screen (ops/quota.py): sync the ledger's
            # used/limit rows into the device and hand the program the
            # batch's ns/req columns — the over-quota verdict column rides
            # the packed block, zero extra dispatch
            quota_ns, quota_req = self._quota_batch_args(batched, device,
                                                         bucket)
            with tracing.span("device.dispatch", topo=topo_mode):
                result = self._run_batch_fn(
                    pb, et, device.nt, device.tc, tb, key,
                    adopt=True,
                    topo_enabled=device.topo_enabled,
                    topo_carry=carry,
                    sample_k=sample_k,
                    sample_start=sample_start,
                    topo_mode=topo_mode,
                    vd_override=vd_bucket,
                    host_key=host_key,
                    ports_enabled=device.encoder.last_has_ports,
                    extra_mask=extra_mask,
                    dra_mask=dra_mask,
                    slice_members=slice_members,
                    slice_grid=slice_grid,
                    quota_ns=quota_ns,
                    quota_req=quota_req,
                    quota_used=device.nsq_used if quota_ns is not None
                    else None,
                    quota_limit=device.nsq_limit if quota_ns is not None
                    else None,
                )
            if result.final_sample_start is not None:
                # keep the rotation index across unsampled batches too (the
                # reference's nextStartNodeIndex persists across attempts) —
                # only sampled batches advance it
                self._start_carry = result.final_sample_start
            t_dispatch = self.now_fn()
            try:
                # stage the one host-read the moment the batch is
                # dispatched: the device→host copy of the packed result
                # block rides along with the execution (and the ring's
                # later batches) instead of paying its own round-trip
                # inside commit_wait
                (result.packed if result.packed is not None
                 else result.node_idx).copy_to_host_async()
            except Exception:  # noqa: BLE001 — optional fast path only
                pass
            self._inflight.append(_Inflight(batched, result, pod_cycle,
                                            t_pop, host_pb, pb, mode_info,
                                            batch_id, bucket,
                                            device.encoder.reclaim_gen,
                                            device, t_dispatch,
                                            quota_col=quota_ns is not None))
        # sig mirrors _run_batch_fn's compile-ledger bucket signature so the
        # flight recorder, compile ledger, and dispatch ledger key alike
        sig = f"{bucket}/{topo_mode or ('general' if device.topo_enabled else 'off')}"
        telemetry.event("dispatch", batchId=batch_id, bucket=bucket,
                        pods=len(batched), topo=topo_mode, sig=sig,
                        packed=result.packed is not None,
                        inflight=len(self._inflight))
        # ledger: the whole batch enters device.inflight (ring dwell),
        # batchId-correlated with the flight recorder's dispatch/commit
        latency_ledger.transition_many(
            [qp.pod.key() for qp in batched], "device.inflight",
            batch_id=batch_id)
        self.smetrics.pipeline_inflight.set(value=len(self._inflight))
        # land the oldest batches beyond the ring depth: their host commits
        # overlap the device execution of everything dispatched after them
        # (depth 0 = synchronous: the batch just dispatched commits now).
        # With the commit worker the handoff is a queue push — batch K's
        # commit runs on the worker thread while this thread pops/encodes/
        # dispatches K+1. The backpressure wait (bounded worker backlog)
        # carries its own span so bench attribution can't mistake a
        # commit-bound pipeline for free overlap.
        while len(self._inflight) > self.pipeline_depth:
            fl = self._inflight.popleft()
            if self.pipeline_depth:
                self.pipelined_batches += 1
            if self.commit_worker is not None:
                backlog = max(1, self.pipeline_depth)
                if self.commit_worker.depth() >= backlog:
                    with tracing.span("device.commit.backpressure"):
                        t_bp = self.now_fn()
                        self.commit_worker.wait_below(backlog)
                        self.smetrics.device_batch_duration.observe(
                            self.now_fn() - t_bp, "commit_backpressure")
                self.commit_worker.submit(fl)
            else:
                self._commit_inflight(fl)
        dur = self.smetrics.device_batch_duration
        dur.observe(t_sync - t0, "upload")
        dur.observe(t_enc - t_sync, "encode")
        dur.observe(t_dispatch - t_enc, "compute")
        self.smetrics.device_batch_size.observe(len(batched))
        # (the sizer's latency observations are fed at the commit site,
        # where the batch's true pop→commit span is known)

    def _try_pipelined_encode(self, batched: List[QueuedPodInfo]):
        """Encode the next batch for dispatch directly on the in-flight
        batch's adopted device carry — legal only when (a) nothing external
        touched the cluster since the in-flight dispatch and (b) encoding
        registers no new signature/term (a fresh row is backfilled from host
        counts that cannot see the in-flight commits). Returns (pb, et, tb)
        or None to take the drain+sync path. Caller holds the device mutex."""
        if not self.pipeline_depth or not self._inflight or self.device is None:
            return None
        if self.commit_worker is not None:
            # async-commit mode: the worker's own in-progress commits dirty
            # the cache, so the has_dirty walk below cannot tell them from
            # external changes. Gate on the event-driven signals instead:
            # any external node-truth event since the chain's last full
            # sync, or a host-rejected commit (device row invalidated),
            # breaks the chain — both strictly conservative.
            if (self._chain_dirty
                    or self.external_change_seq() != self._chain_ext_seq):
                return None
            if any(qp.pod.spec.volumes for qp in batched):
                # the volume prescreen reads self.snapshot, which must not
                # be refreshed while the worker's commit tail may be
                # reading it — PVC batches take the drain+sync path
                return None
        else:
            self.cache.update_snapshot(self.snapshot)
            if self.device.has_dirty(self.snapshot):
                return None  # external change breaks the device-carry chain
        st = self.device.sig_table
        vocab0 = (st.n_sigs, st.n_terms)
        try:
            from ..ops.tiebreak import seeds_for

            pods = [qp.pod for qp in batched]
            bucket = self.sizer.bucket_for(len(pods))
            pb, et = self.device.encoder.encode_pods(
                pods, capacity=bucket, tie_seeds=seeds_for(batched))
            tb = st.encode_topo(pods, capacity=bucket)
            extra_mask = self._volume_masks.build(
                batched, self.snapshot, self.device.encoder,
                self.device.caps.nodes, bucket)
            dra_mask = self._claim_masks.build(batched, self.device, bucket)
        except CapacityError:
            return None  # grow via the drain+sync path (idempotent re-encode)
        if (st.n_sigs, st.n_terms) != vocab0:
            return None
        if self._topo_mode_info() != self._inflight[-1].mode_info:
            # the carry shapes (seg_exist vs term_cnt, vd bucket) differ —
            # land the in-flight batches and restart the chain on host truth
            return None
        return pb, et, tb, extra_mask, dra_mask

    def _drain_inflight(self) -> None:
        """Land every in-flight batch, oldest first. With the commit worker
        this submits the remaining ring and BLOCKS on the worker's flush —
        the one synchronization point the sync/fallback/settle paths rely
        on. A device-death commit poisons the rest (worker backlog stolen
        in one sweep; ring stragglers fail the device-instance check)."""
        if self.commit_worker is not None:
            while self._inflight:
                self.commit_worker.submit(self._inflight.popleft())
            self.commit_worker.flush()
            return
        while self._inflight:
            self._commit_inflight(self._inflight.popleft())

    def _commit_inflight(self, fl: _Inflight) -> None:
        """Land one dispatched batch on the host — on the scheduling thread
        (synchronous mode) or the commit worker. Materializing the PACKED
        result block (node_idx + first_fail in one buffer, its device→host
        copy already staged at dispatch) is the ONE device sync of the batch
        cycle; everything else is async dispatch. A device failure at
        materialization (e.g. the TPU relay dropping mid-flight) fails the
        whole IN-FLIGHT RING back to the queue and rebuilds the device from
        the host cache — crash-only, §5.3. Batches reaching here after a
        death (worker-ring stragglers) carry a stale device instance and
        poison individually without committing."""
        from ..utils import tracing

        from . import telemetry
        from .commit_plane import materialize_profiled

        t0 = self.now_fn()
        wait: Optional[float] = None
        disp: Optional[dict] = None
        packed_ok = fl.result.packed is not None
        mutex = self.commit_plane.device_mutex
        on_worker = self.commit_worker is not None
        if fl.device is not None and fl.device is not self.device:
            # computed on a device that has since died or been rebuilt:
            # slot maps and adopted state no longer correspond — requeue
            # without committing (the per-batch form of ring poison)
            self._poison_batches((fl,), RuntimeError(
                "device rebuilt while batch in flight"), count_breaker=False)
            return
        try:
            from ..utils import relay

            if self.relay_fault_fn is not None:
                # scripted device fault (soak flap / chaos): surfaces at the
                # same point a real relay death would — the materialization
                # read — and takes the identical poison/requeue/rebuild path
                fault = self.relay_fault_fn("commit")
                if fault is not None:
                    raise fault
            relay.count_sync("commit-read")  # THE one blocking read per batch
            # the packed tag keeps bench critical-path attribution honest on
            # mesh-sharded runs: packed=None falls back to per-array reads,
            # a materially different commit-wait shape
            with tracing.span("device.commit.wait", batch=len(fl.qps),
                              packed="packed" if packed_ok else "fallback",
                              worker="commit" if on_worker else "inline"):
                t_wait0 = self.now_fn()
                mode = (fl.mode_info[0] if fl.mode_info else None) or (
                    "general" if getattr(fl.device, "topo_enabled", True)
                    else "off")
                (node_idx, ff, slice_words, quota_words,
                 _), disp = materialize_profiled(
                    fl.result, self.device.caps.nodes,
                    program="schedule_batch", bucket=f"{fl.bucket}/{mode}",
                    t_submit=fl.t_submit or None, now_fn=self.now_fn,
                    batch_id=fl.batch_id, pods=len(fl.qps),
                    quota_col=fl.quota_col,
                    event_extra={"bucket": fl.bucket})
                wait = self.now_fn() - t_wait0
                self.smetrics.device_batch_duration.observe(wait, "commit_wait")
                # residual stall: the transfer was staged at dispatch, so any
                # time spent here is the pipeline waiting on device execution
                self.smetrics.pipeline_stall_seconds.inc(value=wait)
            with mutex:
                self.device.adopt_commits(fl.result, fl.host_pb, node_idx)
            with tracing.span("host.commit", batch=len(fl.qps),
                              worker="commit" if on_worker else "inline"):
                t_host0 = self.now_fn()
                self._commit_batch(fl.qps, fl.result, fl.pod_cycle, fl.t0,
                                   node_idx, pb=fl.pb, ff=ff,
                                   reclaim_gen=fl.reclaim_gen,
                                   batch_id=fl.batch_id,
                                   slice_words=slice_words,
                                   quota_words=quota_words)
                self.smetrics.device_batch_duration.observe(
                    self.now_fn() - t_host0, "commit_host")
            # reconcile: the commits above advanced node generations; the
            # ELIDE-ONLY reconcile refreshes _uploaded_gen for rows whose
            # content matches the adopted mirror, so the next
            # _try_pipelined_encode keeps the carry chain instead of
            # breaking it every batch. Rows needing a real upload (external
            # change, host-rejected commit repair) stay dirty → chain break
            # → safe drain+sync. A host-rejected pod's phantom topology
            # commit can thus survive in the carry for as long as the ring
            # holds already-dispatched batches (conservative direction:
            # nodes look MORE occupied), after which the break resyncs from
            # host truth. The worker reconciles against its OWN snapshot
            # (self.snapshot belongs to the scheduling thread) and reports
            # rows left dirty through the chain gate instead.
            if self.device is not None:
                with tracing.span("device.commit.reconcile",
                                  batch=len(fl.qps),
                                  worker="commit" if on_worker else "inline"):
                    t_rec0 = self.now_fn()
                    snap = (self._commit_snapshot if on_worker
                            else self.snapshot)
                    with mutex:
                        self.cache.update_snapshot(snap)
                        left = self.device.reconcile(snap)
                    if left:
                        self._chain_dirty = True
                    self.smetrics.device_batch_duration.observe(
                        self.now_fn() - t_rec0, "commit_reconcile")
        except Exception as exc:  # noqa: BLE001 — backend death must not kill us
            import logging

            logging.getLogger(__name__).exception("batch commit failed; requeueing")
            # everything dispatched after fl was computed on the dead
            # device; those futures are poison too. Worker mode: steal the
            # worker backlog in one sweep — ring entries still owned by the
            # scheduling thread fail the device-instance check when they
            # arrive. Synchronous mode: clear the ring here (same thread).
            with mutex:
                self.device = None  # full rebuild on next _ensure_device
            self._start_carry = None  # dead-backend future
            if self.commit_worker is not None:
                stale = self.commit_worker.steal_pending()
            else:
                stale = list(self._inflight)
                self._inflight.clear()
            self._poison_batches((fl, *stale), exc)
        else:
            self.relay_breaker.record_success()
            extra = {}
            if disp is not None:  # profiler on: the commit event alone can
                # spot a slow-program outlier batch on /debug/flightrecorder
                extra = {"device_ms": round(disp["execS"] * 1e3, 3),
                         "fetch_ms": round(disp["fetchS"] * 1e3, 3)}
            telemetry.event("commit", batchId=fl.batch_id, bucket=fl.bucket,
                            pods=len(fl.qps), packed=packed_ok,
                            wait_s=round(wait, 6) if wait is not None else None,
                            **extra)
            telemetry.sample_hbm()
        self.smetrics.pipeline_inflight.set(value=len(self._inflight))
        self.smetrics.device_batch_duration.observe(self.now_fn() - t0, "commit")
        # the sizer controls the POP→COMMIT attempt latency: observe it here,
        # where this batch's span just completed (fl.t0 = its pop time). The
        # size fed is the BUCKET (padded program length) — that is what the
        # latency actually tracks. The commit-wait residual feeds the stall
        # model, which caps the bucket where device time outruns the
        # overlapped host window.
        bucket = self.sizer.bucket_for(len(fl.qps))
        self.sizer.update(bucket, self.now_fn() - fl.t0)
        if wait is not None:
            self.sizer.update_wait(bucket, wait)

    def _poison_batches(self, batches, exc: BaseException,
                        count_breaker: bool = True) -> None:
        """Fail dispatched-but-uncommitted batches back to the queue
        (poison + requeue flight events per batch, backoffQ re-entry per
        pod) — the shared tail of ring poison and the stale-device check.
        Requeue moves coalesce into one scan."""
        from . import telemetry

        if count_breaker:
            # relay breaker: count the death; past the threshold (or on a
            # failed half-open probe) the batch path degrades to the oracle
            # until the cheap-cadence probe heals it
            self.relay_breaker.record_failure(exc)
        with self.queue.coalesce_moves():
            for batch in batches:
                telemetry.event("poison", batchId=batch.batch_id,
                                bucket=batch.bucket, pods=len(batch.qps),
                                error=f"{type(exc).__name__}: {exc}"[:200])
                for qp in batch.qps:
                    fwk = self.framework_for_pod(qp.pod)
                    self._fail(fwk, qp,
                               Status.error(f"device batch failed: {exc}"),
                               batch.pod_cycle)
                telemetry.event("requeue", batchId=batch.batch_id,
                                pods=len(batch.qps))

    _VOLUME_FILTERS = frozenset((
        "VolumeRestrictions", "NodeVolumeLimits", "EBSLimits", "GCEPDLimits",
        "AzureDiskLimits", "CinderLimits", "VolumeBinding", "VolumeZone",
    ))

    def _verify_volumes_on_node(self, fwk, state: CycleState, pod: Pod,
                                node_name: str) -> Status:
        """Exact volume-filter check of the device's chosen node (the host
        half of the volume pre-pass; binder.go FindPodVolumes for ONE node)."""
        ni = self.snapshot.get(node_name)
        if ni is None or ni.node is None:
            return Status.error(f"chosen node {node_name} left the snapshot")
        for plugin, _w in fwk.points.get("filter", []):
            if plugin.name() not in self._VOLUME_FILTERS:
                continue
            st = plugin.filter(state, pod, ni)
            if not st.is_success():
                return st
        return Status()

    # default bind-path plugins that tolerate absent PreFilter state (their
    # state is only written for volume-/claim-bearing pods, and those pods
    # run the host prefilter explicitly in _commit_batch; Coscheduling's
    # Permit/Reserve recompute from the store and the waiting-pods map;
    # QuotaAdmission's Reserve charge reads only the pod + its own ledger —
    # its absence from this set silently put a FULL host PreFilter on every
    # batch-committed pod after PR 8, the single largest slice of the
    # r08-measured host.commit bottleneck)
    _DEFAULT_BIND_PATH_PLUGINS = frozenset(
        ("VolumeBinding", "DynamicResources", "Coscheduling",
         "QuotaAdmission"))

    @classmethod
    def _bind_path_needs_prefilter(cls, fwk) -> bool:
        """True when a non-default reserve/permit/pre-bind plugin is present
        (out-of-tree plugins may require PreFilter cycle state)."""
        for point in ("reserve", "permit", "pre_bind"):
            for plugin, _w in fwk.points.get(point, []):
                if plugin.name() not in cls._DEFAULT_BIND_PATH_PLUGINS:
                    return True
        return False

    def _run_batch_fn(self, *args, adopt=False, **kwargs) -> BatchResult:
        """Dispatch the compiled batch program (async — nothing here blocks);
        if the Pallas fused-step kernel fails to compile on this hardware,
        permanently disable it for the process and retry on the plain XLA
        path (graceful degradation, §5.3: the compute backend must never take
        the scheduler down with it). With ``adopt``, the program's evolved
        device arrays (still futures) become the device truth immediately;
        the HOST mirror advances later, at commit time, when node_idx is
        materialized anyway (adopt_commits in _commit_inflight — reading
        node_idx here would force a device sync per dispatch and serialize
        the pipeline)."""
        import logging
        import os

        from . import telemetry

        # compile-ledger attribution: bucket signature = padded pod capacity
        # + topology mode — the two shape axes the sizer/topo walk retraces
        # over (ops/schema.PodBatch.capacity; kwargs as built by the callers)
        mode = kwargs.get("topo_mode") or (
            "general" if kwargs.get("topo_enabled", True) else "off")
        sig = f"{getattr(args[0], 'capacity', '?')}/{mode}"
        try:
            with telemetry.dispatch("schedule_batch", bucket=sig):
                result = self.schedule_batch_fn(*args, **kwargs)
        except Exception:  # noqa: BLE001 — any lowering/runtime failure
            if os.environ.get("KTPU_PALLAS", "auto") == "0":
                raise  # already on the XLA path: a real error
            logging.getLogger(__name__).exception(
                "pallas step failed; disabling KTPU_PALLAS and retrying via XLA")
            os.environ["KTPU_PALLAS"] = "0"
            with telemetry.dispatch("schedule_batch", bucket=sig):
                result = self.schedule_batch_fn(*args, **kwargs)
        if adopt:
            self.device.adopt_device(result)
        return result

    def _commit_batch(self, qps: List[QueuedPodInfo], result: BatchResult,
                      pod_cycle: int, t0: float,
                      node_idx: Optional[np.ndarray] = None,
                      pb=None, ff: Optional[np.ndarray] = None,
                      reclaim_gen: Optional[int] = None,
                      batch_id: str = "",
                      slice_words: Optional[np.ndarray] = None,
                      quota_words: Optional[np.ndarray] = None) -> None:
        if node_idx is None:
            node_idx = np.asarray(result.node_idx)
        # the whole commit — winner binds AND loser requeues — runs inside
        # one queue-move coalescing window: every POD_ADD/POD_DELETE wave
        # the commit's store events fire collapses into one union scan
        with self.queue.coalesce_moves():
            self._commit_batch_coalesced(qps, result, pod_cycle, t0,
                                         node_idx, pb, ff, reclaim_gen,
                                         batch_id, slice_words, quota_words)

    def _commit_batch_coalesced(self, qps: List[QueuedPodInfo],
                                result: BatchResult, pod_cycle: int,
                                t0: float, node_idx: np.ndarray,
                                pb=None, ff: Optional[np.ndarray] = None,
                                reclaim_gen: Optional[int] = None,
                                batch_id: str = "",
                                slice_words: Optional[np.ndarray] = None,
                                quota_words: Optional[np.ndarray] = None
                                ) -> None:
        # ledger: claim time — the batch leaves the device ring and enters
        # the host commit tail (one lock round trip for the whole batch)
        latency_ledger.transition_many(
            [qp.pod.key() for qp in qps], "commit.host", batch_id=batch_id)
        slot_names = self.device.slot_to_name()
        # ff (first_fail) normally arrives unpacked from the packed result
        # block — already on host, zero extra syncs; the lazy reads below
        # only fire for packless (sharded-core) results

        # elastic-cluster commit guard: a winner whose slot was released
        # since dispatch (node removed; possibly already reused by a NEW
        # node), or whose named node left the host cache while the batch
        # was in flight, gets a TYPED rejection + backoffQ requeue — never
        # a ghost placement on a node the kernel did not judge. O(winners).
        stale: Dict[int, str] = {}
        encoder = self.device.encoder
        to_probe: Dict[str, List[int]] = {}
        for i in range(len(qps)):
            idx = int(node_idx[i])
            if idx < 0:
                continue
            if reclaim_gen is not None and encoder.slot_stale_since(
                    idx, reclaim_gen):
                stale[i] = f"slot {idx} reclaimed since dispatch"
                continue
            name = slot_names.get(idx)
            if name is not None:
                to_probe.setdefault(name, []).append(i)
        if to_probe:
            # one cache-lock round trip for the whole batch (per-winner
            # has_real_node calls would put N acquisitions on host.commit,
            # the measured critical-path bottleneck)
            for name in self.cache.missing_real_nodes(to_probe):
                for i in to_probe[name]:
                    stale[i] = f"node {name} removed while batch in flight"

        # device over-quota screen (ops/quota.py): a SCREENED winner whose
        # charge crossed the decision-time used/limit rows surrenders its
        # placement — requeue through the quota gate, which re-judges it
        # against the authoritative host ledger. Losers never flag.
        quota_rejected: Set[int] = set()
        if quota_words is not None:
            from ..ops.quota import QUOTA_OK_BIT, QUOTA_SCREEN_BIT

            for i in range(len(qps)):
                w = int(quota_words[i])
                if (int(node_idx[i]) >= 0 and (w & QUOTA_SCREEN_BIT)
                        and not (w & QUOTA_OK_BIT)):
                    quota_rejected.add(i)

        # gang all-or-nothing (PodGroup/Coscheduling): one vmapped device
        # pass over the batch's gangs decides per-gang verdicts; any gang
        # with an unplaced member is rejected WHOLE — no member of it is
        # assumed or bound, so a partial gang can never strand (the N
        # sequential cycles the oracle path would spend are one kernel here)
        gang_rejected: Dict[int, str] = {}  # batch index -> group key
        gang_members: Dict[str, List[int]] = {}
        slice_gangs: Dict[str, List[int]] = {}
        from ..ops.slice import is_slice_pod

        for i, qp in enumerate(qps):
            gkey = pod_group_key(qp.pod)
            if gkey is not None:
                # slice gangs never take the vmapped gang kernel: their
                # verdict is already on host (planned members are pinned to
                # their torus window, so "every member landed" == placed
                # contiguously) — zero extra device dispatch, zero reads
                if is_slice_pod(qp.pod):
                    slice_gangs.setdefault(gkey, []).append(i)
                else:
                    gang_members.setdefault(gkey, []).append(i)
        if gang_members:
            gang_rejected = self._judge_gangs(qps, result, node_idx,
                                              gang_members)
        if slice_gangs:
            gang_rejected.update(self._judge_slice_gangs(
                qps, node_idx, slice_gangs, slice_words, batch_id, t0))
            gang_members = {**gang_members, **slice_gangs}
        if gang_members and (stale or quota_rejected):
            # a stale or quota-screened member poisons its WHOLE gang: the
            # kernel "placed" it (so _judge_gangs saw the gang complete),
            # but the placement is unlandable — all-or-nothing means every
            # sibling surrenders (a PodGroup never half-admits past quota)
            for gkey, idxs in gang_members.items():
                if idxs[0] in gang_rejected or not any(
                        i in stale or i in quota_rejected for i in idxs):
                    continue
                for i in idxs:
                    gang_rejected[i] = gkey
                plugin = self.framework_for_pod(
                    qps[idxs[0]].pod).plugin("Coscheduling")
                if plugin is not None:
                    plugin.reject_gang(gkey, "incomplete")

        # device preemption screen+rank, ONE call for every failed pod in the
        # batch (the batched analog of DryRunPreemption's parallel fan-out;
        # runs against the current device state, which may already include
        # the next dispatched batch's adopted commits — conservative, and the
        # host verifies the chosen candidate exactly before acting)
        preempt_hints = None
        if pb is not None and any(
            int(node_idx[i]) < 0 for i in range(len(qps))
        ) and self._preemption_wired():
            # cluster-level futility shortcut: when no assigned pod anywhere
            # has lower priority than a failed pod, eviction cannot help —
            # synthesize an all-false screen instead of running the device
            # screen program (it builds [P,N,C,R]-scale intermediates; at 5k
            # nodes on CPU fallback that one execution dominated the
            # Unschedulable row's p99)
            min_prio = self.cache.min_pod_priority()
            failed_prios = [qp.pod.spec.priority for i, qp in enumerate(qps)
                            if int(node_idx[i]) < 0]
            if min_prio is None or all(p <= min_prio for p in failed_prios):
                screen = np.zeros((len(qps), self.device.caps.nodes), bool)
                best = np.full(len(qps), -1, np.int32)
                preempt_hints = (screen, best, dict(self.device.encoder.node_slots))
            if preempt_hints is None:
                try:
                    from ..ops.preempt import screen_prefix
                    from . import telemetry

                    with self.commit_plane.device_mutex:
                        # a priority class first seen this cycle is still
                        # INT_MAX on device (= never evictable) unless
                        # refreshed now; the refresh replaces device.nt, so
                        # it must not interleave with a dispatch's adopt
                        self.device._refresh_class_prio()
                        with telemetry.dispatch(
                                "preempt_screen",
                                bucket=str(getattr(pb, "capacity", "?"))):
                            pres = screen_prefix(pb, self.device.nt,
                                                 result.static_masks,
                                                 node_idx[:len(qps)] < 0)
                        if telemetry.get() is not None:
                            from ..ops.preempt import _screen_jit

                            failed_pad = np.zeros(pb.capacity, bool)
                            failed_pad[:len(qps)] = node_idx[:len(qps)] < 0
                            telemetry.cost_probe(
                                "preempt_screen",
                                str(getattr(pb, "capacity", "?")),
                                _screen_jit,
                                (pb, self.device.nt, result.static_masks,
                                 failed_pad))
                    from ..utils import relay

                    relay.count_sync("preempt-read")
                    screen = np.asarray(pres.screen)
                    best = np.asarray(pres.best)
                    slot_of = dict(self.device.encoder.node_slots)
                    preempt_hints = (screen, best, slot_of)
                except Exception:  # noqa: BLE001 — hints are an optimization only
                    import logging

                    logging.getLogger(__name__).exception("preempt screen failed")

        from .commit_plane import BindItem

        bind_items: List[BindItem] = []
        for i, qp in enumerate(qps):
            pod = qp.pod
            fwk = self.framework_for_pod(pod)
            self.metrics.inc("schedule_attempts")
            idx = int(node_idx[i])
            if i in gang_rejected:
                gkey = gang_rejected[i]
                if idx >= 0:
                    # the program placed (and device-adopted) this member,
                    # but a sibling missed: surrender the placement —
                    # invalidating the row's uploaded generation makes the
                    # next sync repair the device copy from host truth
                    node_name = slot_names.get(idx)
                    if node_name is not None:
                        self._invalidate_device_row(node_name)
                    diagnosis = Diagnosis(
                        unschedulable_plugins={"Coscheduling"})
                else:
                    if ff is None:
                        from ..utils import relay

                        relay.count_sync("diagnosis-read")
                        ff = np.asarray(result.first_fail)
                    diagnosis = self._diagnose(ff[i], slot_names)
                    diagnosis.unschedulable_plugins.add("Coscheduling")
                self._fail(fwk, qp, Status.unschedulable(
                    f'gang "{gkey}" could not be fully placed'),
                    pod_cycle, diagnosis)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                continue
            if i in stale:
                # typed rejection: the device adopted this commit, but the
                # slot's node is gone (or the slot now names a node the
                # kernel never judged). Invalidate whatever row the slot
                # maps to so the next sync repairs the device copy, and
                # requeue via backoffQ — never bind.
                from . import telemetry

                node_name = slot_names.get(idx)
                if node_name is not None:
                    self._invalidate_device_row(node_name)
                telemetry.event("slot_reclaim", batchId=batch_id,
                                pod=pod.key(), slot=idx, reason=stale[i])
                self.metrics.inc("errors")
                self._fail(fwk, qp,
                           Status.error(f"stale placement: {stale[i]}"),
                           pod_cycle)
                self.smetrics.observe_attempt(
                    "error", fwk.profile_name, self.now_fn() - t0)
                continue
            if i in quota_rejected:
                # surrender the placement like a gang-rejected member: the
                # device adopted the commit, so repair the row from host
                # truth, and park the pod back behind the quota gate — the
                # host ledger (commit-time Reserve) stays authoritative, so
                # a stale screen row can only cost a retry, never
                # oversubscribe
                from ..framework.plugins.quota import (
                    ERR_REASON_QUOTA_EXCEEDED)

                node_name = slot_names.get(idx)
                if node_name is not None:
                    self._invalidate_device_row(node_name)
                self._fail(fwk, qp, Status.unresolvable(
                    f'{ERR_REASON_QUOTA_EXCEEDED}: namespace '
                    f'"{pod.meta.namespace}" over quota at decision time '
                    '(device screen)'),
                    pod_cycle,
                    Diagnosis(unschedulable_plugins={"QuotaAdmission"}))
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                continue
            if idx >= 0:
                node_name = slot_names.get(idx)
                if node_name is None:  # stale slot — should not happen
                    self._fail(fwk, qp, Status.error(f"stale node slot {idx}"), pod_cycle)
                    self.smetrics.observe_attempt(
                        "error", fwk.profile_name, self.now_fn() - t0)
                    continue
                state = self._new_cycle_state()
                # Reserve/Permit/PreBind plugins may read PreFilter state;
                # with the default set only VolumeBinding/DynamicResources
                # do (both tolerate absence), so skip the per-pod host
                # prefilter for volume-less, claim-less pods — it is pure
                # overhead on the batch path. Claim pods NEED it: Reserve
                # allocates from the PreFilter claim state, and the re-read
                # also re-verifies the claims still exist at commit time.
                if (pod.spec.volumes or pod.spec.resource_claims
                        or self._bind_path_needs_prefilter(fwk)):
                    _, pre_st = fwk.run_pre_filter_plugins(state, pod)
                    if not pre_st.is_success():
                        # e.g. VolumeRestrictions' RWOP exclusivity rejects
                        # at PreFilter — semantics the compiled program does
                        # not model. The exact sequential path owns the pod
                        # (it re-runs PreFilter and records the proper
                        # unschedulable/unresolvable condition).
                        self._invalidate_device_row(node_name)
                        self.cache.update_snapshot(self.snapshot)
                        self._schedule_fallback(qp, pod_cycle)
                        continue
                if pod.spec.volumes:
                    # the device's volume screen over-admits by design
                    # (ops/volume_mask.py): re-run the EXACT volume filters
                    # on the chosen node only — this both verifies and
                    # populates VolumeBinding's node_bindings for Reserve/
                    # PreBind. O(PVs) once per pod, not per node.
                    st = self._verify_volumes_on_node(fwk, state, pod, node_name)
                    if not st.is_success():
                        # over-admitted choice: the mask was approximate for
                        # this pod. Re-batching could pick the same node
                        # (deterministic tie-break) — route to the EXACT
                        # sequential path instead, which terminates.
                        self._invalidate_device_row(node_name)
                        self.cache.update_snapshot(self.snapshot)
                        self._schedule_fallback(qp, pod_cycle)
                        continue
                if (self.comparer_every_n
                        and self.batch_scheduled % self.comparer_every_n == 0):
                    self._compare_with_oracle(fwk, pod, node_name)
                # the batched bind tail (commit_plane.py) lands the whole
                # batch's winners after the loop: one cache lock round
                # trip, one store transaction, one group-commit WAL line
                bind_items.append(BindItem(fwk, qp, pod, node_name, state))
            else:
                if ff is None:
                    # one [P, N] int8 read covers diagnosis for the whole
                    # batch (vs 8 separate mask transfers)
                    from ..utils import relay

                    relay.count_sync("diagnosis-read")
                    ff = np.asarray(result.first_fail)
                diagnosis = self._diagnose(ff[i], slot_names)
                state = self._new_cycle_state()
                if preempt_hints is not None:
                    from ..framework.plugins.defaultpreemption import DefaultPreemption

                    screen, best, slot_of = preempt_hints
                    best_name = slot_names.get(int(best[i])) if best[i] >= 0 else None
                    state.write(DefaultPreemption.HINTS_KEY,
                                (screen[i], slot_of, best_name))
                self._fail(fwk, qp, Status.unschedulable("no feasible node"), pod_cycle,
                           diagnosis, state=state)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
        if bind_items:
            stats = self.commit_plane.commit_bindings(bind_items, pod_cycle,
                                                      t0)
            # waiting (Permit-parked) pods hold their assume exactly like
            # the per-pod path's WAIT outcome — they count as batch-landed
            self.batch_scheduled += stats.bound + stats.waiting
            for item in bind_items:
                if item.outcome == "failed":
                    # host rejected what the device already adopted (assume/
                    # reserve/bind failure): invalidate the row's uploaded
                    # generation so the next sync re-encodes it from host
                    # truth and the content diff repairs the device copy
                    self._invalidate_device_row(item.node_name)

    def _invalidate_device_row(self, node_name: str) -> None:
        """Drop a node row's uploaded generation (the next sync re-encodes
        it from host truth) and break the pipelined carry chain — the
        device adopted state the host is rejecting."""
        with self.commit_plane.device_mutex:
            if self.device is not None:
                self.device._uploaded_gen.pop(node_name, None)
        self._chain_dirty = True

    def _judge_gangs(self, qps: List[QueuedPodInfo], result: BatchResult,
                     node_idx: np.ndarray,
                     gang_members: Dict[str, List[int]]) -> Dict[int, str]:
        """Per-gang verdicts for one committed batch: run the vmapped gang
        kernel (ops/gang.py via batch.gang_verdicts) over the batch's
        gangs and return {batch index -> group key} for every member of a
        gang that must be rejected whole. Shapes are power-of-two bucketed
        so the kernel compiles once per (gangs, members) bucket."""
        from .batch import gang_verdicts
        from .claim_mask import _bucket

        keys = list(gang_members)
        g_cap = _bucket(len(keys), floor=2)
        m_cap = _bucket(max(len(v) for v in gang_members.values()), floor=2)
        member_idx = np.full((g_cap, m_cap), -1, np.int32)
        member_valid = np.zeros((g_cap, m_cap), bool)
        for g, gkey in enumerate(keys):
            for m, i in enumerate(gang_members[gkey]):
                member_idx[g, m] = i
                member_valid[g, m] = True
        kernel_ok: Optional[np.ndarray] = None
        try:
            from ..utils import relay
            from . import telemetry

            with telemetry.dispatch("gang_verdicts",
                                    bucket=f"{g_cap}x{m_cap}"):
                placed_all_d, kernel_ok_d, _assign = gang_verdicts(
                    result.node_idx, result.first_fail,
                    member_idx, member_valid)
            telemetry.cost_probe("gang_verdicts", f"{g_cap}x{m_cap}",
                                 gang_verdicts,
                                 (result.node_idx, result.first_fail,
                                  member_idx, member_valid))
            relay.count_sync("gang-read")
            placed_all = np.asarray(placed_all_d)
            kernel_ok = np.asarray(kernel_ok_d)
        except Exception:  # noqa: BLE001 — verdicts must never kill the commit
            import logging

            logging.getLogger(__name__).exception("gang kernel failed")
            placed_all = np.array([
                all(int(node_idx[i]) >= 0 for i in gang_members[k])
                for k in keys] + [True] * (g_cap - len(keys)))
        rejected: Dict[int, str] = {}
        for g, gkey in enumerate(keys):
            if bool(placed_all[g]):
                continue
            # reason by kernel verdict: "incomplete" = a distinct-node
            # cover existed on the decision-time masks but the program's
            # sequential evolution (capacity taken by earlier pods) broke
            # it; "infeasible" = no cover exists at all
            reason = ("incomplete"
                      if kernel_ok is not None and bool(kernel_ok[g])
                      else "infeasible")
            for i in gang_members[gkey]:
                rejected[i] = gkey
            fwk = self.framework_for_pod(qps[gang_members[gkey][0]].pod)
            plugin = fwk.plugin("Coscheduling")
            if plugin is not None:
                # tears down waiting members from earlier batches and arms
                # the rejection backoff (the PreFilter fast-fail window)
                plugin.reject_gang(gkey, reason)
        return rejected

    def _slice_batch_args(self, batched: List[QueuedPodInfo], device):
        """Bucketed member index of the batch's slice gangs (ops/slice.py
        marker label + PodGroup key), or (None, None) when the batch has
        none — the common case, whose batch program is unchanged. Member
        rows follow batch order (= queue order), the same ordinal the host
        oracle assigns."""
        from ..ops.slice import is_slice_pod

        groups: Dict[str, List[int]] = {}
        for i, qp in enumerate(batched):
            if is_slice_pod(qp.pod):
                gkey = pod_group_key(qp.pod)
                if gkey is not None:
                    groups.setdefault(gkey, []).append(i)
        if not groups:
            return None, None
        from .claim_mask import _bucket

        g_cap = _bucket(len(groups), floor=2)
        m_cap = _bucket(max(len(v) for v in groups.values()), floor=2)
        member_idx = np.full((g_cap, m_cap), -1, np.int32)
        member_valid = np.zeros((g_cap, m_cap), bool)
        for g, gkey in enumerate(groups):
            for m, i in enumerate(groups[gkey]):
                member_idx[g, m] = i
                member_valid[g, m] = True
        return ((member_idx, member_valid),
                (device.caps.superpods, device.caps.sp_slots))

    def _quota_batch_args(self, batched: List[QueuedPodInfo], device,
                          bucket: int):
        """(ns_idx, req) columns for the batch program's namespace-quota
        screen, or (None, None) when no pod rides a screened namespace.
        Syncs the quota ledger's used/limit rows (own hard + borrowable
        cohort headroom) into the device first, so the screen judges the
        freshest decision-time view. Runs under the device mutex (the
        table sync uploads tensors)."""
        plugin = self._quota_plugin()
        if plugin is None:
            return None, None
        table = plugin.device_quota_table()
        if not table and not device.nsq_slots:
            return None, None
        from ..ops.quota import build_quota_batch_args

        return build_quota_batch_args([qp.pod for qp in batched], device,
                                      table=table, pad_to=bucket)

    def _judge_slice_gangs(self, qps: List[QueuedPodInfo],
                           node_idx: np.ndarray,
                           slice_gangs: Dict[str, List[int]],
                           slice_words: Optional[np.ndarray],
                           batch_id: str, t0: float) -> Dict[int, str]:
        """Slice-gang verdicts from data already on host: the packed
        block's verdict words (plan feasibility) plus node_idx (whether
        every pinned member actually landed — the plan mask makes landing
        equivalent to contiguous placement). No kernel dispatch, no device
        read: the one-blocking-sync guard covers slice batches unchanged."""
        from . import telemetry
        from .batch import SLICE_PLAN_OK_BIT

        rejected: Dict[int, str] = {}
        now = self.now_fn()
        for gkey, idxs in slice_gangs.items():
            plan_ok = slice_words is None or all(
                int(slice_words[i]) & SLICE_PLAN_OK_BIT for i in idxs)
            if all(int(node_idx[i]) >= 0 for i in idxs):
                telemetry.event("slice_assign", batchId=batch_id, gang=gkey,
                                members=len(idxs))
                self.smetrics.slice_wait_duration.observe(
                    now - t0, "scheduled")
                continue
            # "infeasible" = the in-jit planner found no contiguous window
            # on decision-time state; "incomplete" = a window was planned
            # but a pinned member lost it to the scan's sequential evolution
            reason = "incomplete" if plan_ok else "infeasible"
            telemetry.event("slice_reject", batchId=batch_id, gang=gkey,
                            members=len(idxs), reason=reason)
            self.smetrics.slice_wait_duration.observe(now - t0, "rejected")
            for i in idxs:
                rejected[i] = gkey
            fwk = self.framework_for_pod(qps[idxs[0]].pod)
            plugin = fwk.plugin("Coscheduling")
            if plugin is not None:
                plugin.reject_gang(gkey, reason)
            sp = fwk.plugin("SlicePacking")
            if sp is not None:
                # release the oracle plan's node reservations so the retried
                # gang replans against post-rejection state
                sp.forget_gang(gkey)
        self._update_slice_frag_metrics()
        return rejected

    def _update_slice_frag_metrics(self) -> None:
        """Refresh scheduler_slice_fragmentation per superpod from the host
        mirror (numpy — no device sync) and emit an edge-triggered
        frag_alert when a superpod's score crosses the alert threshold
        (KTPU_FRAG_ALERT, default 0.5). Re-arms when the score drops back
        below, so a persistently-shredded superpod alerts once, not per
        batch."""
        device = self.device
        if device is None:
            return
        from ..ops.schema import COL_PODS
        from ..ops.slice import fragmentation_host
        from . import telemetry

        mirror = device._mirror
        valid = mirror["valid"]
        node_free = valid & (mirror["requested"][:, COL_PODS] == 0)
        rows = fragmentation_host(
            mirror["topo_sp"], mirror["topo_pos"], valid, node_free,
            (device.caps.superpods, device.caps.sp_slots))
        threshold = float(os.environ.get("KTPU_FRAG_ALERT", "0.5"))
        alerted = getattr(self, "_frag_alerted", None)
        if alerted is None:
            alerted = self._frag_alerted = set()
        for row in rows:
            self.smetrics.slice_fragmentation.set(
                str(row["sp"]), value=row["frag"])
            if row["frag"] >= threshold and row["sp"] not in alerted:
                alerted.add(row["sp"])
                telemetry.event("frag_alert", superpod=row["sp"],
                                frag=round(row["frag"], 4),
                                largestRun=row["largest_run"],
                                free=row["free"])
            elif row["frag"] < threshold:
                alerted.discard(row["sp"])

    # one immutable Status per attribution id, shared across every node and
    # every diagnosis — building 5k fresh Status objects per failed pod was
    # ~15ms of the Unschedulable row's tail
    _SHARED_STATUSES = tuple(
        Status.unschedulable(reason).with_plugin(plugin)
        for plugin, reason in _ATTRIBUTION_ORDER)

    def _diagnose(self, ff_row: np.ndarray, slot_names: Dict[int, str]) -> Diagnosis:
        """Per-node first-failing plugin in filter config order, read straight
        from the device-computed first_fail ids, so failure messages and queue
        gating stay reference-shaped (SURVEY.md §8 'filter short-circuit
        semantics'). Vectorized: one nonzero pass over the row, shared Status
        instances per plugin id."""
        d = Diagnosis()
        failing = np.nonzero(ff_row)[0]
        statuses = self._SHARED_STATUSES
        for slot in failing:
            name = slot_names.get(int(slot))
            if name is None:
                continue
            st = statuses[int(ff_row[slot]) - 1]
            d.node_to_status[name] = st
            d.unschedulable_plugins.add(st.plugin)
        return d

    def _fail(self, fwk, qp: QueuedPodInfo, status: Status, pod_cycle: int,
              diagnosis: Optional[Diagnosis] = None,
              state: Optional[CycleState] = None) -> None:
        self._handle_scheduling_failure(fwk, state or CycleState(), qp, status,
                                        diagnosis or Diagnosis(), pod_cycle)

    def _preemption_wired(self) -> bool:
        """True when any profile runs a PostFilter (screen computation is
        wasted otherwise)."""
        cached = getattr(self, "_preempt_wired", None)
        if cached is None:
            cached = any(f.points.get("post_filter") for f in self.profiles.values())
            self._preempt_wired = cached
        return cached

    def _compare_with_oracle(self, fwk, pod: Pod, node_name: str) -> None:
        """Device/host comparer (§5.2): re-run the scalar oracle filters for
        this pod against the CURRENT snapshot (which reflects all commits the
        device saw before this pod, since assume updates the cache in commit
        order) and flag placements the oracle rejects."""
        import logging

        self.cache.update_snapshot(self.snapshot)
        ni = self.snapshot.get(node_name)
        self.comparer_checks += 1
        if ni is None or ni.node is None:
            self.comparer_mismatches += 1
            logging.getLogger(__name__).warning(
                "comparer: device placed %s on unknown node %s", pod.key(), node_name)
            return
        state = CycleState()
        _, status = fwk.run_pre_filter_plugins(state, pod)
        if status.is_success():
            status = fwk.run_filter_plugins(state, pod, ni)
        if not status.is_success():
            self.comparer_mismatches += 1
            logging.getLogger(__name__).warning(
                "comparer: oracle rejects device placement %s -> %s: %s",
                pod.key(), node_name, "; ".join(status.reasons))

    def warm_buckets(self, sample_pods=None) -> int:
        """Precompile the batch program at every sizer bucket for the
        CURRENT device/topo configuration (both the fresh and the
        pipelined-carry trace variants). Deadline-cut batches switch pod
        buckets at runtime; without warmup the first batch at each bucket
        pays a multi-second jit compile inside the measured window, which
        poisons both the latency histogram and the sizer's model. Returns
        the number of (bucket, variant) programs compiled/hit in cache.

        ``sample_pods``: pods shaped like the INCOMING workload (not yet in
        the store). Encoding them registers their topology signatures/terms
        first, so the warmed programs are the topo-mode variants the real
        batches will run — without a sample, a cluster whose first spread/
        affinity pods arrive in the measured window would warm the
        topology-off program and compile the topo one mid-measure."""
        from . import telemetry

        # deliberate precompilation: retraces keep counting (the bench's
        # measured-phase delta is taken after this), storms are not
        # flagged — a warmup sweep is not a mid-run bucket walk
        with telemetry.calibration():
            return self._warm_buckets_inner(sample_pods)

    def _warm_buckets_inner(self, sample_pods=None) -> int:
        from ..api.wrappers import make_pod

        self._drain_inflight()
        self._ensure_device()
        self.cache.update_snapshot(self.snapshot)
        self.device.sync(self.snapshot)
        if sample_pods:
            pods_for_warm = list(sample_pods)
        else:
            pods_for_warm = [make_pod("__bucket_warm__").req({"cpu": "1m"}).obj()]
        # registration pass: encoding the sample grows the sig/term tables
        # FIRST, so the topo-mode decision below matches what the real
        # batches will select (capacity growth retried like _flush_batch)
        for _attempt in range(8):
            try:
                self.device.encoder.encode_pods(
                    pods_for_warm,
                    capacity=self.sizer.bucket_for(len(pods_for_warm)))
                self.device.sig_table.encode_topo(
                    pods_for_warm,
                    capacity=self.sizer.bucket_for(len(pods_for_warm)))
                break
            except CapacityError as e:
                self._resync_grown(e)
        else:
            import logging

            logging.getLogger(__name__).warning(
                "warm_buckets: capacities refused to converge for the "
                "sample; warming with unregistered topology (degraded)")
        self.device.sync(self.snapshot)  # refresh counts for new sigs
        n_valid = self.cache.node_count()
        if self.percentage_of_nodes_to_score or not _default_full_batch():
            k = self.num_feasible_nodes_to_find(n_valid)
        else:
            k = n_valid
        sample_k = np.int32(k) if k < n_valid else None
        sample_start = np.int32(0) if k < n_valid else None
        mode_info = self._topo_mode_info()
        topo_mode, vd_bucket, host_key = mode_info
        warmed = 0
        timings = []  # (bucket, warm execution seconds)
        for bucket in sorted({self.sizer.bucket_for(b)
                              for b in self.sizer._ladder()}):
            # a sample larger than the bucket truncates rather than skipping:
            # small buckets are exactly the ones deadline cuts switch to
            warm_slice = pods_for_warm[:bucket]
            try:
                pb, et = self.device.encoder.encode_pods(warm_slice,
                                                         capacity=bucket)
                tb = self.device.sig_table.encode_topo(warm_slice,
                                                       capacity=bucket)
            except CapacityError:
                continue
            common = dict(adopt=False, topo_enabled=self.device.topo_enabled,
                          sample_k=sample_k, sample_start=sample_start,
                          topo_mode=topo_mode, vd_override=vd_bucket,
                          host_key=host_key,
                          ports_enabled=self.device.encoder.last_has_ports)
            res = self._run_batch_fn(pb, et, self.device.nt, self.device.tc,
                                     tb, np.int32(0), topo_carry=None, **common)
            np.asarray(res.node_idx)  # land compile + first execution
            # ports_enabled is a static argname → two executables per bucket.
            # Warm the variant the sample did NOT exercise too, so a batch
            # whose port-bearing mix differs from the warm sample doesn't
            # compile inside the measured window.
            other = dict(common, ports_enabled=not common["ports_enabled"])
            res_o = self._run_batch_fn(pb, et, self.device.nt, self.device.tc,
                                       tb, np.int32(0), topo_carry=None, **other)
            np.asarray(res_o.node_idx)
            if any(p.spec.volumes for p in warm_slice):
                # volume workloads dispatch with an extra_mask tensor — a
                # distinct trace signature; warm it (all-True mask) so the
                # first PVC batch doesn't compile mid-measure
                vm = np.ones((bucket, self.device.caps.nodes), bool)
                res_v = self._run_batch_fn(pb, et, self.device.nt,
                                           self.device.tc, tb, np.int32(0),
                                           topo_carry=None,
                                           **dict(common, extra_mask=vm))
                np.asarray(res_v.node_idx)
                if res_v.final_sel_counts is not None:
                    # the pipelined steady state runs mask+carry — warm that
                    # trace too (PreemptionPVs compiled it mid-measure)
                    res_vc = self._run_batch_fn(
                        pb, et, self.device.nt, self.device.tc, tb,
                        np.int32(0),
                        topo_carry=(res_v.final_sel_counts,
                                    res_v.final_seg_exist),
                        **dict(common, extra_mask=vm))
                    np.asarray(res_vc.node_idx)
            if any(p.spec.resource_claims for p in warm_slice):
                # claim workloads dispatch with a dra_mask tensor — its own
                # trace signature; warm it (all-True) plus the carry variant
                # the pipelined steady state runs. A batch can carry BOTH
                # masks (mixed volume+claim pods): warm that combination too
                # when the sample has volumes, else the first mixed batch
                # compiles mid-measure.
                dm = np.ones((bucket, self.device.caps.nodes), bool)
                variants = [dict(common, dra_mask=dm)]
                if any(p.spec.volumes for p in warm_slice):
                    vm2 = np.ones((bucket, self.device.caps.nodes), bool)
                    variants.append(dict(common, extra_mask=vm2, dra_mask=dm))
                for var in variants:
                    res_d = self._run_batch_fn(pb, et, self.device.nt,
                                               self.device.tc, tb, np.int32(0),
                                               topo_carry=None, **var)
                    np.asarray(res_d.node_idx)
                    if res_d.final_sel_counts is not None:
                        res_dc = self._run_batch_fn(
                            pb, et, self.device.nt, self.device.tc, tb,
                            np.int32(0),
                            topo_carry=(res_d.final_sel_counts,
                                        res_d.final_seg_exist),
                            **var)
                        np.asarray(res_dc.node_idx)
            warmed += 1
            # time a clean second execution: the calibration sample
            t0 = self.now_fn()
            res2 = self._run_batch_fn(pb, et, self.device.nt, self.device.tc,
                                      tb, np.int32(1), topo_carry=None, **common)
            np.asarray(res2.node_idx)
            timings.append((bucket, self.now_fn() - t0))
            if res.final_sel_counts is not None:
                # the pipelined path re-traces with a carry: warm it too.
                # BLOCK on it — an unmaterialized warm program would execute
                # lazily ahead of the first real batch and hand it a
                # multi-hundred-ms stall (the p99 tail this warmup exists
                # to remove).
                res3 = self._run_batch_fn(
                    pb, et, self.device.nt, self.device.tc, tb, np.int32(0),
                    topo_carry=(res.final_sel_counts, res.final_seg_exist),
                    **common)
                np.asarray(res3.node_idx)
                warmed += 1
            if self._preemption_wired() and res.static_masks:
                # failure-path program: the preemption screen compiles on
                # the first batch with failures — a workload whose failures
                # only appear mid-measure (Unschedulable) would pay it
                # inside the window otherwise
                try:
                    from ..ops.preempt import screen_prefix

                    pres = screen_prefix(pb, self.device.nt, res.static_masks,
                                         np.ones(len(warm_slice), bool))
                    np.asarray(pres.best)
                    warmed += 1
                except Exception:  # noqa: BLE001 — warm-only optimization
                    pass
        self._calibrate_sizer(timings)
        return warmed

    def _calibrate_sizer(self, timings) -> None:
        """Seed the BatchSizer's latency model from the warm runs' measured
        per-bucket execution times (least squares on exec(B) = ea + eb·B).
        The pop→commit latency of a pipelined batch spans its own execution
        plus the ring's worth of batches dispatched after it, so the seed is
        a ≈ (K+1)·ea + host overhead, b ≈ (K+1)·eb for ring depth K. Without
        this the model starts from blind seeds and the first dozen measured
        batches are spent oscillating through buckets (each flip breaking
        the pipelined carry chain)."""
        if len(timings) < 2:
            return
        xs = np.array([float(b) for b, _ in timings])
        ys = np.array([t for _, t in timings])
        eb, ea = np.polyfit(xs, ys, 1)
        if eb <= 0:
            return
        span = self.pipeline_depth + 1
        s = self.sizer
        s._a = max(span * ea, 0.0) + 0.03
        s._b = span * eb
        s.updates = max(s.updates, 3)
        s._outliers = 0
        # the warm runs time EXECUTION directly (idle host): seed the stall
        # model with wait ≈ exec — conservative (the steady state subtracts
        # the overlapped host window), and the commit-site observations
        # correct it within a few batches
        s._wfit.a = max(ea, 0.0)
        s._wfit.b = eb
        s._wfit.updates = max(s._wfit.updates, 3)
        s._bucket = None  # let target() re-derive from the calibrated model
        s.target()  # pin the sticky bucket now

    def _schedule_fallback(self, qp: QueuedPodInfo, pod_cycle: int) -> None:
        """Sequential oracle path for pods the kernel doesn't cover."""
        before = self.metrics["scheduled"]
        self.schedule_one_pod(qp, pod_cycle)
        if self.metrics["scheduled"] > before:
            self.fallback_scheduled += 1

    # ------------------------------------------------------------- driving

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                          idle_wait: float = 0.005, max_no_progress: int = 200) -> int:
        """Drive cycles until the queue settles (the shared batched loop,
        Scheduler.run_batched_until_settled), then land any in-flight batch."""
        cycles = self.run_batched_until_settled(
            max_cycles=max_cycles, flush=flush, idle_wait=idle_wait,
            max_no_progress=max_no_progress)
        self._drain_inflight()
        if self._profiling:  # fewer batches than the window: flush the trace
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a torn profiler trace must not fail the settle
                pass
            self._profiling = False
            self._profile_dir = ""
        return cycles
