"""TPUScheduler: the batched execution backend wired into the scheduler.

Replaces the per-pod findNodesThatFitPod/prioritizeNodes middle of the cycle
(schedule_one.go:364,:605) with one compiled device call per pod micro-batch;
queue, cache, assume, bind, and failure handling are the same host machinery
as the sequential path (the BASELINE.json north star, minus the gRPC hop —
the control plane here is in-process Python rather than a Go sidecar peer).

Flow per batch cycle:
  1. drain up to `batch_size` pods from the queue in queue order;
  2. update the cache snapshot; delta-sync the device mirror;
  3. split batch-supported pods from fallback pods (features the kernel
     doesn't cover yet go through the sequential oracle path — graceful
     degradation, SURVEY.md §5.3 build mapping);
  4. one `schedule_batch` call: static masks + in-scan sequential commit;
  5. host: assume + bind winners in order; losers get reference-shaped
     Diagnosis (first-failing-plugin per node, reconstructed from the masks
     in filter config order) and re-queue with backoff.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..api.types import Pod
from ..framework.interface import CycleState, Status
from ..framework.types import Diagnosis, QueuedPodInfo
from ..ops.encode import CapacityError
from ..scheduler.scheduler import Scheduler
from .batch import BatchResult, build_schedule_batch_fn
from .device_state import DeviceState, caps_for_cluster

# filter config order for failure attribution (default_plugins.go filter order)
_ATTRIBUTION_ORDER = (
    ("NodeUnschedulable", "node(s) were unschedulable"),
    ("NodeName", "node(s) didn't match the requested node name"),
    ("TaintToleration", "node(s) had untolerated taint"),
    ("NodeAffinity", "node(s) didn't match Pod's node affinity/selector"),
    ("NodePorts", "node(s) didn't have free ports for the requested pod ports"),
    ("NodeResourcesFit", "Insufficient resources"),
    ("PodTopologySpread", "node(s) didn't match pod topology spread constraints"),
    ("InterPodAffinity", "node(s) didn't match pod affinity/anti-affinity rules"),
)


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the batched kernels compile once per
    (bucket, batch) shape per machine, not per process — first-run warmup is
    the dominant cost otherwise (§5.4: persist nothing beyond compiled-
    executable caches)."""
    import os

    if getattr(_enable_compilation_cache, "_done", False):
        return
    _enable_compilation_cache._done = True
    cache_dir = os.environ.get(
        "KTPU_COMPILE_CACHE", os.path.expanduser("~/.cache/kubernetes_tpu_xla")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 — older jax without the knob
        pass


class TPUScheduler(Scheduler):
    def __init__(self, *args, batch_size: int = 128, comparer_every_n: int = 0,
                 **kwargs):
        super().__init__(*args, **kwargs)
        _enable_compilation_cache()
        self.batch_size = batch_size
        # device/host comparer (SURVEY.md §5.2 mapping of the cache drift
        # detector): every Nth device commit, re-check the placement with
        # the scalar oracle filters; 0 disables
        self.comparer_every_n = comparer_every_n
        self.comparer_checks = 0
        self.comparer_mismatches = 0
        self.device: Optional[DeviceState] = None
        self._batchable_cache: Dict[str, bool] = {}
        self.schedule_batch_fn = build_schedule_batch_fn()
        self.batch_counter = 0
        self._batch_t0 = 0.0
        self.fallback_scheduled = 0
        self.batch_scheduled = 0
        # async pipeline (SURVEY §2.7 P3 analog): at most one dispatched
        # batch in flight; its host commit overlaps the next batch's device
        # compute. KTPU_PIPELINE=0 forces the synchronous path.
        import os

        self._pipeline_enabled = os.environ.get("KTPU_PIPELINE", "1") != "0"
        self._inflight: Optional[_Inflight] = None
        self.pipelined_batches = 0

    # ------------------------------------------------------------- device mgmt

    def _ensure_device(self) -> None:
        n = max(self.cache.node_count(), 1)
        if self.device is None:
            self.device = DeviceState(caps_for_cluster(n, batch=self.batch_size),
                                      ns_labels_fn=self.store.ns_labels)
            self.device.sync(self.snapshot)
        elif self.device.caps.nodes < n:
            # preserve every previously-grown axis; only widen the node axis
            # (and the hostname value vocab that must cover it)
            import dataclasses

            caps = self.device.caps
            nodes = caps.nodes
            while nodes < n:
                nodes *= 2
            caps = dataclasses.replace(
                caps, nodes=nodes,
                value_words=max(caps.value_words, (nodes + 2 + 31) // 32),
            )
            self.device = DeviceState(caps, ns_labels_fn=self.store.ns_labels)
            self.device.sync(self.snapshot)

    # CapacityError.dimension → Capacities field(s) to double (exact names
    # raised by ops/encode.py; "value vocab for 'key'" handled by prefix)
    _GROW_FIELDS = {
        "nodes": ("nodes",),
        "pods": ("pods",),
        "resources": ("resources",),
        "label_keys": ("label_keys",),
        "taints": ("taints",),
        "tolerations": ("tolerations",),
        "exprs": ("exprs",),
        "sel_exprs": ("sel_exprs",),
        "terms": ("terms",),
        "term_exprs": ("term_exprs",),
        "pref_terms": ("pref_terms",),
        "ports": ("ports",),
        "ports vocab": ("port_words",),
        "image vocab": ("image_words", "images"),
        "containers": ("containers",),
        "sigs": ("sigs",),
        "ex_terms": ("ex_terms",),
        "spread_cons": ("spread_cons",),
        "ipa_terms": ("ipa_terms",),
        "ipa_pref": ("ipa_pref",),
    }

    def _resync_grown(self, err: CapacityError) -> None:
        """Grow exactly the offending capacity axis and rebuild the mirror."""
        import dataclasses

        caps = self.device.caps
        fields = self._GROW_FIELDS.get(err.dimension)
        if fields is None and err.dimension.startswith("value vocab"):
            fields = ("value_words",)
        if fields is None:
            raise RuntimeError(f"unknown capacity dimension {err.dimension!r}") from err
        updates = {}
        for f in fields:
            v = getattr(caps, f)
            while v < err.needed:
                v *= 2
            updates[f] = v
        self.device = DeviceState(dataclasses.replace(caps, **updates),
                                  ns_labels_fn=self.store.ns_labels)
        self.device.sync(self.snapshot)

    # ------------------------------------------------------------- batch support

    def batch_supported(self, pod: Pod) -> bool:
        """Features the batched kernel covers today; the rest take the
        sequential oracle path (config fallback knob, SURVEY.md §7).
        Topology spread and inter-pod affinity run on device via the
        sig-count kernels (ops/topology.py); volume plugins stay on the host
        path (volume.py — PreBind-heavy, off the hot loop per SURVEY.md §7
        hard-part 6)."""
        if pod.spec.volumes:
            return False
        # a non-default plugin set would diverge from the compiled program's
        # semantics: only batch pods whose profile IS the default set
        return self._framework_batchable(self.framework_for_pod(pod))

    def _framework_batchable(self, fwk) -> bool:
        """True iff the profile's filter/score plugin sets and weights match
        what the compiled batch program implements (the default set). Custom
        profiles fall back to the sequential oracle path wholesale."""
        cached = self._batchable_cache.get(fwk.profile_name)
        if cached is not None:
            return cached
        from ..framework.registry import DEFAULT_PLUGINS

        ok = True
        for point in ("pre_filter", "filter", "pre_score", "score"):
            have = [(p.name(), w) for p, w in fwk.points.get(point, [])]
            want = list(DEFAULT_PLUGINS.get(point, []))
            if have != want:
                ok = False
                break
        self._batchable_cache[fwk.profile_name] = ok
        return ok

    # ------------------------------------------------------------- the batch cycle

    def schedule_batch_cycle(self) -> int:
        """Schedule up to one micro-batch; returns pods processed.

        Queue order is preserved across the batch/fallback split: pods are
        walked in pop order, consecutive batch-supported pods accumulate into
        one device call, and hitting a fallback pod first flushes the
        accumulated batch — so a high-priority fallback pod never loses its
        turn to lower-priority batched pods (reference strict-serial order)."""
        self._periodic_housekeeping()
        qps = self.queue.pop_batch(self.batch_size)
        if not qps:
            return 0
        # Attempt-latency clock for every pod in this batch: pop → commit.
        # Batching trades per-pod latency for throughput; the p99 of this
        # histogram is the iso-latency evidence BASELINE.md demands.
        self._batch_t0 = self.now_fn()
        pod_cycle = self.queue.scheduling_cycle

        buffer: List[QueuedPodInfo] = []
        self._ensure_device()
        for qp in qps:
            pod = self.store.get_pod(qp.pod.key())
            if pod is None or pod.spec.node_name or not self._responsible_for(pod):
                continue  # skipPodSchedule
            qp.pod = pod
            if self.batch_supported(pod):
                buffer.append(qp)
                continue
            # fallback pod: flush what's queued first (strict pop order),
            # then give the sequential path a fresh snapshot
            self._flush_batch(buffer, pod_cycle)
            buffer = []
            self.cache.update_snapshot(self.snapshot)
            self._schedule_fallback(qp, pod_cycle)
        self._flush_batch(buffer, pod_cycle)
        return len(qps)

    def _flush_batch(self, batched: List[QueuedPodInfo], pod_cycle: int) -> None:
        if not batched:
            return
        t0 = self.now_fn()
        self.cache.update_snapshot(self.snapshot)
        for _attempt in range(8):
            try:
                self.device.sync(self.snapshot)
                t_sync = self.now_fn()
                pods = [qp.pod for qp in batched]
                pb, et = self.device.encoder.encode_pods(pods)
                tb = self.device.sig_table.encode_topo(pods)
                break
            except CapacityError as e:
                self._resync_grown(e)
        else:
            for qp in batched:  # capacities refuse to converge
                self._schedule_fallback(qp, pod_cycle)
            return
        t_enc = self.now_fn()
        self.batch_counter += 1
        key = jax.random.PRNGKey(self.batch_counter)
        result = self._run_batch_fn(
            pb, et, self.device.nt, self.device.tc, tb, key,
            pb_for_adopt=pb,
            topo_enabled=self.device.topo_enabled,
        )
        t_compute = self.now_fn()
        self._commit_batch(batched, result, pod_cycle)
        t_commit = self.now_fn()
        dur = self.smetrics.device_batch_duration
        dur.observe(t_sync - t0, "upload")
        dur.observe(t_enc - t_sync, "encode")
        dur.observe(t_compute - t_enc, "compute")
        dur.observe(t_commit - t_compute, "commit")
        self.smetrics.device_batch_size.observe(len(batched))

    @staticmethod
    def _bind_path_needs_prefilter(fwk) -> bool:
        """True when a non-default reserve/permit/pre-bind plugin is present
        (out-of-tree plugins may require PreFilter cycle state)."""
        for point in ("reserve", "permit", "pre_bind"):
            for plugin, _w in fwk.points.get(point, []):
                if plugin.name() != "VolumeBinding":
                    return True
        return False

    def _run_batch_fn(self, *args, pb_for_adopt=None, **kwargs) -> BatchResult:
        """Run the compiled batch program; if the Pallas fused-step kernel
        fails to compile/execute on this hardware, permanently disable it
        for the process and retry on the plain XLA path (graceful
        degradation, §5.3: the compute backend must never take the
        scheduler down with it). On success, the program's evolved dynamic
        state is adopted so the next sync elides commit-only row uploads."""
        import logging
        import os

        try:
            result = self.schedule_batch_fn(*args, **kwargs)
        except Exception:  # noqa: BLE001 — any lowering/runtime failure
            if os.environ.get("KTPU_PALLAS", "auto") == "0":
                raise  # already on the XLA path: a real error
            logging.getLogger(__name__).exception(
                "pallas step failed; disabling KTPU_PALLAS and retrying via XLA")
            os.environ["KTPU_PALLAS"] = "0"
            result = self.schedule_batch_fn(*args, **kwargs)
        if pb_for_adopt is not None:
            # both halves of the adopt, in order: device arrays first (never
            # blocks — futures), then the host mirror that makes the next
            # sync's content diff elide commit-only rows. Missing either one
            # leaves device and mirror divergent (r2's stale-device bug).
            self.device.adopt_device(result)
            self.device.adopt_commits(result, pb_for_adopt, np.asarray(result.node_idx))
        return result

    def _materialize_masks(self, result: BatchResult) -> Dict[str, np.ndarray]:
        """Pull the per-plugin feasibility masks to host — ONLY on failure
        paths (each mask is a [batch, nodes] device→host transfer; the happy
        path needs just node_idx)."""
        masks = {k: np.asarray(v) for k, v in result.static_masks.items()}
        masks["NodePorts"] = np.asarray(result.ports_ok)
        masks["NodeResourcesFit"] = np.asarray(result.fit_ok)
        masks["PodTopologySpread"] = np.asarray(result.spread_ok)
        masks["InterPodAffinity"] = np.asarray(result.ipa_ok)
        return masks

    def _commit_batch(self, qps: List[QueuedPodInfo], result: BatchResult, pod_cycle: int) -> None:
        node_idx = np.asarray(result.node_idx)
        slot_names = self.device.slot_to_name()
        masks: Optional[Dict[str, np.ndarray]] = None  # lazy: failures only

        for i, qp in enumerate(qps):
            pod = qp.pod
            fwk = self.framework_for_pod(pod)
            self.metrics["schedule_attempts"] += 1
            idx = int(node_idx[i])
            if idx >= 0:
                node_name = slot_names.get(idx)
                if node_name is None:  # stale slot — should not happen
                    self._fail(fwk, qp, Status.error(f"stale node slot {idx}"), pod_cycle)
                    self.smetrics.observe_attempt(
                        "error", fwk.profile_name, self.now_fn() - self._batch_t0)
                    continue
                state = CycleState()
                # Reserve/Permit/PreBind plugins may read PreFilter state;
                # with the default set only VolumeBinding does (and it
                # tolerates absence), so skip the per-pod host prefilter for
                # volume-less pods — it is pure overhead on the batch path
                if pod.spec.volumes or self._bind_path_needs_prefilter(fwk):
                    fwk.run_pre_filter_plugins(state, pod)
                if (self.comparer_every_n
                        and self.batch_scheduled % self.comparer_every_n == 0):
                    self._compare_with_oracle(fwk, pod, node_name)
                # t0 = batch pop time: the binding cycle observes the
                # scheduled-attempt duration (pop → bind) exactly once.
                self.assume_and_bind(fwk, state, qp, pod, node_name, pod_cycle,
                                     t0=self._batch_t0)
                self.batch_scheduled += 1
            else:
                if masks is None:
                    masks = self._materialize_masks(result)
                diagnosis = self._diagnose(i, masks, slot_names)
                self._fail(fwk, qp, Status.unschedulable("no feasible node"), pod_cycle, diagnosis)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - self._batch_t0)

    def _diagnose(self, i: int, masks: Dict[str, np.ndarray], slot_names: Dict[int, str]) -> Diagnosis:
        """Reconstruct per-node first-failing plugin in filter config order so
        failure messages and queue gating stay reference-shaped (SURVEY.md §8
        'filter short-circuit semantics')."""
        d = Diagnosis()
        for slot, name in slot_names.items():
            for plugin, reason in _ATTRIBUTION_ORDER:
                m = masks.get(plugin)
                if m is not None and not bool(m[i, slot]):
                    d.node_to_status[name] = Status.unschedulable(reason).with_plugin(plugin)
                    d.unschedulable_plugins.add(plugin)
                    break
        return d

    def _fail(self, fwk, qp: QueuedPodInfo, status: Status, pod_cycle: int, diagnosis: Optional[Diagnosis] = None) -> None:
        self._handle_scheduling_failure(fwk, CycleState(), qp, status, diagnosis or Diagnosis(), pod_cycle)

    def _compare_with_oracle(self, fwk, pod: Pod, node_name: str) -> None:
        """Device/host comparer (§5.2): re-run the scalar oracle filters for
        this pod against the CURRENT snapshot (which reflects all commits the
        device saw before this pod, since assume updates the cache in commit
        order) and flag placements the oracle rejects."""
        import logging

        self.cache.update_snapshot(self.snapshot)
        ni = self.snapshot.get(node_name)
        self.comparer_checks += 1
        if ni is None or ni.node is None:
            self.comparer_mismatches += 1
            logging.getLogger(__name__).warning(
                "comparer: device placed %s on unknown node %s", pod.key(), node_name)
            return
        state = CycleState()
        _, status = fwk.run_pre_filter_plugins(state, pod)
        if status.is_success():
            status = fwk.run_filter_plugins(state, pod, ni)
        if not status.is_success():
            self.comparer_mismatches += 1
            logging.getLogger(__name__).warning(
                "comparer: oracle rejects device placement %s -> %s: %s",
                pod.key(), node_name, status.message)

    def _schedule_fallback(self, qp: QueuedPodInfo, pod_cycle: int) -> None:
        """Sequential oracle path for pods the kernel doesn't cover."""
        before = self.metrics["scheduled"]
        self.schedule_one_pod(qp, pod_cycle)
        if self.metrics["scheduled"] > before:
            self.fallback_scheduled += 1

    # ------------------------------------------------------------- driving

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                          idle_wait: float = 0.005, max_no_progress: int = 200) -> int:
        """Drive cycles until the queue settles.

        The reference blocks on ``Pop``; this loop instead waits briefly and
        bounds consecutive no-placement iterations, so a pod that flaps
        between queues (fails, re-enters activeQ with a lapsed backoff, fails
        again) cannot turn this into a hot spin (VERDICT r1 weak #7).
        """
        import time as _time

        cycles = 0
        no_progress = 0
        while cycles < max_cycles:
            before_sched = self.metrics["scheduled"]
            before_unsched = self.queue.pending_pods()["unschedulable"]
            n = self.schedule_batch_cycle()
            if n == 0:
                if flush:
                    self.queue.flush_backoff_completed()
                    if self.queue.pending_pods()["active"] > 0:
                        no_progress += 1
                        if no_progress > max_no_progress:
                            break
                        continue
                break
            cycles += n
            pending = self.queue.pending_pods()
            # Progress = placements OR pods newly parked unschedulable (they
            # stay parked until an external event; failure-draining a batch
            # IS progress toward settling). Only cycles that neither place
            # nor park — a pod flapping straight back into activeQ — pay the
            # wait and count toward the bound.
            if (self.metrics["scheduled"] > before_sched
                    or pending["unschedulable"] > before_unsched):
                no_progress = 0
            else:
                no_progress += 1
                if no_progress > max_no_progress:
                    break
                _time.sleep(idle_wait * min(no_progress, 10))
        return cycles
