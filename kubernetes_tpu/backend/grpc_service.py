"""gRPC binding of the batched device service (SURVEY §5.8 hop 6).

Hardened transport per ROADMAP round-3 item 5: real gRPC framing (HTTP/2,
protobuf messages generated from native/ktpu_device.proto), pod-template
deduplication on ScheduleBatch (the QPS-5000 workloads reuse a handful of
pod shapes, so the steady-state request is one template table + name refs
instead of N full pod objects), and device-computed preemption hints
riding back with unschedulable results.

grpc service stubs are not generated (grpc_tools is absent from the image);
the server registers generic method handlers and the client uses
channel.unary_unary — functionally identical to protoc-gen-grpc output.
Messages come from the vendored module tools/gen_pb2.py emits into
kubernetes_tpu/native/ktpu_device_pb2.py (trusted while its embedded
PROTO_SHA256 matches the .proto source); a stale vendored module falls
back to `protoc --python_out` into native/build (cached by mtime), and
when protoc is absent too the error names the regeneration command.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Dict, Optional

from .errors import (
    ConflictError,
    PermanentDeviceError,
    RetryPolicy,
    StaleEpochError,
    TransientDeviceError,
    raise_injected_fault,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_PROTO_DIR = os.path.join(_REPO_ROOT, "native")
_PROTO = os.path.join(_PROTO_DIR, "ktpu_device.proto")
_BUILD_DIR = os.path.join(_PROTO_DIR, "build")
_PB2 = os.path.join(_BUILD_DIR, "ktpu_device_pb2.py")

_pb2 = None
_pb2_lock = threading.Lock()

SERVICE = "ktpu.v1.Device"


def _proto_sha256() -> str:
    import hashlib

    with open(_PROTO, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _vendored_hash() -> Optional[str]:
    """PROTO_SHA256 literal read from the vendored module's TEXT — the
    staleness check must run BEFORE the module is imported: executing a
    stale module registers 'ktpu_device.proto' in the process-default
    descriptor pool, and the protoc-built fallback would then raise
    duplicate-file instead of loading."""
    import re

    try:
        with open(os.path.join(_REPO_ROOT, "kubernetes_tpu", "native",
                               "ktpu_device_pb2.py"), encoding="utf-8") as f:
            head = f.read(4096)
    except OSError:
        return None
    m = re.search(r'^PROTO_SHA256 = "([0-9a-f]{64})"', head, re.M)
    return m.group(1) if m else None


def _vendored_pb2():
    """The tools/gen_pb2.py-vendored module, or None when it is absent or
    stale against the current .proto source (hash-gated so a proto edit
    without regeneration can never speak a stale schema)."""
    if _vendored_hash() != _proto_sha256():
        return None
    try:
        from ..native import ktpu_device_pb2 as vendored
    except ImportError:
        return None
    return vendored


def pb2_available() -> bool:
    """True when pb2() will succeed: a hash-fresh vendored module, a
    cached protoc build, or protoc itself."""
    from ..utils.protoc import build_available

    if _vendored_pb2() is not None:
        return True
    return build_available(_pb2, _PB2, _PROTO)


def pb2():
    """Import the protobuf message module: the vendored gen_pb2.py output
    when fresh, else a protoc build (cached by mtime)."""
    global _pb2
    if _pb2 is not None:
        return _pb2
    with _pb2_lock:
        if _pb2 is not None:
            return _pb2
        vendored = _vendored_pb2()
        if vendored is not None:
            _pb2 = vendored
            return _pb2
        if (not os.path.exists(_PB2)
                or os.path.getmtime(_PB2) < os.path.getmtime(_PROTO)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            try:
                subprocess.run(
                    ["protoc", f"--python_out={_BUILD_DIR}", "-I",
                     _PROTO_DIR, _PROTO],
                    check=True, capture_output=True, timeout=60)
            except FileNotFoundError as e:
                # typed: deterministic config/availability failure — a
                # retry of the identical call cannot help
                raise PermanentDeviceError(
                    "vendored ktpu_device_pb2 is stale or missing and protoc "
                    "is not installed; run `python tools/gen_pb2.py`") from e
        import importlib.util

        spec = importlib.util.spec_from_file_location("ktpu_device_pb2", _PB2)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _pb2 = mod
        return _pb2


# ----------------------------------------------------------- dict <-> proto
# (the transport speaks backend/service.py's dict payloads at both ends, so
# DeviceService and WireScheduler stay transport-agnostic)


def _deltas_to_proto(payload: dict):
    p = pb2()
    req = p.ApplyDeltasRequest(full=bool(payload.get("full")))
    for e in payload.get("nodes", ()):
        req.nodes.append(p.NodeDelta(
            node_json=json.dumps(e["node"]).encode(),
            pod_json=[json.dumps(pw).encode() for pw in e.get("pods", ())],
            gen=int(e.get("gen", 0))))
    req.removed.extend(payload.get("removed", ()))
    for ns, labels in (payload.get("namespaces") or {}).items():
        req.namespaces[ns] = json.dumps(labels).encode()
    req.traceparent = payload.get("traceparent") or ""
    req.expect_epoch = payload.get("expectEpoch") or ""
    fields = req.DESCRIPTOR.fields_by_name
    if "inflight_batch_ids" in fields:
        # pipelined clients: holds from these batches survive owner-content
        # omission (a stale pb2 just drops them — legacy request/response)
        req.inflight_batch_ids.extend(payload.get("inflightBatchIds") or ())
    if "replicator" in fields:
        req.replicator = bool(payload.get("replicator"))
    _stamp_session_proto(req, payload)
    return req


def _stamp_session_proto(req, payload: dict) -> None:
    """clientId/sessionGen onto a request proto (0 = not yet joined); a
    stale pb2 without the fields just drops them (legacy single-client)."""
    fields = req.DESCRIPTOR.fields_by_name
    if "client_id" in fields:
        req.client_id = payload.get("clientId") or ""
        req.session_gen = int(payload.get("sessionGen") or 0)


def _session_from_proto(req) -> dict:
    fields = req.DESCRIPTOR.fields_by_name
    if "client_id" not in fields:
        return {}
    out = {"clientId": req.client_id or None}
    if req.session_gen:
        out["sessionGen"] = int(req.session_gen)
    return out


def _deltas_from_proto(req) -> dict:
    out = {
        "full": req.full,
        "nodes": [{
            "node": json.loads(e.node_json),
            "pods": [json.loads(b) for b in e.pod_json],
            "gen": e.gen,
        } for e in req.nodes],
        "removed": list(req.removed),
        "namespaces": {ns: json.loads(b) for ns, b in req.namespaces.items()},
    }
    if req.traceparent:
        out["traceparent"] = req.traceparent
    if req.expect_epoch:
        out["expectEpoch"] = req.expect_epoch
    fields = req.DESCRIPTOR.fields_by_name
    if "inflight_batch_ids" in fields and req.inflight_batch_ids:
        out["inflightBatchIds"] = list(req.inflight_batch_ids)
    if "replicator" in fields and req.replicator:
        out["replicator"] = True
    out.update(_session_from_proto(req))
    return out


def _batch_to_proto(payload: dict):
    """Template-dedup encode: per pod, strip the only per-pod fields (name/
    uid) out of the wire dict; identical remainders share one table entry."""
    p = pb2()
    req = p.ScheduleBatchRequest()
    table: Dict[bytes, int] = {}
    for pw in payload.get("pods", ()):
        meta = dict(pw.get("meta") or {})
        name = meta.pop("name", "")
        uid = meta.pop("uid", "")
        namespace = meta.get("namespace", "default")
        tmpl = json.dumps(dict(pw, meta=meta), sort_keys=True).encode()
        idx = table.get(tmpl)
        if idx is None:
            idx = len(req.templates)
            table[tmpl] = idx
            req.templates.append(tmpl)
        req.pods.append(p.PodRef(template=idx, name=name,
                                 namespace=namespace, uid=uid))
    req.tie_seeds.extend(int(s) for s in payload.get("tieSeeds", ()))
    req.traceparent = payload.get("traceparent") or ""
    req.expect_epoch = payload.get("expectEpoch") or ""
    req.batch_id = payload.get("batchId") or ""
    from ..api import dra

    for c in payload.get("claims") or ():
        pc = req.claims.add()
        pc.pod = int(c.get("pod", 0))
        for key, op, kind, operand in c.get("selectors") or ():
            s = pc.selectors.add()
            s.key = str(key)
            s.op = int(op)
            s.kind = int(kind)
            if int(kind) == dra.KIND_INT:
                s.int_val = int(operand)
            else:
                s.str_val = str(operand)
        pc.allocated_nodes.extend(c.get("allocatedNodes") or ())
    _stamp_session_proto(req, payload)
    return req


def _batch_from_proto(req) -> dict:
    templates = [json.loads(t) for t in req.templates]
    pods = []
    for ref in req.pods:
        tmpl = templates[ref.template]
        meta = dict(tmpl.get("meta") or {})
        meta["name"] = ref.name
        meta["namespace"] = ref.namespace or meta.get("namespace", "default")
        if ref.uid:
            meta["uid"] = ref.uid
        pods.append(dict(tmpl, meta=meta))
    out = {"pods": pods}
    if req.tie_seeds:
        out["tieSeeds"] = list(req.tie_seeds)
    if req.traceparent:
        out["traceparent"] = req.traceparent
    if req.expect_epoch:
        out["expectEpoch"] = req.expect_epoch
    if req.batch_id:
        out["batchId"] = req.batch_id
    if req.claims:
        from ..api import dra

        out["claims"] = [{
            "pod": pc.pod,
            "selectors": [
                [s.key, s.op, s.kind,
                 s.int_val if s.kind == dra.KIND_INT else s.str_val]
                for s in pc.selectors],
            "allocatedNodes": list(pc.allocated_nodes),
        } for pc in req.claims]
    out.update(_session_from_proto(req))
    return out


def _results_to_proto(out: dict):
    p = pb2()
    resp = p.ScheduleBatchResponse()
    has_conflict = "conflict" in p.PodResult.DESCRIPTOR.fields_by_name
    for r in out.get("results", ()):
        pr = p.PodResult(node_name=r.get("nodeName") or "")
        if has_conflict and r.get("conflict"):
            pr.conflict = True
            pr.error = r.get("error") or ""
            resp.results.append(pr)
            continue
        if not pr.node_name:
            pr.unschedulable_plugins.extend(r.get("unschedulablePlugins") or ())
            pr.statuses_json = json.dumps(r.get("statuses") or {}).encode()
            hint = r.get("preempt")
            if hint:
                if hint.get("candidates") is None:
                    pr.preempt.truncated = True
                else:
                    pr.preempt.candidates.extend(hint["candidates"])
                pr.preempt.best = hint.get("best") or ""
        resp.results.append(pr)
    return resp


def _results_from_proto(resp) -> dict:
    results = []
    pod_result_fields = (
        resp.DESCRIPTOR.fields_by_name["results"].message_type.fields_by_name)
    has_conflict = "conflict" in pod_result_fields
    for pr in resp.results:
        if has_conflict and pr.conflict:
            results.append({"nodeName": None, "conflict": True,
                            "error": pr.error or ""})
            continue
        if pr.node_name:
            results.append({"nodeName": pr.node_name})
            continue
        r = {
            "nodeName": None,
            "unschedulablePlugins": list(pr.unschedulable_plugins),
            "statuses": json.loads(pr.statuses_json) if pr.statuses_json else {},
        }
        if pr.HasField("preempt"):
            r["preempt"] = {
                "candidates": (None if pr.preempt.truncated
                               else list(pr.preempt.candidates)),
                "best": pr.preempt.best or None,
            }
        results.append(r)
    return {"results": results}


def _device_time_to_proto(resp, out: dict) -> None:
    """Stamp the dispatch profiler's echoed deviceTime onto the response
    (no-op when the profiler was off or the vendored pb2 predates the
    field — the uniform stale-pb2 degradation rule)."""
    dt = out.get("deviceTime")
    if (not isinstance(dt, dict)
            or "device_time" not in resp.DESCRIPTOR.fields_by_name):
        return
    resp.device_time.dwell_ms = float(dt.get("dwellMs") or 0.0)
    resp.device_time.exec_ms = float(dt.get("execMs") or 0.0)
    resp.device_time.fetch_ms = float(dt.get("fetchMs") or 0.0)
    resp.device_time.device_ms = float(dt.get("deviceMs") or 0.0)


def _device_time_from_proto(resp) -> Optional[dict]:
    """The client half: the HTTP-shaped deviceTime dict, or None when the
    server didn't echo one (profiler off / older server or pb2)."""
    if ("device_time" not in resp.DESCRIPTOR.fields_by_name
            or not resp.HasField("device_time")):
        return None
    return {"dwellMs": resp.device_time.dwell_ms,
            "execMs": resp.device_time.exec_ms,
            "fetchMs": resp.device_time.fetch_ms,
            "deviceMs": resp.device_time.device_ms}


# ------------------------------------------------------------------ server


def serve_grpc(service, port: int = 0):
    """Bind a DeviceService to a localhost gRPC server; returns
    (server, port). Generic handlers stand in for generated service stubs."""
    import grpc
    from concurrent import futures

    p = pb2()

    def _abort_stale(ctx, exc):
        # FAILED_PRECONDITION carries the CURRENT epoch in the details so
        # the client can resync and re-stamp in one round trip (the HTTP
        # binding's 409 + staleEpoch body)
        ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                  f"stale epoch; current={exc.epoch}")

    def _abort_conflict(ctx, exc):
        # ABORTED = the cross-client race / fenced-session verdict (the
        # HTTP binding's 409 + conflict body): the state base is fine, a
        # resync cannot help — rejoin/requeue, never retry the transport
        ctx.abort(grpc.StatusCode.ABORTED, f"commit conflict: {exc}")

    def apply_deltas(request, ctx):
        try:
            out = service.apply_deltas(_deltas_from_proto(request))
        except StaleEpochError as exc:
            _abort_stale(ctx, exc)
        except ConflictError as exc:
            _abort_conflict(ctx, exc)
        resp = p.ApplyDeltasResponse(nodes=int(out.get("nodes", 0)),
                                     epoch=out.get("epoch", ""),
                                     delta_seq=int(out.get("deltaSeq", 0)))
        if "session_gen" in p.ApplyDeltasResponse.DESCRIPTOR.fields_by_name:
            resp.session_gen = int(out.get("sessionGen") or 0)
        return resp

    def schedule_batch(request, ctx):
        try:
            out = service.schedule_batch(_batch_from_proto(request))
        except StaleEpochError as exc:
            _abort_stale(ctx, exc)
        except ConflictError as exc:
            _abort_conflict(ctx, exc)
        resp = _results_to_proto(out)
        resp.epoch = out.get("epoch", "")
        resp.delta_seq = int(out.get("deltaSeq", 0))
        fields = p.ScheduleBatchResponse.DESCRIPTOR.fields_by_name
        if "session_gen" in fields:
            resp.session_gen = int(out.get("sessionGen") or 0)
        if "batch_id" in fields:
            resp.batch_id = out.get("batchId") or ""
        _device_time_to_proto(resp, out)
        return resp

    def heartbeat(request, ctx):
        req_dict = _session_from_proto(request)
        if ("replicator" in request.DESCRIPTOR.fields_by_name
                and request.replicator):
            req_dict["replicator"] = True
        try:
            out = service.heartbeat(req_dict)
        except ConflictError as exc:
            _abort_conflict(ctx, exc)
        resp = p.HeartbeatResponse(
            epoch=out.get("epoch", ""),
            session_gen=int(out.get("sessionGen") or 0),
            sessions=int(out.get("sessions") or 0),
            lease_ttl_s=float(out.get("leaseTtlS") or 0.0),
            delta_seq=int(out.get("deltaSeq") or 0))
        resp.fenced.extend(out.get("fenced") or ())
        return resp

    def sessions_dump(request, ctx):
        out = service.sessions_dump({})
        return p.SessionsResponse(sessions_json=json.dumps(out).encode())

    def health(request, ctx):
        out = service.health({})
        return p.HealthResponse(status=out.get("status", "serving"),
                                epoch=out.get("epoch", ""),
                                delta_seq=int(out.get("deltaSeq", 0)),
                                nodes=int(out.get("nodes", 0)))

    rpc_handlers = {
        "ApplyDeltas": grpc.unary_unary_rpc_method_handler(
            apply_deltas,
            request_deserializer=p.ApplyDeltasRequest.FromString,
            response_serializer=p.ApplyDeltasResponse.SerializeToString),
        "ScheduleBatch": grpc.unary_unary_rpc_method_handler(
            schedule_batch,
            request_deserializer=p.ScheduleBatchRequest.FromString,
            response_serializer=p.ScheduleBatchResponse.SerializeToString),
        "Health": grpc.unary_unary_rpc_method_handler(
            health,
            request_deserializer=p.HealthRequest.FromString,
            response_serializer=p.HealthResponse.SerializeToString),
    }
    if hasattr(p, "HeartbeatRequest"):  # stale pb2: no session verbs
        rpc_handlers["Heartbeat"] = grpc.unary_unary_rpc_method_handler(
            heartbeat,
            request_deserializer=p.HeartbeatRequest.FromString,
            response_serializer=p.HeartbeatResponse.SerializeToString)
        rpc_handlers["Sessions"] = grpc.unary_unary_rpc_method_handler(
            sessions_dump,
            request_deserializer=p.SessionsRequest.FromString,
            response_serializer=p.SessionsResponse.SerializeToString)
    handlers = grpc.method_handlers_generic_handler(SERVICE, rpc_handlers)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((handlers,))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


class GrpcClient:
    """Drop-in for service.WireClient over gRPC: same dict payloads, same
    error taxonomy and retry policy. gRPC status codes map onto the
    taxonomy: UNAVAILABLE/DEADLINE_EXCEEDED are transient,
    FAILED_PRECONDITION is the stale-epoch signal, everything else is
    permanent (a deterministic server exception re-raises on re-send)."""

    _STALE_PREFIX = "stale epoch; current="

    def __init__(self, endpoint: str, read_timeout: float = 60.0,
                 retry: Optional[RetryPolicy] = None, fault_plan=None):
        import grpc

        p = pb2()
        self.read_timeout = read_timeout
        self.retry = retry if retry is not None else RetryPolicy()
        self.fault_plan = fault_plan
        self._grpc = grpc
        self._channel = grpc.insecure_channel(endpoint)
        self._apply = self._channel.unary_unary(
            f"/{SERVICE}/ApplyDeltas",
            request_serializer=p.ApplyDeltasRequest.SerializeToString,
            response_deserializer=p.ApplyDeltasResponse.FromString)
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/ScheduleBatch",
            request_serializer=p.ScheduleBatchRequest.SerializeToString,
            response_deserializer=p.ScheduleBatchResponse.FromString)
        # feature-detect against the COMPILED schema: a stale pb2 built
        # from an older proto must degrade (claim pods fall back to the
        # local sequential path; the half-open probe pushes a full batch)
        # rather than crash mid-request
        self.supports_dra = (
            "claims" in p.ScheduleBatchRequest.DESCRIPTOR.fields_by_name)
        self.supports_health = hasattr(p, "HealthRequest")
        self._health = (self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=p.HealthRequest.SerializeToString,
            response_deserializer=p.HealthResponse.FromString)
            if self.supports_health else None)
        self.supports_sessions = (
            hasattr(p, "HeartbeatRequest")
            and "client_id" in p.ApplyDeltasRequest.DESCRIPTOR.fields_by_name)
        self._heartbeat = (self._channel.unary_unary(
            f"/{SERVICE}/Heartbeat",
            request_serializer=p.HeartbeatRequest.SerializeToString,
            response_deserializer=p.HeartbeatResponse.FromString)
            if self.supports_sessions else None)
        self._sessions = (self._channel.unary_unary(
            f"/{SERVICE}/Sessions",
            request_serializer=p.SessionsRequest.SerializeToString,
            response_deserializer=p.SessionsResponse.FromString)
            if self.supports_sessions else None)

    def _call(self, op: str, stub, request):
        grpc = self._grpc

        def attempt():
            raise_injected_fault(self.fault_plan, op, self.read_timeout)
            try:
                return stub(request, timeout=self.read_timeout)
            except grpc.RpcError as e:
                code = e.code()
                details = e.details() or ""
                if code == grpc.StatusCode.FAILED_PRECONDITION:
                    epoch = ""
                    if self._STALE_PREFIX in details:
                        epoch = details.split(self._STALE_PREFIX, 1)[1].strip()
                    raise StaleEpochError(epoch, details) from e
                if code == grpc.StatusCode.ABORTED:
                    # the typed conflict verdict: fenced session or a
                    # cross-client pod/capacity race — rejoin/requeue
                    raise ConflictError(details or "commit conflict") from e
                if code in (grpc.StatusCode.UNAVAILABLE,
                            grpc.StatusCode.DEADLINE_EXCEEDED,
                            grpc.StatusCode.RESOURCE_EXHAUSTED):
                    raise TransientDeviceError(
                        f"device service {code.name}: {details}") from e
                raise PermanentDeviceError(
                    f"device service {code.name}: {details}") from e

        return self.retry.run(op, attempt)

    @staticmethod
    def _session_gen_out(resp, out: dict) -> dict:
        if ("session_gen" in resp.DESCRIPTOR.fields_by_name
                and resp.session_gen):
            out["sessionGen"] = int(resp.session_gen)
        return out

    def apply_deltas(self, payload: dict) -> dict:
        resp = self._call("apply_deltas", self._apply, _deltas_to_proto(payload))
        out = {"nodes": resp.nodes}
        if resp.epoch:
            out["epoch"] = resp.epoch
            out["deltaSeq"] = resp.delta_seq
        return self._session_gen_out(resp, out)

    def schedule_batch(self, payload: dict) -> dict:
        resp = self._call("schedule_batch", self._schedule,
                          _batch_to_proto(payload))
        out = _results_from_proto(resp)
        if resp.epoch:
            out["epoch"] = resp.epoch
            out["deltaSeq"] = resp.delta_seq
        if ("batch_id" in resp.DESCRIPTOR.fields_by_name and resp.batch_id):
            # echoed idempotency key: the pipelined reply router matches
            # out-of-order replies to their in-flight batches by this id
            out["batchId"] = resp.batch_id
        dt = _device_time_from_proto(resp)
        if dt is not None:
            out["deviceTime"] = dt
        return self._session_gen_out(resp, out)

    def heartbeat(self, payload: dict) -> dict:
        """Lease renewal + takeover signal (HA session verb)."""
        if self._heartbeat is None:
            raise PermanentDeviceError("Heartbeat RPC unsupported by this pb2")
        p = pb2()
        req = p.HeartbeatRequest(
            client_id=payload.get("clientId") or "",
            session_gen=int(payload.get("sessionGen") or 0))
        if ("replicator" in req.DESCRIPTOR.fields_by_name
                and payload.get("replicator")):
            req.replicator = True
        resp = self._call("heartbeat", self._heartbeat, req)
        return {"epoch": resp.epoch, "sessionGen": int(resp.session_gen),
                "sessions": int(resp.sessions),
                "fenced": list(resp.fenced),
                "leaseTtlS": float(resp.lease_ttl_s),
                "deltaSeq": int(resp.delta_seq)}

    def sessions_dump(self) -> dict:
        """Session-table introspection (/debug/sessions passthrough)."""
        if self._sessions is None:
            raise PermanentDeviceError("Sessions RPC unsupported by this pb2")
        resp = self._call("sessions", self._sessions, pb2().SessionsRequest())
        return json.loads(resp.sessions_json or b"{}")

    def health(self) -> dict:
        """The cheap identity/liveness verb (half-open circuit probe)."""
        if self._health is None:
            raise PermanentDeviceError("Health RPC unsupported by this pb2")
        resp = self._call("health", self._health, pb2().HealthRequest())
        return {"status": resp.status, "epoch": resp.epoch,
                "deltaSeq": resp.delta_seq, "nodes": resp.nodes}

    def close(self) -> None:
        self._channel.close()
