"""Device-resident cluster mirror with generation-keyed delta uploads.

The TPU analog of the incremental snapshot (cache.go:198): the host tracks the
last-uploaded generation per node slot; ``sync`` encodes only dirty NodeInfos
into row blocks and applies them with one batched scatter per field —
the `dynamic_update_slice` pipeline of SURVEY.md §7 step 3.

Capacity growth: encoders raise CapacityError when a vocab/axis overflows; the
caller rebuilds DeviceState with grown Capacities and resyncs from scratch
(recompilation policy: double the offending axis — bucketed static shapes).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.snapshot import Snapshot
from ..framework.types import NodeInfo
from ..ops.encode import CapacityError, ClusterEncoder
from ..ops.schema import Capacities, INT_NONE, NodeTensors

_ROW_FIELDS = (
    ("valid", bool), ("unschedulable", bool),
    ("allocatable", np.int32), ("requested", np.int32), ("nonzero_requested", np.int32),
    ("label_val", np.int32), ("label_num", np.int32),
    ("taint_key", np.int32), ("taint_val", np.int32), ("taint_effect", np.int32),
    ("port_bits", np.uint32), ("image_bits", np.uint32), ("class_req", np.int32),
    ("name_hash", np.uint32), ("topo_sp", np.int32), ("topo_pos", np.int32),
)


def _apply_rows(nt: NodeTensors, slots: jax.Array, updates: dict,
                image_sizes: jax.Array, image_num_nodes: jax.Array) -> NodeTensors:
    """One fused scatter of all dirty rows into the node tensors, jitted.
    Slot counts are bucketed by the caller so this compiles once per bucket,
    not once per distinct dirty-row count (no donation: image_sizes may alias
    a field of nt when the image vocab is unchanged)."""
    new_fields = {f: getattr(nt, f).at[slots].set(updates[f]) for f in updates}
    new_fields["image_sizes"] = image_sizes
    new_fields["image_num_nodes"] = image_num_nodes
    new_fields["class_prio"] = nt.class_prio
    return NodeTensors(**new_fields)


_apply_rows_jit = jax.jit(_apply_rows)


def _bucket(n: int, floor: int = 8) -> int:
    """Next power of two ≥ n (≥ floor) — the static-shape recompile guard."""
    b = floor
    while b < n:
        b *= 2
    return b


class DeviceState:
    def __init__(self, caps: Capacities, ns_labels_fn=None):
        from .sig_table import SigTable

        self.caps = caps
        self.encoder = ClusterEncoder(caps)
        self.sig_table = SigTable(self.encoder, ns_labels_fn)
        self.nt = self._empty_tensors()
        self._n_prio = len(self.encoder.prio_vocab)  # uploaded class_prio size
        self._tc = None                           # cached device TopoCounts
        self._tc_version = -1
        self._uploaded_gen: Dict[str, int] = {}   # node name -> generation on device
        self._image_counts: Dict[str, int] = {}   # image -> num nodes (host truth)
        self._image_sizes: Dict[str, int] = {}
        self._node_images: Dict[str, frozenset] = {}
        self.syncs = 0
        self.rows_uploaded = 0
        self.rows_elided = 0
        self.nodes_removed = 0  # removal-sweep tombstones (elastic churn)
        # transfer telemetry: bytes scattered device-ward by the last /
        # all sync calls (the padded row-block size — what actually rides
        # the relay), read by backend/telemetry.py and /debug
        self.last_upload_bytes = 0
        self.upload_bytes = 0
        # host-side mirror of the device row content: lets sync skip rows
        # whose re-encoded content already matches the device (in particular
        # rows whose only change was an adopted batch commit). Initialized to
        # the empty-row encoding, matching _empty_tensors (label_num is
        # INT_NONE-filled, not zero).
        empty_row = self.encoder.encode_node_row(NodeInfo())
        self._mirror: Dict[str, np.ndarray] = {
            field: np.broadcast_to(
                np.asarray(empty_row[field], dtype), (caps.nodes,) + np.shape(empty_row[field])
            ).copy()
            for field, dtype in _ROW_FIELDS
        }
        # Node OBJECT identity at the last mirror write per node name: while
        # unchanged, the row's static fields (labels/taints/allocatable/
        # images) cannot differ from the mirror, so reconcile only needs to
        # compare the pod-commit-dynamic fields
        self._mirror_node: Dict[str, object] = {}
        # --- device-attribute table (resource.k8s.io DRA) -----------------
        # [nodes, A] kind/value cells synced from node-published device
        # slices (NodeStatus.device_attributes): kind 0 = absent, 1 = int,
        # 2 = interned string id. Kept OUTSIDE NodeTensors on purpose —
        # attributes are static per node object (no batch commit ever
        # touches them), so they need none of the mirror/adoption machinery;
        # the claim-feasibility kernel (backend/batch.py) reads them
        # directly. The attribute-key axis grows by doubling (bucketed
        # static shapes, same policy as Capacities).
        self.attr_slots: Dict[str, int] = {}   # attribute key -> column
        self.attr_val_ids: Dict[str, int] = {} # string value vocab (ids from 1)
        # refcounted release for the attribute-value vocab (the label/taint
        # vocab treatment from the elastic PR, ROADMAP item 5 follow-up):
        # per-value publishing-node counts; an id freed at refcount zero
        # joins the free-list and is recycled before the counter grows, so
        # node churn with fresh attribute values cannot grow the vocab
        # monotonically. Selector operands interned without a publishing
        # node stay pinned (bounded by distinct configured operand values).
        self._attr_val_refs: Dict[str, int] = {}
        self._attr_val_free: List[int] = []
        self._attr_val_next = 1
        self._node_attr_values: Dict[str, frozenset] = {}
        self._attr_cols = 8
        self._attr_kind_m = np.zeros((caps.nodes, self._attr_cols), np.int32)
        self._attr_val_m = np.zeros((caps.nodes, self._attr_cols), np.int32)
        # jnp.array (copying), never asarray: the host mirror keeps mutating
        # and a zero-copy alias would silently corrupt the device view
        self.attr_kind = jnp.array(self._attr_kind_m)
        self.attr_val = jnp.array(self._attr_val_m)
        self._node_attrs: Dict[str, dict] = {}  # name -> last-synced mapping
        # --- per-namespace quota screen tensors (ops/quota.py) -----------
        # [NS, Q] usage/limit pair the batch program's over-quota screen
        # judges winners against: synced from the host quota ledger before
        # dispatch (content-diffed — an unchanged table re-uploads nothing)
        # and carried to remote devices by the delta channel's quotaTable
        # payload. Kept OUTSIDE NodeTensors like the attribute table: the
        # namespace axis is its own bucketed shape (grown by doubling) and
        # no batch commit mutates it device-side — the evolving copy lives
        # only inside the screen's scan carry.
        from ..ops.quota import QUOTA_DIMS, QUOTA_NO_LIMIT

        self.nsq_slots: Dict[str, int] = {}   # namespace -> tensor row
        self._nsq_rows = 8
        self._nsq_used_m = np.zeros((self._nsq_rows, QUOTA_DIMS), np.int32)
        self._nsq_limit_m = np.full((self._nsq_rows, QUOTA_DIMS),
                                    QUOTA_NO_LIMIT, np.int32)
        # jnp.array (copying) for the same aliasing reason as attr_kind
        self.nsq_used = jnp.array(self._nsq_used_m)
        self.nsq_limit = jnp.array(self._nsq_limit_m)
        self.nsq_uploads = 0  # content-diff re-uploads (telemetry/debug)
        # O(changes) reconcile/has_dirty: names this device previously left
        # dirty, and the snapshot structure version it last fully walked.
        # While the structure version is unchanged, only changed_names ∪
        # _recon_pending can possibly be gen-stale — the full-N walk is
        # reserved for membership/zone changes (snapshot.py changed_names).
        self._recon_pending: set = set()
        self._seen_struct: int = -1

    @property
    def tc(self):
        """Device TopoCounts, re-uploaded only when the host truth changed."""
        if self._tc is None or self._tc_version != self.sig_table.version:
            self._tc = self.sig_table.topo_counts()
            self._tc_version = self.sig_table.version
        return self._tc

    @property
    def topo_enabled(self) -> bool:
        return self.sig_table.n_sigs > 1 or self.sig_table.n_terms > 1

    def _empty_tensors(self) -> NodeTensors:
        c = self.caps
        z = np.zeros
        return NodeTensors(
            valid=jnp.asarray(z(c.nodes, bool)),
            unschedulable=jnp.asarray(z(c.nodes, bool)),
            allocatable=jnp.asarray(z((c.nodes, c.resources), np.int32)),
            requested=jnp.asarray(z((c.nodes, c.resources), np.int32)),
            nonzero_requested=jnp.asarray(z((c.nodes, c.resources), np.int32)),
            label_val=jnp.asarray(z((c.nodes, c.label_keys), np.int32)),
            label_num=jnp.asarray(np.full((c.nodes, c.label_keys), INT_NONE, np.int32)),
            taint_key=jnp.asarray(z((c.nodes, c.taints), np.int32)),
            taint_val=jnp.asarray(z((c.nodes, c.taints), np.int32)),
            taint_effect=jnp.asarray(z((c.nodes, c.taints), np.int32)),
            port_bits=jnp.asarray(z((c.nodes, c.port_words), np.uint32)),
            image_bits=jnp.asarray(z((c.nodes, c.image_words), np.uint32)),
            image_sizes=jnp.asarray(z(c.images, np.int32)),
            image_num_nodes=jnp.asarray(z(c.images, np.int32)),
            class_req=jnp.asarray(z((c.nodes, c.prio_classes, c.resources), np.int32)),
            class_prio=jnp.asarray(self.encoder.class_prio_array()),
            name_hash=jnp.asarray(z(c.nodes, np.uint32)),
            topo_sp=jnp.asarray(np.full(c.nodes, -1, np.int32)),
            topo_pos=jnp.asarray(np.full(c.nodes, -1, np.int32)),
        )

    # ------------------------------------------------------- device attributes

    def attr_slot(self, key: str) -> int:
        """Column for an attribute key, registering (and growing the axis by
        doubling) on first sight. Selector encoding registers keys too, so a
        selector on a never-published key gets a real, all-absent column."""
        slot = self.attr_slots.get(key)
        if slot is None:
            slot = len(self.attr_slots)
            self.attr_slots[key] = slot
            while slot >= self._attr_cols:
                self._grow_attr_cols()
        return slot

    def _grow_attr_cols(self) -> None:
        cols = self._attr_cols * 2
        pad = ((0, 0), (0, cols - self._attr_cols))
        self._attr_kind_m = np.pad(self._attr_kind_m, pad)
        self._attr_val_m = np.pad(self._attr_val_m, pad)
        self._attr_cols = cols
        self.attr_kind = jnp.array(self._attr_kind_m)
        self.attr_val = jnp.array(self._attr_val_m)

    def attr_value_id(self, value: str) -> int:
        """Interned id for a string attribute value (shared by node rows and
        selector operands — string equality becomes id equality). Freed ids
        (refcount-zero releases) are recycled before the counter grows."""
        vid = self.attr_val_ids.get(value)
        if vid is None:
            if self._attr_val_free:
                vid = self._attr_val_free.pop()
            else:
                vid = self._attr_val_next
                self._attr_val_next += 1
            self.attr_val_ids[value] = vid
        return vid

    def _retain_attr_values(self, name: str, attrs: dict) -> None:
        """Refcount the STRING attribute values ``name`` publishes; a value
        no node publishes anymore frees its vocab id to the free-list.
        Rows re-encode per sync and selector rows rebuild per batch, so a
        recycled id can never be read through a stale compiled artifact."""
        from ..api import dra as dra_api

        new = set()
        for raw in attrs.values():
            kind, val = dra_api.attr_kind_val(raw)
            if kind == dra_api.KIND_STR:
                new.add(val)
        new = frozenset(new)
        old = self._node_attr_values.get(name, frozenset())
        if new == old:
            return
        for v in new - old:
            self._attr_val_refs[v] = self._attr_val_refs.get(v, 0) + 1
        for v in old - new:
            left = self._attr_val_refs.get(v, 0) - 1
            if left > 0:
                self._attr_val_refs[v] = left
                continue
            self._attr_val_refs.pop(v, None)
            vid = self.attr_val_ids.pop(v, None)
            if vid is not None:
                self._attr_val_free.append(vid)
        if new:
            self._node_attr_values[name] = new
        else:
            self._node_attr_values.pop(name, None)

    def _track_attrs(self, name: str, ni: Optional[NodeInfo], slot: int,
                     pending: Dict[int, dict]) -> None:
        """Record a dirty node's published attribute map for upload (called
        from sync's dirty walk — attribute changes always ride a node-object
        change, so the generation probe covers them)."""
        node = ni.node if ni is not None else None
        attrs = (dict(getattr(node.status, "device_attributes", None) or {})
                 if node is not None else {})
        if self._node_attrs.get(name, {}) == attrs:
            return
        # refcounted value retention BEFORE the row encodes: a value whose
        # last publisher just left frees its id here, so the encode below
        # can already recycle it for this sync's newcomers
        self._retain_attr_values(name, attrs)
        if attrs:
            self._node_attrs[name] = attrs
        else:
            self._node_attrs.pop(name, None)
        for key in attrs:
            self.attr_slot(key)  # register first: rows encode after growth
        pending[slot] = attrs

    def _upload_attrs(self, pending: Dict[int, dict]) -> None:
        if not pending:
            return
        from ..api import dra as dra_api

        for slot, attrs in pending.items():
            krow = np.zeros(self._attr_cols, np.int32)
            vrow = np.zeros(self._attr_cols, np.int32)
            for key, raw in attrs.items():
                kind, val = dra_api.attr_kind_val(raw)
                if kind == dra_api.KIND_ABSENT:
                    continue
                col = self.attr_slot(key)
                krow[col] = kind
                vrow[col] = val if kind == dra_api.KIND_INT else self.attr_value_id(val)
            self._attr_kind_m[slot] = krow
            self._attr_val_m[slot] = vrow
        # full re-upload, not a scatter: attribute maps change only with
        # node-object churn (rare), and [N, A] int32 is small next to the
        # row tensors — not worth a third scatter program
        self.attr_kind = jnp.array(self._attr_kind_m)
        self.attr_val = jnp.array(self._attr_val_m)

    # ------------------------------------------------- namespace quota table

    def _grow_nsq_rows(self) -> None:
        from ..ops.quota import QUOTA_NO_LIMIT

        rows = self._nsq_rows * 2
        grow = rows - self._nsq_rows
        self._nsq_used_m = np.pad(self._nsq_used_m, ((0, grow), (0, 0)))
        self._nsq_limit_m = np.concatenate([
            self._nsq_limit_m,
            np.full((grow, self._nsq_limit_m.shape[1]), QUOTA_NO_LIMIT,
                    np.int32)])
        self._nsq_rows = rows

    def set_ns_quota(self, table: Dict[str, Tuple]) -> bool:
        """Sync the namespace-quota tensor pair from a host ledger view
        (ns -> (used row, limit row) in ops/quota.QUOTA_DIM_ORDER ints).
        Content-diffed against the host mirror, so a steady-state table
        uploads nothing; returns whether a re-upload happened. ``table`` is
        the COMPLETE desired state: a registered namespace absent from it
        (quota deleted) resets to never-flags rows — a stale screening row
        for an unquota'd namespace would otherwise reject-and-requeue the
        same pod forever (the gate re-admits what the screen re-flags)."""
        from ..ops.quota import QUOTA_NO_LIMIT

        cap = int(QUOTA_NO_LIMIT)
        dirty = False
        for ns in self.nsq_slots:
            if ns not in table:
                slot = self.nsq_slots[ns]
                if (self._nsq_used_m[slot].any()
                        or (self._nsq_limit_m[slot] != cap).any()):
                    self._nsq_used_m[slot] = 0
                    self._nsq_limit_m[slot] = cap
                    dirty = True
        for ns, (used_row, limit_row) in table.items():
            slot = self.nsq_slots.get(ns)
            if slot is None:
                slot = len(self.nsq_slots)
                self.nsq_slots[ns] = slot
                while slot >= self._nsq_rows:
                    self._grow_nsq_rows()
                dirty = True
            u = np.clip(np.asarray(used_row, np.int64), 0, cap).astype(np.int32)
            lim = np.clip(np.asarray(limit_row, np.int64), 0, cap).astype(np.int32)
            if not np.array_equal(self._nsq_used_m[slot], u):
                self._nsq_used_m[slot] = u
                dirty = True
            if not np.array_equal(self._nsq_limit_m[slot], lim):
                self._nsq_limit_m[slot] = lim
                dirty = True
        if dirty:
            # full re-upload, not a scatter: [NS, Q] int32 is tiny next to
            # the row tensors (the attribute-table treatment)
            self.nsq_used = jnp.array(self._nsq_used_m)
            self.nsq_limit = jnp.array(self._nsq_limit_m)
            self.nsq_uploads += 1
            self.upload_bytes += (self._nsq_used_m.nbytes
                                  + self._nsq_limit_m.nbytes)
        return dirty

    # ------------------------------------------------------------------ sync

    def _refresh_class_prio(self) -> None:
        """Upload the priority-class vocab whenever it grew — independent of
        row changes (class_req content usually reaches the device via batch
        ADOPTION, so row uploads may be elided forever while the vocab
        array would stay stale at INT_MAX = nothing-evictable)."""
        if self._n_prio != len(self.encoder.prio_vocab):
            import dataclasses as _dc

            self._n_prio = len(self.encoder.prio_vocab)
            self.nt = _dc.replace(
                self.nt, class_prio=jnp.asarray(self.encoder.class_prio_array()))

    def sync(self, snapshot: Snapshot) -> int:
        """Upload rows for nodes whose generation advanced; returns number of
        rows uploaded. Raises CapacityError when the cluster outgrows caps."""
        self._refresh_class_prio()
        self.last_upload_bytes = 0
        dirty: List[Tuple[int, NodeInfo]] = []
        images_changed = False
        attr_pending: Dict[int, dict] = {}
        from . import telemetry

        # removed nodes FIRST: tombstone their rows (zeroed on device, slot
        # to the free-list, vocab retentions dropped), so a node added in
        # the SAME sync reuses the freed slot immediately instead of
        # growing the axis for one generation. Membership comes from the
        # ENCODER's slot map, not _uploaded_gen — commit-repair paths pop a
        # node's gen to force re-upload, and a node deleted in that window
        # would otherwise leak its slot (and stale mirror row) forever.
        current = snapshot.node_info_map
        removed = [n for n in self.encoder.node_slots if n not in current]
        for name in removed:
            self._uploaded_gen.pop(name, None)
            self._mirror_node.pop(name, None)
            slot = self.encoder.release_node_slot(name)
            self.nodes_removed += 1
            telemetry.event("node_remove", node=name,
                            slot=slot if slot is not None else -1)
            if slot is not None:
                dirty.append((slot, NodeInfo()))  # empty row: valid=False
                self.sig_table.recount_node(slot, None)
                self._track_attrs(name, None, slot, attr_pending)
            else:
                self._node_attrs.pop(name, None)
            images_changed |= self._track_images(name, None)
        for name, ni in current.items():
            if self._uploaded_gen.get(name) == ni.generation:
                continue
            reuses0 = self.encoder.slot_reuses
            slot = self.encoder.node_slot(name)
            if self.encoder.slot_reuses != reuses0:
                # a tombstoned row was handed to this node: the free-list
                # kept row capacity bounded instead of growing the axis
                telemetry.event("slot_reclaim", node=name, slot=slot)
            dirty.append((slot, ni))
            self._uploaded_gen[name] = ni.generation
            images_changed |= self._track_images(name, ni)
            self._track_attrs(name, ni, slot, attr_pending)
            if ni.node is not self._mirror_node.get(name):
                # labels/taints can only change with the Node OBJECT; rows
                # dirtied by commits alone skip the retention re-diff (the
                # same identity gate the static-row cache rides)
                self.encoder.retain_node_values(name, ni.node)
            self.sig_table.recount_node(slot, ni)
        if removed and dirty:
            # a slot tombstoned AND re-assigned within this sync appears
            # twice in the worklist; the scatter must see only the LAST
            # write per slot (duplicate indices in .at[].set are undefined)
            dirty = list({slot: (slot, ni) for slot, ni in dirty}.values())
        # device-attribute table upload happens even when every row upload
        # below gets content-elided (attrs live outside the row mirror)
        self._upload_attrs(attr_pending)

        # the full walk leaves every gen aligned: reset the O(changes) probes.
        # Duck-typed snapshots (wire service, test shims) may lack the
        # bookkeeping fields; they always take the full-walk paths.
        self._seen_struct = getattr(snapshot, "structure_version", -1)
        self._recon_pending.clear()
        getattr(snapshot, "changed_names", set()).clear()

        if not dirty:
            return 0
        # content-diff against the mirror: a row whose re-encoded content
        # already matches the device (e.g. its only change was an adopted
        # batch commit) needs no upload
        changed: List[Tuple[int, dict]] = []
        for slot, ni in dirty:
            row = self.encoder.encode_node_row(ni)
            if ni.node is not None:
                self._mirror_node[ni.node.meta.name] = ni.node
            if all(
                np.array_equal(np.asarray(row[f], dtype), self._mirror[f][slot])
                for f, dtype in _ROW_FIELDS
            ):
                self.rows_elided += 1
                continue
            for f, dtype in _ROW_FIELDS:
                self._mirror[f][slot] = np.asarray(row[f], dtype)
            changed.append((slot, row))
        if not changed and not images_changed:
            return 0
        if not changed:
            # vocab-level image arrays changed but no rows did: reuse slot 0
            changed = [(0, {f: self._mirror[f][0] for f, _ in _ROW_FIELDS})]
        # bucket-pad the row count to a power of two so the fused scatter
        # compiles once per bucket; padding repeats row 0 (idempotent set)
        n = len(changed)
        b = _bucket(n)
        slots = np.empty(b, np.int32)
        slots[:n] = [s for s, _ in changed]
        slots[n:] = slots[0]
        rows = [r for _, r in changed]
        updates = {}
        for field, dtype in _ROW_FIELDS:
            stacked = np.empty((b,) + np.shape(rows[0][field]), dtype)
            stacked[:n] = np.stack([r[field] for r in rows]).astype(dtype)
            stacked[n:] = stacked[0]
            updates[field] = stacked
        nt = self.nt
        if images_changed:
            sizes = np.zeros(self.caps.images, np.int32)
            counts = np.zeros(self.caps.images, np.int32)
            for img, cnt in self._image_counts.items():
                iid = self.encoder.image_id(img)
                counts[iid] = cnt
                sizes[iid] = min(self._image_sizes.get(img, 0), 2**31 - 1)
            image_sizes = jnp.asarray(sizes)
            image_num_nodes = jnp.asarray(counts)
        else:
            image_sizes = nt.image_sizes
            image_num_nodes = nt.image_num_nodes
        with telemetry.dispatch("apply_rows", bucket=str(b)):
            dev_slots = jnp.asarray(slots)
            self.nt = _apply_rows_jit(nt, dev_slots, updates,
                                      image_sizes, image_num_nodes)
        telemetry.cost_probe("apply_rows", str(b), _apply_rows_jit,
                             (nt, dev_slots, updates, image_sizes,
                              image_num_nodes))
        self.syncs += 1
        self.rows_uploaded += n
        nbytes = sum(arr.nbytes for arr in updates.values()) + slots.nbytes
        self.last_upload_bytes = int(nbytes)
        self.upload_bytes += int(nbytes)
        telemetry.transfer("upload", nbytes)
        return n

    def reconcile(self, snapshot: Snapshot) -> int:
        """Elide-only sync for the pipelined steady state: refresh
        ``_uploaded_gen`` for dirty rows whose re-encoded content already
        equals the mirror — i.e. rows whose only change was an adopted batch
        commit. Rows that would need a REAL upload are left dirty on
        purpose: at reconcile time the device may already carry the NEXT
        dispatched batch's adopted state, and scattering host rows into it
        would erase in-flight commits (device/host divergence the content
        diff then elides forever). Leaving them dirty makes the next
        ``has_dirty`` probe break the carry chain, and the safe drain+sync
        path repairs everything. Returns the number of rows left dirty."""
        self._refresh_class_prio()
        left = 0
        mirror = self._mirror
        req_m, nz_m = mirror["requested"], mirror["nonzero_requested"]
        ports_m, creq_m = mirror["port_bits"], mirror["class_req"]
        if getattr(snapshot, "structure_version", None) == self._seen_struct:
            # membership/zones unchanged since the last full walk: only the
            # names update_snapshot re-cloned (plus rows we previously left
            # dirty) can be gen-stale — O(changes), not O(nodes)
            names = snapshot.changed_names | self._recon_pending
            items = [(n, snapshot.node_info_map[n]) for n in names
                     if n in snapshot.node_info_map]
            check_removals = False
        else:
            items = list(snapshot.node_info_map.items())
            check_removals = True
        pending = set()
        for name, ni in items:
            if self._uploaded_gen.get(name) == ni.generation:
                continue
            if name not in self._uploaded_gen:
                left += 1  # new node: needs a real upload
                pending.add(name)
                continue
            if ni.node is not self._mirror_node.get(name):
                left += 1  # node OBJECT replaced: static fields may differ
                pending.add(name)
                continue
            if self._node_images.get(name, frozenset()) != frozenset(ni.image_states):
                left += 1  # image vocab change: needs a real upload
                pending.add(name)
                continue
            slot = self.encoder.node_slots.get(name)
            if slot is None:
                left += 1
                pending.add(name)
                continue
            try:
                # static fields are pinned by the identity check above; only
                # the pod-commit-dynamic fields can have moved
                row = self.encoder.encode_dynamic_fields(ni)
            except CapacityError:
                left += 1
                pending.add(name)
                continue
            if (np.array_equal(row["requested"], req_m[slot])
                    and np.array_equal(row["nonzero_requested"], nz_m[slot])
                    and np.array_equal(row["port_bits"], ports_m[slot])
                    and np.array_equal(row["class_req"], creq_m[slot])):
                self._uploaded_gen[name] = ni.generation
                self.rows_elided += 1
                # per-row recount is the reconcile constant-factor hot spot
                # (O(sigs × pods-on-node) python per elided row); with no
                # sigs/terms registered the counts are all zero and only
                # the _slot_pods bookkeeping matters
                st = self.sig_table
                if st.n_sigs > 1 or st.n_terms > 1:
                    st.recount_node(slot, ni)
                else:
                    st.track_slot_pods(slot, ni)
            else:
                left += 1
                pending.add(name)
        if check_removals:
            removed = [n for n in self.encoder.node_slots
                       if n not in snapshot.node_info_map]
            left += len(removed)
            pending.update(removed)
            self._seen_struct = getattr(snapshot, "structure_version", -1)
        self._recon_pending = pending
        getattr(snapshot, "changed_names", set()).clear()
        return left

    def has_dirty(self, snapshot: Snapshot) -> bool:
        """Cheap generation-only probe: would sync() find any dirty or
        removed node? In the async pipeline, any dirtiness at dispatch time
        is by construction an EXTERNAL change (the in-flight batch's commits
        are not in the cache yet), which breaks the device-carry chain.
        O(changes): while the snapshot's structure version is the one this
        device last fully walked, only changed/pending names can be stale;
        a structure change conservatively reports dirty (the drain+sync it
        triggers realigns the version)."""
        if getattr(snapshot, "structure_version", None) != self._seen_struct:
            return True
        for name in snapshot.changed_names | self._recon_pending:
            ni = snapshot.node_info_map.get(name)
            if ni is None or self._uploaded_gen.get(name) != ni.generation:
                return True
        return False

    def adopt_device(self, result) -> None:
        """Adopt the batch program's evolved dynamic state as the new device
        truth. The arrays may still be unmaterialized futures — this never
        blocks, which is what lets the pipeline dispatch the next batch while
        the host commits this one."""
        import dataclasses as _dc

        if result.final_requested is None:
            return
        updates = dict(
            requested=result.final_requested,
            nonzero_requested=result.final_nonzero,
            port_bits=result.final_ports,
        )
        if result.final_class_req is not None:
            updates["class_req"] = result.final_class_req
        self.nt = _dc.replace(self.nt, **updates)

    def adopt_commits(self, result, host_pb: dict, node_idx: np.ndarray) -> None:
        """Advance the host mirror by the batch's per-slot adds, so the next
        sync's content diff elides every row whose only change was this
        batch's commits (the delta-upload saving of returning the carry).

        ``host_pb`` is the encoder's host-side copy of the pod batch
        (ClusterEncoder.last_host_pb) — reading the device PodBatch back
        would cost a relay round-trip per array. Runs at COMMIT time (the
        mirror only matters before the next sync, which a drain precedes);
        adopt_device runs at dispatch time and never blocks."""
        if result.final_requested is None:
            return
        req = host_pb["req"]
        nz = host_pb["nonzero_req"]
        port_ids = host_pb["port_ids"]
        # mirror only what the device evolved: the pallas path returns no
        # final_class_req, so the device class table is refreshed by row
        # upload instead of adoption there
        prio_class = host_pb.get("prio_class") if result.final_class_req is not None else None
        for i, slot in enumerate(node_idx):
            if slot < 0:
                continue
            self._mirror["requested"][slot] += req[i]
            self._mirror["nonzero_requested"][slot] += nz[i]
            if prio_class is not None:
                self._mirror["class_req"][slot, prio_class[i]] += req[i]
            for pid in port_ids[i]:
                if pid > 0:
                    self._mirror["port_bits"][slot, pid >> 5] |= np.uint32(1) << np.uint32(pid & 31)

    def _track_images(self, name: str, ni: Optional[NodeInfo]) -> bool:
        """Maintain global image num-node counts (first-seen size wins,
        mirroring cache.addNodeImageStates). Returns True if vocab changed."""
        old = self._node_images.get(name, frozenset())
        new = frozenset(ni.image_states) if ni is not None else frozenset()
        if old == new:
            return False
        for img in new - old:
            self._image_counts[img] = self._image_counts.get(img, 0) + 1
            if img not in self._image_sizes and ni is not None:
                self._image_sizes[img] = ni.image_states[img]
        for img in old - new:
            c = self._image_counts.get(img, 0) - 1
            if c <= 0:
                self._image_counts.pop(img, None)
                self._image_sizes.pop(img, None)
                # no node reports it anymore: free the vocab id so image
                # churn cannot grow the image axis monotonically
                self.encoder.release_image(img)
            else:
                self._image_counts[img] = c
        if new:
            self._node_images[name] = new
        else:
            self._node_images.pop(name, None)
        return True

    def slot_to_name(self) -> Dict[int, str]:
        """LIVE reverse map (maintained by the encoder) — rebuilding a
        5k-entry dict per commit was a fixed ~2ms/batch. Callers read only;
        anyone needing a stable copy must dict() it."""
        return self.encoder.slot_names


def caps_for_cluster(n_nodes: int, batch: int = 128) -> Capacities:
    """Pick static capacities for a cluster size (node-count buckets 1k/5k/...;
    hostname value vocab must cover every node)."""
    from ..ops.schema import round_node_capacity

    nodes = round_node_capacity(n_nodes)
    value_words = max(32, (nodes + 2 + 31) // 32)  # hostname vocab ≥ node count
    # synthetic torus fallback assigns sp = slot // sp_slots: the superpod
    # axis must cover every slot or the first sync of a large cluster spins
    # through CapacityError growth
    superpods = max(16, (nodes + 15) // 16)
    return Capacities(nodes=nodes, pods=batch, value_words=value_words,
                      superpods=superpods)
