"""The batched device service seam (SURVEY §5.8 hop 6).

The reference's only out-of-process scheduling extension is the per-pod JSON
extender webhook (extender.go:42,247) — one HTTP POST per pod per extender,
which is exactly its performance failure. This service batches and adds
state: the control plane streams generation-keyed node deltas
(``ApplyDeltas``) and submits whole pod micro-batches (``ScheduleBatch``);
the device side keeps the encoded mirror across calls, so steady-state
requests carry only dirty rows and the pod batch.

Three pieces:
  * ``DeviceService`` — transport-agnostic server core owning a DeviceState
    and the compiled batch program; the hot path mirrors TPUScheduler's
    device half (delta sync, capacity growth, adopt-on-dispatch).
  * ``serve``/``DeviceServiceHTTP`` — stdlib HTTP/JSON binding on localhost
    (the in-process path stays the fast mode; this seam exists to measure
    and bound the serialization/transport cost the reference pays at
    QPS-5000, scheduler_perf util.go:86-90).
  * ``WireScheduler`` — a Scheduler whose filter/score middle goes over the
    wire; queue/cache/assume/bind/failure handling stay the same host
    machinery (the north-star seam: the control plane does not know whether
    the backend is in-process or remote).

Wire envelope: {"apiVersion": "ktpu/v1", ...}; objects use api/codec.py.
"""

from __future__ import annotations

import http.client
import itertools
import json
import os
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace
from typing import Dict, List, Optional

import numpy as np

from ..api.codec import from_wire, to_wire
from ..api.types import Node, Pod
from ..framework.types import Diagnosis, NodeInfo, QueuedPodInfo
from ..framework.interface import CycleState, Status
from ..ops.encode import CapacityError
from ..scheduler.scheduler import Scheduler
from ..utils import tracing
from .batch import build_schedule_batch_fn
from .circuit import CircuitBreaker, OPEN, STATE_VALUES
from .device_state import DeviceState, caps_for_cluster
from .errors import (
    DeviceServiceError,
    PermanentDeviceError,
    RetryPolicy,
    StaleEpochError,
    TransientDeviceError,
    raise_injected_fault,
)
from .tpu_scheduler import _ATTRIBUTION_ORDER, TPUScheduler

API_VERSION = "ktpu/v1"

# process-epoch minting: unique per DeviceService INSTANCE (a restarted
# sidecar is a new instance holding a fresh empty DeviceState; the epoch is
# how the client tells a restart from a healthy peer — etcd's cluster-id /
# member-id check on reconnect plays the same role)
_EPOCH_IDS = itertools.count(1)


def _new_epoch() -> str:
    return f"{os.getpid():x}-{next(_EPOCH_IDS)}"


class DeviceService:
    """Server core: node mirror + device state + one compiled batch program."""

    def __init__(self, batch_size: int = 512,
                 percentage_of_nodes_to_score: int = 0):
        self.batch_size = batch_size
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        # state-resync protocol: the epoch names THIS process incarnation;
        # delta_seq counts applied delta generations within it. A client
        # whose expectEpoch disagrees gets a stale-state error instead of
        # silently having its deltas applied against the wrong (empty) base.
        self.epoch = _new_epoch()
        self.delta_seq = 0
        # idempotency cache: (batchId, response) of the last committed
        # batch. A transport retry after a LOST RESPONSE (timeout/reset
        # once the server already committed) replays the cached response
        # instead of double-committing the pods against capacity their
        # first copies consumed. One entry suffices: the client is
        # sequential and only ever retries its most recent batch.
        self._last_batch: Optional[tuple] = None
        self.batch_replays = 0
        self.infos: Dict[str, NodeInfo] = {}
        # duck-typed Snapshot: the wire service mirrors nodes wholesale per
        # delta, so every sync is a "structure changed" full walk — the
        # changed_names/structure_version fields exist only to satisfy
        # DeviceState's O(changes) bookkeeping (a fresh version each sync
        # forces the full path, which is correct here)
        self.snap = SimpleNamespace(node_info_map=self.infos,
                                    changed_names=set(), structure_version=0)
        self.ns_labels: Dict[str, Dict[str, str]] = {}
        self.device: Optional[DeviceState] = None
        self.schedule_batch_fn = build_schedule_batch_fn()
        self.batch_counter = 0
        self._start_carry = None  # adaptive-sampling rotation (device scalar)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- epoch

    def check_epoch(self, req: dict) -> None:
        """Refuse a request stamped with another incarnation's epoch: the
        client's incremental deltas assume a base THIS process never had.
        A full resync (``full: true``) establishes a new base, so it is
        exempt — it is exactly the recovery move the error demands."""
        expect = req.get("expectEpoch")
        if expect and expect != self.epoch and not req.get("full"):
            raise StaleEpochError(self.epoch)

    def _stamp(self, out: dict) -> dict:
        out["epoch"] = self.epoch
        out["deltaSeq"] = self.delta_seq
        return out

    # ------------------------------------------------------------- deltas

    def apply_deltas(self, req: dict) -> dict:
        self.check_epoch(req)
        # server half of W3C-traceparent propagation: the delta sync parents
        # under the client's scheduling.cycle span (no-op, one global read,
        # when tracing is disabled)
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.apply_deltas",
                                      nodes=len(req.get("nodes", ()))):
            return self._apply_deltas_traced(req)

    def _apply_deltas_traced(self, req: dict) -> dict:
        with self._lock:
            if req.get("full"):
                self.infos.clear()
                self.ns_labels.clear()
                self.device = None
            for e in req.get("nodes", ()):
                node = from_wire(Node, e["node"])
                ni = NodeInfo(node)
                for pw in e.get("pods", ()):
                    ni.add_pod(from_wire(Pod, pw))
                ni.generation = e.get("gen", ni.generation)
                self.infos[node.meta.name] = ni
            for name in req.get("removed", ()):
                self.infos.pop(name, None)
            # namespace labels ride along so namespaceSelector terms match
            # identically to the in-process path (sig_table ns_labels_fn)
            for ns, labels in (req.get("namespaces") or {}).items():
                self.ns_labels[ns] = dict(labels)
            self._sync()
            self.delta_seq += 1
            return self._stamp({"apiVersion": API_VERSION,
                                "nodes": len(self.infos)})

    def _ensure_device(self) -> None:
        import dataclasses

        n = max(len(self.infos), 1)
        ns_fn = lambda ns: self.ns_labels.get(ns, {})  # noqa: E731
        if self.device is None:
            self.device = DeviceState(caps_for_cluster(n, batch=self.batch_size),
                                      ns_labels_fn=ns_fn)
        elif self.device.caps.nodes < n:
            caps = self.device.caps
            nodes = caps.nodes
            while nodes < n:
                nodes *= 2
            self.device = DeviceState(dataclasses.replace(
                caps, nodes=nodes,
                value_words=max(caps.value_words, (nodes + 2 + 31) // 32)),
                ns_labels_fn=ns_fn)

    def _sync(self) -> None:
        self._ensure_device()
        for _attempt in range(8):
            try:
                with tracing.span("device.sync"):
                    self.device.sync(self.snap)
                return
            except CapacityError as e:
                self._grow(e)
        raise RuntimeError("device capacities refuse to converge")

    def _grow(self, err: CapacityError) -> None:
        import dataclasses

        caps = self.device.caps
        fields = TPUScheduler._GROW_FIELDS.get(err.dimension)
        if fields is None and err.dimension.startswith("value vocab"):
            fields = ("value_words",)
        if fields is None:
            raise RuntimeError(f"unknown capacity dimension {err.dimension!r}") from err
        updates = {}
        for f in fields:
            v = getattr(caps, f)
            while v < err.needed:
                v *= 2
            updates[f] = v
        self.device = DeviceState(
            dataclasses.replace(caps, **updates),
            ns_labels_fn=lambda ns: self.ns_labels.get(ns, {}))

    # --------------------------------------------------------------- health
    def health(self, req: dict) -> dict:
        """Cheap liveness/identity verb: no device work, no epoch check (a
        stale client calling this LEARNS the current epoch — exactly what a
        half-open circuit probe needs instead of pushing a full batch
        through a maybe-dead service)."""
        with self._lock:
            return self._stamp({"apiVersion": API_VERSION,
                                "status": "serving",
                                "nodes": len(self.infos)})

    # ------------------------------------------------------------- schedule

    def schedule_batch(self, req: dict) -> dict:
        self.check_epoch(req)
        batch_id = req.get("batchId")
        with self._lock:
            if (batch_id and self._last_batch is not None
                    and self._last_batch[0] == batch_id):
                self.batch_replays += 1
                return self._last_batch[1]
        pods = [from_wire(Pod, pw) for pw in req.get("pods", ())]
        tie_seeds = req.get("tieSeeds") or None
        # parent the whole server-side batch under the client's
        # scheduling.cycle span (W3C traceparent riding the request dict):
        # one trace then covers scheduler pop → wire → device commit
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.schedule_batch",
                                      batch=len(pods)):
            out = self._schedule_batch_traced(pods, tie_seeds,
                                              req.get("claims"))
        if batch_id:
            with self._lock:
                self._last_batch = (batch_id, out)
        return out

    def _schedule_batch_traced(self, pods: List[Pod], tie_seeds,
                               claims=None) -> dict:
        with self._lock:
            self._ensure_device()
            for _attempt in range(8):
                try:
                    with tracing.span("device.sync"):
                        self.device.sync(self.snap)
                    with tracing.span("device.encode", batch=len(pods)):
                        pb, et = self.device.encoder.encode_pods(
                            pods, tie_seeds=tie_seeds)
                        tb = self.device.sig_table.encode_topo(pods)
                    break
                except CapacityError as e:
                    self._grow(e)
            else:
                raise RuntimeError("device capacities refuse to converge")
            host_pb = self.device.encoder.last_host_pb
            self.batch_counter += 1
            # sampling parity with the in-process batched path: explicit
            # percentage → exact rotating-window emulation; adaptive (0) →
            # full batch on accelerators, reference adaptive sample on CPU
            # (the tpu_scheduler._flush_batch rule)
            from ..scheduler.scheduler import num_feasible_nodes_to_find
            from .tpu_scheduler import _default_full_batch

            n_valid = len(self.infos)
            if self.percentage_of_nodes_to_score:
                k = num_feasible_nodes_to_find(n_valid,
                                               self.percentage_of_nodes_to_score)
            elif _default_full_batch():
                k = n_valid
            else:
                k = num_feasible_nodes_to_find(n_valid, 0)
            if k < n_valid:
                sample_k = np.int32(k)
                sample_start = (self._start_carry if self._start_carry is not None
                                else np.int32(0))
            else:
                sample_k = None
                sample_start = None
            # resource.k8s.io claims: the client ships pre-resolved selector
            # rows (it has the store; this process does not) and the mask
            # builds against THIS device's attribute table — the same
            # claim_feasibility_mask the in-process path dispatches
            dra_mask = None
            if claims:
                from .claim_mask import build_dra_mask, wire_claims_to_entries

                pad_to = len(host_pb["req"])
                dra_mask = build_dra_mask(
                    self.device, wire_claims_to_entries(claims), pad_to)
            with tracing.span("device.dispatch", batch=len(pods)):
                result = self.schedule_batch_fn(
                    pb, et, self.device.nt, self.device.tc, tb,
                    np.int32(self.batch_counter),
                    topo_enabled=self.device.topo_enabled,
                    sample_k=sample_k, sample_start=sample_start,
                    dra_mask=dra_mask)
            if result.final_sample_start is not None:
                self._start_carry = result.final_sample_start
            # adopt exactly like the in-process path: the client will assume
            # these placements; its next delta push re-encodes any row the
            # host view disagrees on and the content diff repairs it
            with tracing.span("device.commit", batch=len(pods)):
                # THE blocking read: the packed result block lands node_idx
                # AND first_fail in one materialization (the per-array reads
                # were one relay round-trip each on the TPU tunnel)
                if result.packed is not None:
                    from .batch import unpack_result_block

                    node_idx, ff = unpack_result_block(
                        result.packed, self.device.caps.nodes)
                else:
                    node_idx = np.asarray(result.node_idx)
                    ff = None
                self.device.adopt_device(result)
                self.device.adopt_commits(result, host_pb, node_idx)
            slot_names = self.device.slot_to_name()
            # device preemption screen for the batch's failures (ROADMAP
            # wire-hardening: hints ride back with unschedulable results so
            # the client's PostFilter skips hopeless candidates)
            screen = best = None
            if any(int(node_idx[i]) < 0 for i in range(len(pods))):
                try:
                    from ..ops.preempt import screen_prefix

                    self.device._refresh_class_prio()
                    pres = screen_prefix(pb, self.device.nt,
                                         result.static_masks,
                                         node_idx[:len(pods)] < 0)
                    screen = np.asarray(pres.screen)
                    best = np.asarray(pres.best)
                except Exception:  # noqa: BLE001 — hints are optional
                    screen = best = None
            results: List[dict] = []
            for i in range(len(pods)):
                idx = int(node_idx[i])
                if idx >= 0 and idx in slot_names:
                    results.append({"nodeName": slot_names[idx]})
                    continue
                if ff is None:  # packless (sharded-core) results only
                    ff = np.asarray(result.first_fail)
                # REAL slots only — padding slots fail the fit check and
                # would pollute the plugin attribution (queue gating)
                plugins = set()
                statuses = {}
                for slot, name in slot_names.items():
                    fid = int(ff[i][slot])
                    if fid > 0:
                        plugins.add(fid)
                        if len(statuses) < 64:  # payload-bounded sample
                            statuses[name] = _ATTRIBUTION_ORDER[fid - 1][0]
                r = {
                    "nodeName": None,
                    "unschedulablePlugins": [
                        _ATTRIBUTION_ORDER[fid - 1][0] for fid in sorted(plugins)],
                    "statuses": statuses,
                }
                if screen is not None:
                    all_cands = [name for slot, name in slot_names.items()
                                 if bool(screen[i][slot])]
                    best_name = (slot_names.get(int(best[i]))
                                 if best is not None and best[i] >= 0 else None)
                    if len(all_cands) <= 1024:
                        # an exact screen only: a truncated candidate list
                        # would wrongly mark the dropped nodes hopeless
                        # (defaultpreemption treats the screen as exact)
                        r["preempt"] = {"candidates": all_cands,
                                        "best": best_name}
                    elif best_name is not None:
                        # too many candidates to ship: the ranked best alone
                        # still helps (preferred-node fast path)
                        r["preempt"] = {"candidates": None, "best": best_name}
                results.append(r)
        return self._stamp({"apiVersion": API_VERSION, "results": results})


# ---------------------------------------------------------------- transport


class ServiceBinding:
    """Mutable service slot behind a running server: the handler dispatches
    through it, so a crash-and-restart fault (or an operator restart) can
    swap in a FRESH DeviceService — new epoch, empty DeviceState — without
    tearing down the listener, exactly like a sidecar process restart
    behind a stable Service IP."""

    def __init__(self, service: DeviceService, fault_plan=None):
        self.service = service
        self.fault_plan = fault_plan
        self.restarts = 0

    def restart(self) -> DeviceService:
        old = self.service
        self.service = DeviceService(
            batch_size=old.batch_size,
            percentage_of_nodes_to_score=old.percentage_of_nodes_to_score)
        self.restarts += 1
        return self.service


_OPS = {"/v1/applyDeltas": "apply_deltas", "/v1/scheduleBatch": "schedule_batch",
        "/v1/health": "health"}


class _Handler(BaseHTTPRequestHandler):
    binding: ServiceBinding = None  # set by serve()

    def log_message(self, *args):  # quiet
        pass

    def _json(self, code: int, out: dict) -> None:
        payload = json.dumps(out).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):  # noqa: N802 — stdlib naming
        op = _OPS.get(self.path)
        if op is None:
            self.send_error(404)
            return
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        plan = self.binding.fault_plan
        fault = plan.next_server(op) if plan is not None else None
        if fault is not None:
            if fault.kind == "crash":
                # the sidecar dies mid-request and supervision restarts it:
                # swap in a fresh service (new epoch, empty state) and sever
                # the connection — the client sees a reset, not a response
                self.binding.restart()
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            self._json(fault.status,
                       {"error": f"injected fault: {fault.kind}"})
            return
        try:
            out = getattr(self.binding.service, op)(body)
        except StaleEpochError as exc:
            # 409: the client must full-resync (distinct from 5xx so the
            # retry loop does not burn its budget re-sending stale deltas)
            self._json(409, {"error": str(exc), "staleEpoch": True,
                             "epoch": exc.epoch})
            return
        except Exception as exc:  # noqa: BLE001 — wire errors must be JSON
            self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        self._json(200, out)


def serve(service: DeviceService, port: int = 0, fault_plan=None):
    """Start the HTTP binding on localhost; returns (server, port). The
    caller owns shutdown (server.shutdown()). ``server.binding`` exposes
    the live service slot (restartable; chaos tests script crashes through
    ``fault_plan``, a testing.faults.FaultPlan)."""
    binding = ServiceBinding(service, fault_plan=fault_plan)
    handler = type("BoundHandler", (_Handler,), {"binding": binding})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    server.binding = binding
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


class WireClient:
    """HTTP/JSON transport with the full fault story: split connect/read
    deadlines (a hung accept and a slow batch are different failures), the
    typed error taxonomy (backend/errors.py), and retry-with-backoff for
    transient failures inside the RetryPolicy's per-call deadline budget.
    ``fault_plan`` intercepts calls before the socket for deterministic
    chaos tests."""

    def __init__(self, endpoint: str, connect_timeout: float = 5.0,
                 read_timeout: float = 60.0, retry: Optional[RetryPolicy] = None,
                 fault_plan=None):
        self.endpoint = endpoint.rstrip("/")
        u = urllib.parse.urlsplit(self.endpoint)
        scheme = u.scheme or "http"
        if scheme not in ("http", "https") or not u.netloc:
            # a scheme-less endpoint ('127.0.0.1:5000', the gRPC form)
            # would silently parse as a PATH and hit port 80 forever —
            # loud error now beats permanent breaker-open later
            raise ValueError(
                f"device-service endpoint must be http(s)://host:port, "
                f"got {endpoint!r}")
        self._conn_cls = (http.client.HTTPSConnection if scheme == "https"
                          else http.client.HTTPConnection)
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if scheme == "https" else 80)
        self._base_path = u.path.rstrip("/")
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self.retry = retry if retry is not None else RetryPolicy()
        self.fault_plan = fault_plan

    def _do_post(self, path: str, data: bytes) -> dict:
        conn = self._conn_cls(self._host, self._port,
                              timeout=self.connect_timeout)
        try:
            try:
                conn.connect()
                # connected: the remaining budget is the READ deadline
                conn.sock.settimeout(self.read_timeout)
                conn.request("POST", self._base_path + path, body=data,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                status = resp.status
                body = resp.read()
            except (ConnectionError, http.client.HTTPException, socket.timeout,
                    TimeoutError, OSError) as e:
                # refused/reset/timeout/torn response: the transient family
                raise TransientDeviceError(
                    f"device service unreachable: {type(e).__name__}: {e}") from e
        finally:
            conn.close()
        try:
            out = json.loads(body or b"{}")
        except ValueError as e:
            # classify by status first: a torn/HTML body on an
            # infrastructure 5xx is still the transient family
            if status in (502, 503, 504):
                raise TransientDeviceError(
                    f"device service {status}: non-JSON body") from e
            raise PermanentDeviceError(f"malformed device response: {e}") from e
        if status == 409 and out.get("staleEpoch"):
            raise StaleEpochError(out.get("epoch", ""), out.get("error", ""))
        if status in (502, 503, 504):
            # infrastructure-flavored 5xx (overload, proxy, restart in
            # progress) MAY clear: give the retry loop a chance before the
            # breaker counts it
            raise TransientDeviceError(
                f"device service {status}: {out.get('error', '')}")
        if status >= 400:
            # includes 500: the handler answers it only for a service-side
            # exception, which is deterministic — re-sending the identical
            # batch re-raises it (matches gRPC's UNKNOWN → permanent)
            raise PermanentDeviceError(
                f"device service {status}: {out.get('error', '')}")
        if "error" in out:
            raise PermanentDeviceError(out["error"])
        return out

    def _post(self, path: str, payload: dict, op: str) -> dict:
        data = json.dumps(payload).encode()

        def attempt():
            raise_injected_fault(self.fault_plan, op, self.read_timeout)
            return self._do_post(path, data)

        return self.retry.run(op, attempt)

    # the JSON transport is schema-free: claim rows ride the request as-is
    supports_dra = True
    supports_health = True

    def apply_deltas(self, payload: dict) -> dict:
        return self._post("/v1/applyDeltas", payload, "apply_deltas")

    def schedule_batch(self, payload: dict) -> dict:
        return self._post("/v1/scheduleBatch", payload, "schedule_batch")

    def health(self) -> dict:
        """The cheap identity/liveness verb (half-open probe)."""
        return self._post("/v1/health", {"apiVersion": API_VERSION}, "health")


# ---------------------------------------------------------------- scheduler


class WireScheduler(Scheduler):
    """Control plane driving the device service over the wire: the batched
    analog of the HTTP extender, with the same host machinery around it as
    TPUScheduler (queue order, assume/bind, failure handling + backoff)."""

    def __init__(self, *args, endpoint: str, batch_size: int = 256,
                 transport: str = "http",
                 connect_timeout: float = 5.0, read_timeout: float = 60.0,
                 wire_max_retries: int = 3, wire_backoff_base: float = 0.05,
                 wire_backoff_max: float = 2.0, wire_deadline_s: float = 90.0,
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 fault_plan=None, sleep_fn=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.retry_policy = RetryPolicy(
            max_retries=wire_max_retries, backoff_base=wire_backoff_base,
            backoff_max=wire_backoff_max, deadline_s=wire_deadline_s,
            sleep_fn=sleep_fn if sleep_fn is not None else time.sleep,
            now_fn=self.now_fn,
            on_retry=lambda op: self.smetrics.wire_retries.inc(op))
        if transport == "grpc":
            from .grpc_service import GrpcClient

            self.client = GrpcClient(endpoint, read_timeout=read_timeout,
                                     retry=self.retry_policy,
                                     fault_plan=fault_plan)
        else:
            self.client = WireClient(endpoint, connect_timeout=connect_timeout,
                                     read_timeout=read_timeout,
                                     retry=self.retry_policy,
                                     fault_plan=fault_plan)
        self.batch_size = batch_size
        # circuit breaker + oracle degradation: N consecutive transport
        # failures open the breaker and every pod takes the sequential
        # oracle path until a half-open probe heals the wire (scheduling
        # never stops with a dead sidecar)
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s, now_fn=self.now_fn,
            on_state_change=self._on_breaker_state)
        self.smetrics.backend_circuit_state.set(value=0)
        self._degraded_since: Optional[float] = None
        self.degraded_pods = 0
        # state-resync protocol: last epoch the device answered with; a
        # mismatch (restart) surfaces as StaleEpochError → full resync
        self._device_epoch: Optional[str] = None
        self.resyncs = 0
        # idempotency keys for schedule_batch: one id per LOGICAL batch
        # (transport retries re-send the same id, so a server that already
        # committed replays its response instead of double-committing)
        self._batch_id_prefix = _new_epoch()
        self._batch_ids = itertools.count(1)
        self._sent_gens: Dict[str, int] = {}
        self._sent_ns: Dict[str, dict] = {}
        self._batchable_cache: Dict[str, bool] = {}
        self.settle_abandoned = False
        # claim resolution for the wire dra_mask path (the builder only
        # reads the store; the mask itself builds server-side)
        from .claim_mask import ClaimMaskBuilder

        self._claim_masks = ClaimMaskBuilder(self.store)

    # ------------------------------------------------------- degraded mode

    def _on_breaker_state(self, old: str, new: str) -> None:
        self.smetrics.backend_circuit_state.set(value=STATE_VALUES[new])
        now = self.now_fn()
        if new == "open" and self._degraded_since is None:
            self._degraded_since = now
        elif new == "closed" and self._degraded_since is not None:
            self.smetrics.degraded_seconds.inc(value=now - self._degraded_since)
            self._degraded_since = None

    def _accrue_degraded(self) -> None:
        """Fold elapsed degraded time into the counter incrementally so a
        long-open breaker is visible before it heals."""
        if self._degraded_since is not None:
            now = self.now_fn()
            self.smetrics.degraded_seconds.inc(value=now - self._degraded_since)
            self._degraded_since = now

    def _wire_supported(self, pod: Pod) -> bool:
        """Same gating as TPUScheduler.batch_supported: the service runs the
        compiled DEFAULT plugin set — volume pods and custom profiles take
        the local sequential path. Claim pods ride the wire when every
        claim resolves AND the transport carries the dra_mask input
        (ROADMAP PR 1 follow-up: the request schema ships resolved
        selector rows; the server builds the mask against its own
        attribute table)."""
        if pod.spec.volumes:
            return False
        if pod.spec.resource_claims:
            if not getattr(self.client, "supports_dra", False):
                return False
            if not self._claim_masks.batchable(pod):
                return False
        fwk = self.framework_for_pod(pod)
        cached = self._batchable_cache.get(fwk.profile_name)
        if cached is None:
            from ..framework.registry import DEFAULT_PLUGINS

            cached = all(
                [(p.name(), w) for p, w in fwk.points.get(point, [])]
                == list(DEFAULT_PLUGINS.get(point, []))
                for point in ("pre_filter", "filter", "pre_score", "score")
            )
            self._batchable_cache[fwk.profile_name] = cached
        return cached

    def _build_entries(self, skip_unsent_check: bool = False):
        """(entries, pending_gens) over the current snapshot — the one wire
        shape for per-node deltas, shared by the incremental push and the
        full resync so the two payloads can never drift apart."""
        entries: List[dict] = []
        pending_gens: Dict[str, int] = {}
        for name, ni in self.snapshot.node_info_map.items():
            if ni.node is None:
                continue
            if not skip_unsent_check and self._sent_gens.get(name) == ni.generation:
                continue
            entries.append({
                "gen": ni.generation,
                "node": to_wire(ni.node),
                "pods": [to_wire(p) for p in ni.pods],
            })
            pending_gens[name] = ni.generation
        return entries, pending_gens

    def _push_deltas(self) -> None:
        """Incremental state sync. Bookkeeping (_sent_gens/_sent_ns) commits
        only AFTER the wire call succeeds: a failed push must leave the rows
        marked unsent, or the retry after recovery would skip them and the
        device mirror would silently diverge from host truth."""
        self.cache.update_snapshot(self.snapshot)
        current = self.snapshot.node_info_map
        removed = [n for n in self._sent_gens if n not in current]
        entries, pending_gens = self._build_entries()
        namespaces = {}
        for ns, obj in self.store.namespaces.items():
            labels = dict(obj.meta.labels)
            if self._sent_ns.get(ns) != labels:
                namespaces[ns] = labels
        if not (entries or removed or namespaces):
            return
        payload = {"apiVersion": API_VERSION, "nodes": entries,
                   "removed": removed, "namespaces": namespaces}
        if self._device_epoch:
            payload["expectEpoch"] = self._device_epoch
        else:
            # epoch unknown = WE are the fresh process (client restart): a
            # surviving device may hold a mirror from our predecessor —
            # ghost nodes we cannot name in `removed` (_sent_gens is empty).
            # The first contact is therefore a FULL sync, establishing a
            # clean base exactly like the informer relist on startup.
            payload["full"] = True
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        try:
            out = self.client.apply_deltas(payload)
        except StaleEpochError as exc:
            # the device restarted under us: its mirror is a fresh empty
            # state — incremental deltas are meaningless against it
            self._full_resync(exc.epoch)
            return
        self._device_epoch = out.get("epoch", self._device_epoch)
        self._sent_gens.update(pending_gens)
        for n in removed:
            self._sent_gens.pop(n, None)
        for ns, labels in namespaces.items():
            self._sent_ns[ns] = labels

    def _full_resync(self, new_epoch: Optional[str] = None) -> None:
        """Epoch-mismatch recovery: forget everything we believe the device
        holds and ship the complete host truth as one ``full`` delta (the
        informer relist of the crash-only contract, pointed at the device)."""
        self.resyncs += 1
        self._sent_gens.clear()
        self._sent_ns.clear()
        self._device_epoch = new_epoch
        self.cache.update_snapshot(self.snapshot)
        entries, pending_gens = self._build_entries(skip_unsent_check=True)
        namespaces = {ns: dict(obj.meta.labels)
                      for ns, obj in self.store.namespaces.items()}
        payload = {"apiVersion": API_VERSION, "full": True, "nodes": entries,
                   "removed": [], "namespaces": namespaces}
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        out = self.client.apply_deltas(payload)
        self._device_epoch = out.get("epoch", new_epoch)
        self._sent_gens.update(pending_gens)
        self._sent_ns.update(namespaces)

    def schedule_batch_cycle(self) -> int:
        self._periodic_housekeeping()
        qps = self.queue.pop_batch(self.batch_size)
        if not qps:
            return 0
        t0 = self.now_fn()
        pod_cycle = self.queue.scheduling_cycle
        buffer: List[QueuedPodInfo] = []
        for qp in qps:
            pod = self.store.get_pod(qp.pod.key())
            if pod is None or pod.spec.node_name or not self._responsible_for(pod):
                continue
            qp.pod = pod
            # host-side gang quorum gate (the remote program does not model
            # Coscheduling's PreFilter) — same rule as the in-process path
            from ..framework.plugins.coscheduling import gang_precheck_status

            fwk = self.framework_for_pod(pod)
            gang_st = gang_precheck_status(fwk, pod)
            if gang_st is not None:
                self.metrics["schedule_attempts"] += 1
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, gang_st,
                    Diagnosis(unschedulable_plugins={"Coscheduling"}),
                    pod_cycle)
                continue
            if self._wire_supported(pod):
                buffer.append(qp)
                continue
            # strict pop order: flush the wire batch before a fallback pod so
            # a lower-priority local pod never jumps a batched one
            self._flush_wire(buffer, pod_cycle, t0)
            buffer = []
            self.cache.update_snapshot(self.snapshot)
            self.schedule_one_pod(qp, pod_cycle)
        self._flush_wire(buffer, pod_cycle, t0)
        return len(qps)

    def _flush_wire(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        if not batch:
            return
        # one scheduling.cycle span per wire batch: the traceparent injected
        # below makes the server's device.sync/encode/dispatch/commit spans
        # children of this span — a single trace from pop to device commit
        with tracing.span("scheduling.cycle", batch=len(batch),
                          transport=type(self.client).__name__):
            self._flush_wire_traced(batch, pod_cycle, t0)

    def _flush_wire_traced(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        if not self.breaker.allow():
            # breaker open: the device is presumed down — route the whole
            # batch through the sequential oracle path (scheduling never
            # stops); the next allow() past the reset timeout probes
            self._accrue_degraded()
            self._schedule_degraded(batch, pod_cycle)
            return
        from .circuit import HALF_OPEN

        if (self.breaker.state == HALF_OPEN
                and getattr(self.client, "supports_health", False)):
            # half-open probe = the cheap health RPC, not a full batch
            # pushed through a maybe-dead service: a dead sidecar costs one
            # tiny request and this batch degrades immediately; a live one
            # answers in microseconds and the real push proceeds
            try:
                self.client.health()
            except DeviceServiceError as exc:
                self.breaker.record_failure(exc)  # half-open: re-opens
                self._accrue_degraded()
                self._schedule_degraded(batch, pod_cycle)
                return
        try:
            self._push_deltas()
            res = self._wire_schedule_batch(batch)
        except DeviceServiceError as exc:
            # deliberately counts PERMANENT errors too: a deterministically
            # broken device (version skew answering 4xx forever) should open
            # the breaker and degrade to the oracle — the alternative is an
            # endless requeue→fail loop with zero wire throughput. The
            # breaker's lastError (/debug/circuit) keeps the bug visible.
            self.breaker.record_failure(exc)
            if self.breaker.state == OPEN:
                # threshold crossed (or a failed half-open probe): degrade
                # THIS batch immediately rather than bouncing it off backoff
                self._accrue_degraded()
                self._schedule_degraded(batch, pod_cycle)
            else:
                # breaker still counting: rate-limited requeue — the pods
                # re-enter via the backoff queue with their attempt counts,
                # never hot-looping the active queue
                self._requeue_wire_failure(batch, exc, pod_cycle, t0)
            return
        self.breaker.record_success()
        self._process_wire_results(batch, res, pod_cycle, t0)

    def _wire_schedule_batch(self, batch: List[QueuedPodInfo]) -> dict:
        from ..ops.tiebreak import seeds_for
        from .claim_mask import wire_claims_for_batch

        payload = {"apiVersion": API_VERSION,
                   "pods": [to_wire(qp.pod) for qp in batch],
                   "tieSeeds": [int(s) for s in seeds_for(batch)],
                   "batchId": f"{self._batch_id_prefix}-{next(self._batch_ids)}"}
        claims = wire_claims_for_batch(self.store, [qp.pod for qp in batch])
        if claims:
            payload["claims"] = claims
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        if self._device_epoch:
            payload["expectEpoch"] = self._device_epoch
        # device restarted between the delta push and this batch (or again
        # mid-recovery — a crash-looping sidecar): each stale answer costs
        # one cheap full resync, bounded so a restart storm falls through to
        # the breaker instead of spinning here
        stale_retries = 0
        while True:
            try:
                res = self.client.schedule_batch(payload)
                break
            except StaleEpochError as exc:
                stale_retries += 1
                if stale_retries > 2:
                    raise
                self._full_resync(exc.epoch)
                if self._device_epoch:
                    payload["expectEpoch"] = self._device_epoch
                else:
                    payload.pop("expectEpoch", None)
        self._device_epoch = res.get("epoch", self._device_epoch)
        return res

    def _schedule_degraded(self, batch: List[QueuedPodInfo], pod_cycle: int) -> None:
        self.degraded_pods += len(batch)
        self.cache.update_snapshot(self.snapshot)
        for qp in batch:
            self.schedule_one_pod(qp, pod_cycle)

    def _requeue_wire_failure(self, batch: List[QueuedPodInfo],
                              exc: Exception, pod_cycle: int, t0: float) -> None:
        for qp in batch:
            fwk = self.framework_for_pod(qp.pod)
            self.metrics["schedule_attempts"] += 1
            self.metrics["errors"] += 1
            self.smetrics.observe_attempt(
                "error", fwk.profile_name, self.now_fn() - t0)
            self._handle_scheduling_failure(
                fwk, self._new_cycle_state(), qp,
                Status.error(f"device service: {exc}"), Diagnosis(), pod_cycle)

    def _invalidate_node(self, node_name: str) -> None:
        """Force ``node_name``'s row back through the delta channel: the
        device adopted a placement the host is rejecting, and the host
        generation did NOT advance (nothing was assumed), so without this
        the server would keep the phantom commit forever — its sync skips
        rows whose generation matches and its mirror already holds the
        adopted state. Bumping the cache generation makes the next push
        re-send host truth; the server's content diff then repairs the row
        (the wire twin of TPUScheduler's ``_uploaded_gen`` pop)."""
        from ..framework.types import next_generation

        with self.cache._lock:
            ni = self.cache.nodes.get(node_name)
            if ni is not None:
                ni.generation = next_generation()
                # the incremental snapshot walks the dirty set, not raw
                # generations — without this the bump is never revisited
                self.cache._dirty.add(node_name)
        self._sent_gens.pop(node_name, None)

    def _process_wire_results(self, batch: List[QueuedPodInfo], res: dict,
                              pod_cycle: int, t0: float) -> None:
        from ..framework.plugins.coscheduling import pod_group_key

        # hint-screen scaffolding, shared by every failed pod in the batch
        hint_names = hint_slot_of = None
        # gang all-or-nothing: a gang with any unplaced member is rejected
        # WHOLE — placed members surrender their slots instead of parking a
        # partial gang at Permit (mirror of the in-process _judge_gangs)
        gang_rejected: Dict[int, str] = {}
        groups: Dict[str, List[int]] = {}
        for i, qp in enumerate(batch):
            gkey = pod_group_key(qp.pod)
            if gkey is not None:
                groups.setdefault(gkey, []).append(i)
        for gkey, idxs in groups.items():
            if any(not res["results"][i].get("nodeName") for i in idxs):
                for i in idxs:
                    gang_rejected[i] = gkey
                plugin = self.framework_for_pod(
                    batch[idxs[0]].pod).plugin("Coscheduling")
                if plugin is not None:
                    plugin.reject_gang(gkey, "incomplete")
        for i, (qp, r) in enumerate(zip(batch, res["results"])):
            fwk = self.framework_for_pod(qp.pod)
            self.metrics["schedule_attempts"] += 1
            node_name = r.get("nodeName")
            if i in gang_rejected:
                if node_name:
                    # the device already adopted this member's placement;
                    # surrendering it must re-send the node's host truth
                    self._invalidate_node(node_name)
                d = Diagnosis(unschedulable_plugins={"Coscheduling"})
                d.unschedulable_plugins.update(
                    r.get("unschedulablePlugins") or ())
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, Status.unschedulable(
                        f'gang "{gang_rejected[i]}" could not be fully '
                        "placed"), d, pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                continue
            if node_name:
                if self.snapshot.get(node_name) is None:
                    # ghost placement: the device named a node the host no
                    # longer knows (a desync window the resync protocol
                    # hasn't closed yet) — error-requeue the pod instead of
                    # binding it to a nonexistent node
                    self.metrics["errors"] += 1
                    self.smetrics.observe_attempt(
                        "error", fwk.profile_name, self.now_fn() - t0)
                    self._handle_scheduling_failure(
                        fwk, self._new_cycle_state(), qp,
                        Status.error(f"device placed pod on unknown node "
                                     f"{node_name}"), Diagnosis(), pod_cycle)
                    continue
                state = self._new_cycle_state()
                if qp.pod.spec.resource_claims or qp.pod.spec.volumes:
                    # Reserve allocates claims from PreFilter cycle state
                    # (and re-verifies the claims still exist) — exactly
                    # the in-process commit rule
                    _, pre_st = fwk.run_pre_filter_plugins(state, qp.pod)
                    if not pre_st.is_success():
                        # host rejected what the device adopted: re-send
                        # the node's truth on the next push
                        self._invalidate_node(node_name)
                        self.cache.update_snapshot(self.snapshot)
                        self.schedule_one_pod(qp, pod_cycle)
                        continue
                self.assume_and_bind(fwk, state, qp, qp.pod,
                                     node_name, pod_cycle, t0=t0)
            else:
                d = Diagnosis()
                for name, plugin in (r.get("statuses") or {}).items():
                    reason = dict(_ATTRIBUTION_ORDER).get(plugin, "unschedulable")
                    d.node_to_status[name] = Status.unschedulable(reason).with_plugin(plugin)
                d.unschedulable_plugins.update(r.get("unschedulablePlugins") or ())
                state = self._new_cycle_state()
                hint = r.get("preempt")
                if hint is not None:
                    # rebuild the screen over OUR node names: candidates the
                    # service listed pass, every other known node fails,
                    # unknown (post-snapshot) nodes stay permissive. A None
                    # candidate list means the service truncated (screen
                    # inexact): pass everything and keep only the ranked
                    # best as the preferred-node fast path.
                    from ..framework.plugins.defaultpreemption import DefaultPreemption

                    if hint_slot_of is None:  # loop-invariant: build once
                        hint_names = list(self._sent_gens)
                        hint_slot_of = {n: i for i, n in enumerate(hint_names)}
                    if hint.get("candidates") is None:
                        row = np.ones(len(hint_names), bool)
                    else:
                        row = np.zeros(len(hint_names), bool)
                        for n in hint["candidates"]:
                            if n in hint_slot_of:
                                row[hint_slot_of[n]] = True
                    state.write(DefaultPreemption.HINTS_KEY,
                                (row, hint_slot_of, hint.get("best")))
                self._handle_scheduling_failure(
                    fwk, state, qp, Status.unschedulable("no feasible node"),
                    d, pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                          idle_wait: float = 0.005, max_no_progress: int = 200) -> int:
        # the shared batched settle loop (Scheduler.run_batched_until_settled),
        # incl. the idle-wait backoff for flapping pods
        return self.run_batched_until_settled(
            max_cycles=max_cycles, flush=flush, idle_wait=idle_wait,
            max_no_progress=max_no_progress)

    def debug_circuit(self) -> dict:
        """/debug/circuit body: breaker state + resync/degradation story."""
        out = self.breaker.dump()
        out.update({
            "enabled": True,
            "deviceEpoch": self._device_epoch,
            "resyncs": self.resyncs,
            "degradedPods": self.degraded_pods,
            "retryPolicy": {
                "maxRetries": self.retry_policy.max_retries,
                "backoffBase": self.retry_policy.backoff_base,
                "backoffMax": self.retry_policy.backoff_max,
                "deadlineS": self.retry_policy.deadline_s,
            },
        })
        return out
