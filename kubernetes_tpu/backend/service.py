"""The batched device service seam (SURVEY §5.8 hop 6).

The reference's only out-of-process scheduling extension is the per-pod JSON
extender webhook (extender.go:42,247) — one HTTP POST per pod per extender,
which is exactly its performance failure. This service batches and adds
state: the control plane streams generation-keyed node deltas
(``ApplyDeltas``) and submits whole pod micro-batches (``ScheduleBatch``);
the device side keeps the encoded mirror across calls, so steady-state
requests carry only dirty rows and the pod batch.

Three pieces:
  * ``DeviceService`` — transport-agnostic server core owning a DeviceState
    and the compiled batch program; the hot path mirrors TPUScheduler's
    device half (delta sync, capacity growth, adopt-on-dispatch).
  * ``serve``/``DeviceServiceHTTP`` — stdlib HTTP/JSON binding on localhost
    (the in-process path stays the fast mode; this seam exists to measure
    and bound the serialization/transport cost the reference pays at
    QPS-5000, scheduler_perf util.go:86-90).
  * ``WireScheduler`` — a Scheduler whose filter/score middle goes over the
    wire; queue/cache/assume/bind/failure handling stay the same host
    machinery (the north-star seam: the control plane does not know whether
    the backend is in-process or remote).

HA topology (ISSUE 6): N WireScheduler replicas share ONE DeviceService.
Requests carry a ``clientId``/``sessionGen``; the service keeps per-client
sessions with leases, overlays adopted-but-unconfirmed placements as holds,
validates every placement at commit time (typed ``conflict`` verdicts on
cross-client races), and fences dead clients so survivors adopt the freed
capacity. See README "HA topology".

Wire envelope: {"apiVersion": "ktpu/v1", ...}; objects use api/codec.py.
"""

from __future__ import annotations

import http.client
import itertools
import json
import os
import socket
import threading
import time
import urllib.parse
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace
from typing import Deque, Dict, List, Optional

import numpy as np

from ..api.codec import from_wire, to_wire
from ..api.types import Node, Pod
from ..framework.types import Diagnosis, NodeInfo, QueuedPodInfo
from ..framework.interface import CycleState, Status
from ..metrics import latency_ledger
from ..ops.encode import CapacityError
from ..scheduler.scheduler import Scheduler
from ..testing import locktrace
from ..utils import tracing
from . import telemetry
from .batch import build_schedule_batch_fn
from .circuit import CircuitBreaker, OPEN, STATE_VALUES
from .device_state import DeviceState, caps_for_cluster
from .errors import (
    ConflictError,
    DeviceServiceError,
    PermanentDeviceError,
    RetryPolicy,
    StaleEpochError,
    TransientDeviceError,
    raise_injected_fault,
)
from .tpu_scheduler import _ATTRIBUTION_ORDER, TPUScheduler

API_VERSION = "ktpu/v1"

# session lease: a scheduler replica that stops heartbeating for this long
# is declared dead and FENCED — its uncommitted capacity is released for the
# survivors and any late request from the dead incarnation gets a Conflict
# (the fencing-token rule: a fenced writer can never commit)
DEFAULT_LEASE_TTL_S = 15.0

# process-epoch minting: unique per DeviceService INSTANCE (a restarted
# sidecar is a new instance holding a fresh empty DeviceState; the epoch is
# how the client tells a restart from a healthy peer — etcd's cluster-id /
# member-id check on reconnect plays the same role)
_EPOCH_IDS = itertools.count(1)


def _new_epoch() -> str:
    return f"{os.getpid():x}-{next(_EPOCH_IDS)}"


class ClientSession:
    """Per-client sync state (the server half of what used to be the single
    unnamed client's ``_sent_gens``): which node generations THIS client has
    pushed, its delta sequence, its idempotency cache, and its lease. A
    fresh/rejoining client resets only its own slice — other clients' state
    is untouched."""

    # idempotency-cache depth: a PIPELINED client keeps up to K batches in
    # flight, so a transport retry can be for any of its last K logical
    # batches, not just the newest (the single-entry cache of the strictly
    # request/response era). Bounded well above any sane pipeline depth —
    # a retry falling off this cache is re-COMPUTED, which the ownership
    # check resolves as the owner re-deciding its own holds (no double
    # bind), but the replayed-result fast path is lost.
    IDEMPOTENCY_DEPTH = 32

    __slots__ = ("client_id", "gen", "created_at", "last_seen", "delta_seq",
                 "sent_gens", "last_batches", "batch_replays", "batches",
                 "fenced", "fenced_seq", "fence_seq_seen", "released_holds",
                 "replicator", "last_push_seq")

    def __init__(self, client_id: str, gen: int, now: float):
        self.client_id = client_id
        # warm-standby replication session (DeviceFabric): its node claims
        # keep the warm DeviceState alive across the promote-time full
        # resync, but never block another client's ghost sweep — the
        # replicator mirrors a PAST truth; the resyncing client IS truth
        self.replicator = False
        # service delta_seq at this session's last applied push: a
        # replicator "lapped" by a direct client's full resync (the resync
        # happened after the replicator's last contact) must reseed — its
        # next push could re-create nodes the resync swept
        self.last_push_seq = 0
        self.gen = gen                      # session incarnation (rejoin bumps)
        self.created_at = now
        self.last_seen = now                # lease heartbeat clock
        self.delta_seq = 0
        self.sent_gens: Dict[str, int] = {}  # node -> last gen this client pushed
        # batchId -> response, insertion-ordered, bounded (see above)
        self.last_batches: "OrderedDict[str, dict]" = OrderedDict()
        self.batch_replays = 0
        self.batches = 0
        self.fenced = False
        self.fenced_seq = 0                 # fence-log seq of OUR fencing
        self.fence_seq_seen = 0             # fence-log cursor for heartbeats
        self.released_holds = 0

    @property
    def last_batch(self) -> Optional[tuple]:
        """(batchId, response) of the NEWEST cached batch (None when the
        cache is empty/poisoned) — the single-entry era's introspection
        surface, kept for the fence tests and /debug/sessions."""
        if not self.last_batches:
            return None
        bid = next(reversed(self.last_batches))
        return (bid, self.last_batches[bid])

    def cache_batch(self, batch_id: str, response: dict) -> None:
        self.last_batches[batch_id] = response
        while len(self.last_batches) > self.IDEMPOTENCY_DEPTH:
            self.last_batches.popitem(last=False)


class _Hold:
    """One adopted-but-unconfirmed placement: the device committed the pod
    for ``owner``, but no client's host truth includes it yet. While held,
    every delta for the node re-overlays the pod so another replica's
    (lagging) push can never erase the capacity and hand it out twice.
    ``batch_id`` names the batch that created it: a PIPELINED owner's delta
    push may predate its processing of that batch's reply, so omission from
    the owner's content releases the hold only once the owner no longer
    lists the batch as in flight."""

    __slots__ = ("pod", "node_name", "owner", "seen", "batch_id")

    def __init__(self, pod: Pod, node_name: str, owner: str,
                 batch_id: Optional[str] = None):
        self.pod = pod
        self.node_name = node_name
        self.owner = owner
        self.seen: set = set()  # client ids whose pushed content included it
        self.batch_id = batch_id


class DeviceService:
    """Server core: node mirror + device state + one compiled batch program.

    Multi-tenant (active-active HA): any number of scheduler replicas share
    this one service. Every request may carry a ``clientId`` (+ the
    ``sessionGen`` the service answered with); the service keeps per-client
    sessions, overlays adopted-but-unconfirmed placements onto the shared
    mirror (``_Hold``), validates every placement at commit time against
    current ownership/occupancy (cross-client races get a typed ``conflict``
    verdict, never a double-bind), and fences clients whose lease expires —
    releasing their uncommitted capacity to the survivors."""

    def __init__(self, batch_size: int = 512,
                 percentage_of_nodes_to_score: int = 0,
                 lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
                 now_fn=time.monotonic):
        self.batch_size = batch_size
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.lease_ttl_s = lease_ttl_s
        self.now_fn = now_fn
        # state-resync protocol: the epoch names THIS process incarnation;
        # delta_seq counts applied delta generations within it. A client
        # whose expectEpoch disagrees gets a stale-state error instead of
        # silently having its deltas applied against the wrong (empty) base.
        self.epoch = _new_epoch()
        self.delta_seq = 0
        # per-client sessions (idempotency caches live inside — one entry
        # per client suffices: each client is sequential and only ever
        # retries its most recent batch). batch_replays stays as the
        # aggregate counter the single-client tests read.
        self.sessions: Dict[str, ClientSession] = {}
        self._session_gens = itertools.count(1)
        self.batch_replays = 0
        # adopted-but-unconfirmed placements: pod key -> _Hold
        self.holds: Dict[str, _Hold] = {}
        # pod key -> node for pods present in pushed CONTENT (host truth):
        # the ownership check's "already bound" index
        self._pod_nodes: Dict[str, str] = {}
        self._node_pod_keys: Dict[str, set] = {}
        # delta_seq of the most recent DIRECT (non-replicator) full
        # resync: the lap marker for replicator sessions (see
        # ClientSession.last_push_seq)
        self._last_direct_full_seq = 0
        # fence log: (seq, client_id) — heartbeat responses tell survivors
        # which peers were fenced since their last beat
        self._fences: List[tuple] = []
        self._fence_seq = 0
        self.takeovers = 0
        self.commit_conflicts = 0
        self.infos: Dict[str, NodeInfo] = {}
        # duck-typed Snapshot: the wire service mirrors nodes wholesale per
        # delta, so every sync is a "structure changed" full walk — the
        # changed_names/structure_version fields exist only to satisfy
        # DeviceState's O(changes) bookkeeping (a fresh version each sync
        # forces the full path, which is correct here)
        self.snap = SimpleNamespace(node_info_map=self.infos,
                                    changed_names=set(), structure_version=0)
        self.ns_labels: Dict[str, Dict[str, str]] = {}
        # ns -> (used row, limit row): the client's quota-ledger export for
        # the device over-quota screen, replaced whole by each delta
        # payload that carries a quotaTable (it is tiny, so the client
        # ships the complete desired state whenever it changes)
        self.quota_table: Dict[str, tuple] = {}
        self.device: Optional[DeviceState] = None
        self.schedule_batch_fn = build_schedule_batch_fn()
        self.batch_counter = 0
        self._start_carry = None  # adaptive-sampling rotation (device scalar)
        self._lock = locktrace.make_lock("DeviceService")

    # ------------------------------------------------------------- epoch

    def check_epoch(self, req: dict) -> None:
        """Refuse a request stamped with another incarnation's epoch: the
        client's incremental deltas assume a base THIS process never had.
        A full resync (``full: true``) establishes a new base, so it is
        exempt — it is exactly the recovery move the error demands."""
        expect = req.get("expectEpoch")
        if expect and expect != self.epoch and not req.get("full"):
            raise StaleEpochError(self.epoch)

    def _stamp(self, out: dict) -> dict:  # ktpu: locked
        out["epoch"] = self.epoch
        out["deltaSeq"] = self.delta_seq
        return out

    # ------------------------------------------------------------ sessions

    def _live_sessions(self) -> List[ClientSession]:  # ktpu: locked
        return [s for s in self.sessions.values() if not s.fenced]

    def _session_for(self, req: dict) -> ClientSession:  # ktpu: locked
        """Resolve (creating/rejoining as needed) the request's session and
        touch its lease. Caller holds the lock. Raises ConflictError for a
        fenced incarnation: a dead-declared client must rejoin (fresh
        sessionGen + full resync), never silently keep committing."""
        now = self.now_fn()
        self._sweep_leases(now)
        cid = req.get("clientId") or ""
        gen = req.get("sessionGen")
        s = self.sessions.get(cid)
        if s is None or (s.fenced and gen is None):
            # first contact, or an explicit rejoin after a fence: a fresh
            # incarnation with its own generation and empty sync state.
            # History starts NOW — fences that predate this session are not
            # takeover news for it.
            s = ClientSession(cid, next(self._session_gens), now)
            s.fence_seq_seen = self._fence_seq
            self.sessions[cid] = s
        if s.fenced:
            raise ConflictError(
                f"client {cid!r} session {gen} was fenced (lease expired "
                f"after {self.lease_ttl_s}s); rejoin with a full resync")
        if gen is not None and gen != s.gen:
            # a zombie from a previous incarnation of the same clientId:
            # its view of its own holds is gone — it must not commit
            raise ConflictError(
                f"client {cid!r} session {gen} superseded by {s.gen}")
        if req.get("replicator"):
            s.replicator = True
        s.last_seen = now
        return s

    def _sweep_leases(self, now: float) -> None:  # ktpu: locked
        """Fence every named session whose lease expired. Anonymous
        (legacy, clientId-less) sessions never expire — they are the
        single-client demo topology and send no heartbeats."""
        for cid, s in list(self.sessions.items()):
            if not cid or s.fenced:
                continue
            if now - s.last_seen > self.lease_ttl_s:
                self._fence(s)

    def _fence(self, s: ClientSession) -> None:  # ktpu: locked
        """Declare a client dead: poison its idempotency cache server-side
        (a late transport retry of its last batch will NOT be replayed),
        and release its adopted-but-unconfirmed rows so a survivor adopts
        the freed capacity — the scheduler-death twin of PR 5's device
        poison-and-requeue."""
        last_batch_id = s.last_batch[0] if s.last_batch else None
        s.fenced = True
        s.last_batches.clear()  # poison: a zombie retry must never replay
        self._fence_seq += 1
        s.fenced_seq = self._fence_seq
        self._fences.append((self._fence_seq, s.client_id))
        self.takeovers += 1
        released_before = s.released_holds
        for key, hold in list(self.holds.items()):
            if hold.owner != s.client_id:
                continue
            # only never-confirmed capacity is released: a hold whose pod
            # is in the node's current pushed content — or was EVER seen in
            # any client's truth — is really bound; removing it would free
            # capacity a live pod still occupies and hand it out twice
            confirmed = (key in self._node_pod_keys.get(hold.node_name, ())
                         or hold.seen)
            if not confirmed:
                ni = self.infos.get(hold.node_name)
                if ni is not None:
                    ni.remove_pod(hold.pod)
                s.released_holds += 1
            del self.holds[key]
        telemetry.event("fence", client=s.client_id, epoch=self.epoch,
                        batchId=last_batch_id,
                        releasedHolds=s.released_holds - released_before)

    def _prune_fences(self) -> None:  # ktpu: locked
        """Bound the fence bookkeeping (lock held): default client ids are
        unique per scheduler process, so routine replica redeploys would
        otherwise accrete one dead ClientSession (O(nodes) sent_gens) and
        one fence-log entry FOREVER. Once every live session's heartbeat
        cursor has passed a fence, the log entry and the dead session are
        droppable — the fencing token lives in the session GENERATION (a
        zombie's stamped gen can never match a newly minted one), not in
        the fenced record."""
        live = [s for s in self.sessions.values()
                if not s.fenced and s.client_id]
        if not live:
            return
        horizon = min(s.fence_seq_seen for s in live)
        if self._fences and self._fences[0][0] <= horizon:
            self._fences = [(seq, cid) for seq, cid in self._fences
                            if seq > horizon]
        # dead session records stay inspectable (/debug/sessions) for a
        # grace window, then drop once every live peer has been told
        grace = 10.0 * self.lease_ttl_s
        now = self.now_fn()
        for cid, s in list(self.sessions.items()):
            if (s.fenced and s.fenced_seq <= horizon
                    and now - s.last_seen > grace):
                del self.sessions[cid]

    def heartbeat(self, req: dict) -> dict:
        """Lease renewal + takeover signal: touching the session IS the
        renewal; the response carries every peer fenced since this
        client's previous beat so a survivor can adopt the dead replica's
        queue slice (and count scheduler_ha_takeovers_total)."""
        with self._lock:
            s = self._session_for(req)
            fenced = [cid for seq, cid in self._fences
                      if seq > s.fence_seq_seen and cid != s.client_id]
            s.fence_seq_seen = self._fence_seq
            self._prune_fences()
            return self._stamp({
                "apiVersion": API_VERSION,
                "sessionGen": s.gen,
                "leaseTtlS": self.lease_ttl_s,
                "sessions": len(self._live_sessions()),
                "fenced": fenced,
            })

    def sessions_dump(self, req: Optional[dict] = None) -> dict:
        """/v1/sessions (the /debug/sessions body): per-client lease age,
        delta sequence, in-flight hold count, replay/fence counters."""
        with self._lock:
            now = self.now_fn()
            per_owner: Dict[str, int] = {}
            for hold in self.holds.values():
                per_owner[hold.owner] = per_owner.get(hold.owner, 0) + 1
            sessions = []
            for cid in sorted(self.sessions):
                s = self.sessions[cid]
                sessions.append({
                    "clientId": cid,
                    "sessionGen": s.gen,
                    "leaseAgeS": now - s.last_seen,
                    "leaseTtlS": self.lease_ttl_s if cid else None,
                    "deltaSeq": s.delta_seq,
                    "sentNodes": len(s.sent_gens),
                    "batches": s.batches,
                    "batchReplays": s.batch_replays,
                    "inflightHolds": per_owner.get(cid, 0),
                    "releasedHolds": s.released_holds,
                    "fenced": s.fenced,
                })
            return self._stamp({
                "apiVersion": API_VERSION,
                "enabled": True,
                "leaseTtlS": self.lease_ttl_s,
                "takeovers": self.takeovers,
                "commitConflicts": self.commit_conflicts,
                "holds": len(self.holds),
                "sessions": sessions,
            })

    # ------------------------------------------------------------- deltas

    def apply_deltas(self, req: dict) -> dict:
        self.check_epoch(req)
        # server half of W3C-traceparent propagation: the delta sync parents
        # under the client's scheduling.cycle span (no-op, one global read,
        # when tracing is disabled)
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.apply_deltas",
                                      nodes=len(req.get("nodes", ()))):
            return self._apply_deltas_traced(req)

    def _apply_deltas_traced(self, req: dict) -> dict:
        # decode OUTSIDE the lock: the wire payload is request-local and the
        # from_wire walk is O(nodes × pods) pure-CPU work — holding the
        # service lock across it starves peer replicas' heartbeats for no
        # consistency gain (found by the locktrace hold-time review)
        decoded = []
        for e in req.get("nodes", ()):
            node = from_wire(Node, e["node"])
            pods = [from_wire(Pod, pw) for pw in e.get("pods", ())]
            decoded.append((node, pods, e.get("gen")))
        # pipelined clients name the batches whose replies they have not
        # processed yet: holds created by those batches must survive
        # owner-content omission (the owner's truth CANNOT include them)
        inflight_ids = set(req.get("inflightBatchIds") or ())
        with self._lock:
            s = self._session_for(req)
            if s.replicator and self._last_direct_full_seq > s.last_push_seq:
                # LAPPED replicator: a direct client full-resynced this
                # service after the replicator's last contact (promote, or
                # a failback reseed window). Its pending push was built
                # against a pre-resync world and could re-CREATE nodes the
                # resync swept — refuse it and demand a fresh full seed
                # (the fabric's ConflictError handler reseeds). The cursor
                # advances so the reseed itself is accepted.
                s.last_push_seq = self.delta_seq
                raise ConflictError(
                    "replicator lapped by a direct full resync; reseed")
            if req.get("full"):
                # full resync replaces THIS client's contribution only. A
                # mirror node no other live session claims and the full set
                # omits is a ghost (a dead predecessor's world) — sweep it.
                # With a single session this degenerates to the old
                # clear-everything semantics.
                s.sent_gens.clear()
                pushed = {node.meta.name for node, _, _ in decoded}
                # the anonymous (legacy single-client) session never claims
                # nodes: it predates sessions, sends no heartbeats, and its
                # full pushes keep the old everything-or-nothing contract
                others = [o for o in self._live_sessions()
                          if o is not s and o.client_id]
                # a REPLICATOR session's claims never block the sweep: it
                # mirrors a past truth, and a node it alone still claims
                # after a scheduler client's full resync is exactly the
                # ghost the sweep exists to drop (the fabric's delta
                # stream repairs the replicator's view separately). It DOES
                # count for device retention below — dropping the warm
                # DeviceState at promote would throw the O(dirty) resync
                # away.
                claimers = [o for o in others if not o.replicator]
                if s.replicator:
                    # a replicator's full RESEED outranks direct claims
                    # older than its own previous contact (a healed
                    # ex-active's idle session would otherwise pin its
                    # stale tenure claims — and their ghost nodes —
                    # forever); claims refreshed by a newer direct push
                    # still win (the promote-resync case)
                    claimers = [o for o in claimers
                                if o.last_push_seq > s.last_push_seq]
                for name in list(self.infos):
                    if name in pushed:
                        continue
                    if any(name in o.sent_gens for o in claimers):
                        continue
                    self._drop_node(name)
                    for o in others:
                        o.sent_gens.pop(name, None)
                if not others:
                    self.ns_labels.clear()
                    self.quota_table.clear()
                    self.device = None
            live_ids = {o.client_id for o in self._live_sessions()}
            # a REPLICATOR mirrors a client's PAST pushes: if a direct
            # (non-replicator) session has already pushed a node at the
            # same or a newer generation, the replicator's entry is stale
            # — skip it. This closes the promote-time race where an
            # in-flight replication push lands AFTER the promoted
            # replica's full resync: the client's rows can never be
            # overwritten backward (worst case a skipped row stays for
            # the next delta to repair — extra upload bytes, never wrong
            # truth).
            direct = ([o for o in self._live_sessions()
                       if o is not s and not o.replicator and o.client_id]
                      if s.replicator else [])
            # ...but only direct sessions that pushed SINCE the
            # replicator's previous contact outrank the stream wholesale
            # (removals/sweeps below): a healed ex-active's idle session
            # keeps stale claims alive forever (its lease is deliberately
            # kept warm), and deferring to those would strand deleted
            # nodes in the standby mirror until the next promote.
            # s.last_push_seq still holds the PREVIOUS contact here — it
            # advances only after this push applies.
            direct_newer = [o for o in direct
                            if o.last_push_seq > s.last_push_seq]
            for node, pods, gen in decoded:
                name = node.meta.name
                if s.replicator and gen is not None and any(
                        o.sent_gens.get(name) is not None
                        and o.sent_gens[name] >= gen for o in direct):
                    continue
                ni = NodeInfo(node)
                content_keys = set()
                for pod in pods:
                    ni.add_pod(pod)
                    content_keys.add(pod.key())
                if gen is not None:
                    ni.generation = gen
                    s.sent_gens[name] = gen
                # hold reconciliation: the pusher's content is authoritative
                # for its OWN holds (assumed pods live in its cache, so an
                # omission means surrendered/forgotten/expired — release);
                # other owners' holds are re-overlaid until every live
                # client's truth has caught up (else a lagging replica's
                # push would erase capacity another replica just committed
                # and the next batch would hand it out twice)
                for key, hold in list(self.holds.items()):
                    if hold.node_name != name:
                        continue
                    if key in content_keys:
                        hold.seen.add(s.client_id)
                        if live_ids <= hold.seen:
                            del self.holds[key]  # durable in everyone's truth
                    elif (hold.owner == s.client_id
                          and not (hold.batch_id
                                   and hold.batch_id in inflight_ids)):
                        del self.holds[key]      # owner surrendered it
                    else:
                        # overlay: capacity stays taken — a peer's unconfirmed
                        # hold, or the pusher's OWN hold from a batch still in
                        # flight on its pipelined transport (its truth cannot
                        # include the placement before it processes the reply)
                        ni.add_pod(hold.pod)
                for key in self._node_pod_keys.get(name, ()):
                    # only drop index entries still pointing HERE: a pod
                    # deleted and re-bound elsewhere under the same key has
                    # a live entry for its new node that must survive this
                    # node's stale key list
                    if self._pod_nodes.get(key) == name:
                        del self._pod_nodes[key]
                self._node_pod_keys[name] = content_keys
                for key in content_keys:
                    self._pod_nodes[key] = name
                self.infos[name] = ni
            for name in req.get("removed", ()):
                if s.replicator and any(name in o.sent_gens
                                        for o in direct_newer):
                    # stale replicated removal: a direct client has pushed
                    # the node SINCE this replicator's previous contact —
                    # its truth wins
                    s.sent_gens.pop(name, None)
                    continue
                self._drop_node(name)
                s.sent_gens.pop(name, None)
            # namespace labels ride along so namespaceSelector terms match
            # identically to the in-process path (sig_table ns_labels_fn)
            for ns, labels in (req.get("namespaces") or {}).items():
                self.ns_labels[ns] = dict(labels)
            # the quota screen table rides the same channel: presence means
            # the client shipped its COMPLETE ledger view (absent namespaces
            # lost their quota — set_ns_quota resets their rows)
            qt = req.get("quotaTable")
            if qt is not None:
                self.quota_table = {
                    ns: (rows.get("used") or [], rows.get("limit") or [])
                    for ns, rows in qt.items()}
            self._sync()
            self.delta_seq += 1
            s.delta_seq += 1
            s.last_push_seq = self.delta_seq
            if req.get("full") and not s.replicator and s.client_id:
                self._last_direct_full_seq = self.delta_seq
            return self._stamp({"apiVersion": API_VERSION,
                                "nodes": len(self.infos),
                                "sessionGen": s.gen})

    def _drop_node(self, name: str) -> None:  # ktpu: locked
        """Remove a node and every index/hold anchored to it (lock held)."""
        self.infos.pop(name, None)
        for key in self._node_pod_keys.pop(name, ()):
            if self._pod_nodes.get(key) == name:  # see _apply_deltas_traced
                del self._pod_nodes[key]
        for key, hold in list(self.holds.items()):
            if hold.node_name == name:
                del self.holds[key]

    def _ensure_device(self) -> None:  # ktpu: locked
        import dataclasses

        n = max(len(self.infos), 1)
        ns_fn = lambda ns: self.ns_labels.get(ns, {})  # noqa: E731  # ktpu: unguarded-ok(invoked by device.sync, which only runs under the service lock)
        if self.device is None:
            self.device = DeviceState(caps_for_cluster(n, batch=self.batch_size),
                                      ns_labels_fn=ns_fn)
        elif self.device.caps.nodes < n:
            caps = self.device.caps
            nodes = caps.nodes
            while nodes < n:
                nodes *= 2
            self.device = DeviceState(dataclasses.replace(
                caps, nodes=nodes,
                value_words=max(caps.value_words, (nodes + 2 + 31) // 32)),
                ns_labels_fn=ns_fn)

    def _sync(self) -> None:  # ktpu: locked
        self._ensure_device()
        for _attempt in range(8):
            try:
                # deliberate blocking-under-lock: the mirror the device syncs
                # from must not change until the batch that judged against it
                # commits — the commit-time validation contract
                locktrace.note_blocking(
                    "device_sync", "DeviceService.sync",
                    allowed="mirror must stay frozen from sync to commit")
                with tracing.span("device.sync"):
                    self.device.sync(self.snap)
                return
            except CapacityError as e:
                self._grow(e)
        # typed per the taxonomy: deterministic (the same delta re-raises),
        # so the client must never burn retry budget on it
        raise PermanentDeviceError("device capacities refuse to converge")

    def _grow(self, err: CapacityError) -> None:  # ktpu: locked
        import dataclasses

        caps = self.device.caps
        fields = TPUScheduler._GROW_FIELDS.get(err.dimension)
        if fields is None and err.dimension.startswith("value vocab"):
            fields = ("value_words",)
        if fields is None:
            raise PermanentDeviceError(
                f"unknown capacity dimension {err.dimension!r}") from err
        updates = {}
        for f in fields:
            v = getattr(caps, f)
            while v < err.needed:
                v *= 2
            updates[f] = v
        self.device = DeviceState(
            dataclasses.replace(caps, **updates),
            ns_labels_fn=lambda ns: self.ns_labels.get(ns, {}))  # ktpu: unguarded-ok(invoked by device.sync, which only runs under the service lock)

    # --------------------------------------------------------------- health
    def health(self, req: dict) -> dict:
        """Cheap liveness/identity verb: no device work, no epoch check (a
        stale client calling this LEARNS the current epoch — exactly what a
        half-open circuit probe needs instead of pushing a full batch
        through a maybe-dead service)."""
        with self._lock:
            return self._stamp({"apiVersion": API_VERSION,
                                "status": "serving",
                                "nodes": len(self.infos)})

    # ------------------------------------------------------------- schedule

    def schedule_batch(self, req: dict) -> dict:
        self.check_epoch(req)
        batch_id = req.get("batchId")
        session_req = {"clientId": req.get("clientId"),
                       "sessionGen": req.get("sessionGen")}
        with self._lock:
            s = self._session_for(session_req)
            if batch_id and batch_id in s.last_batches:
                # transport retry of a batch this session already committed
                # (with pipelining the retry can be for ANY of the last K
                # batches, not just the newest): replay the stored response
                s.batch_replays += 1
                self.batch_replays += 1
                return s.last_batches[batch_id]
        pods = [from_wire(Pod, pw) for pw in req.get("pods", ())]
        tie_seeds = req.get("tieSeeds") or None
        # parent the whole server-side batch under the client's
        # scheduling.cycle span (W3C traceparent riding the request dict):
        # one trace then covers scheduler pop → wire → device commit
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.schedule_batch",
                                      batch=len(pods)):
            out = self._schedule_batch_traced(pods, tie_seeds,
                                              req.get("claims"),
                                              session_req=session_req,
                                              batch_id=batch_id)
        if batch_id:
            with self._lock:
                cur = self.sessions.get(session_req.get("clientId") or "")
                if cur is not None and not cur.fenced:
                    cur.cache_batch(batch_id, out)
        return out

    def _placement_fits(self, ni: NodeInfo, pod: Pod) -> bool:
        """Commit-time occupancy re-check of one proposed placement against
        the CURRENT mirror (content + holds), via the same fitsRequest the
        admission-time Filter runs — commit and filter can never disagree.
        The kernel judged against the same state under the same lock, so a
        miss here means the capacity raced between this client's sync and
        its batch — conflict, not double-bind."""
        from ..framework.plugins.noderesources import fits_request

        return not fits_request(pod.resource_request(), ni)

    def _validate_placements(self, cid: str, pods: List[Pod],
                             node_idx: np.ndarray,
                             slot_names: Dict[int, str],
                             batch_id=None) -> Dict[int, str]:  # ktpu: locked
        """Ownership check (lock held): every proposed placement is judged
        against current ownership and occupancy AT COMMIT TIME. Accepted
        placements become holds (overlaid into the mirror immediately, so
        later pods in this batch and every later batch from any client see
        the capacity taken); rejected ones return {batch index: reason} and
        are answered with a typed conflict verdict. Two replicas racing for
        the same pod or the same capacity can never both win."""
        conflicts: Dict[int, str] = {}
        for i, pod in enumerate(pods):
            idx = int(node_idx[i])
            if idx < 0 or idx not in slot_names:
                continue
            key = pod.key()
            node_name = slot_names[idx]
            bound = self._pod_nodes.get(key)
            if bound is not None:
                conflicts[i] = f"pod already bound on {bound}"
                continue
            hold = self.holds.get(key)
            if hold is not None and hold.owner != cid:
                conflicts[i] = (f"pod already committed by client "
                                f"{hold.owner!r}")
                continue
            ni = self.infos.get(node_name)
            if ni is None:
                conflicts[i] = f"node {node_name} left the mirror"
                continue
            if hold is not None:
                # the owner re-deciding its own pod (retry after a failed
                # host commit): surrender the old hold before re-checking
                old_ni = self.infos.get(hold.node_name)
                if old_ni is not None:
                    old_ni.remove_pod(hold.pod)
                del self.holds[key]
            if not self._placement_fits(ni, pod):
                conflicts[i] = (f"node {node_name} occupancy changed "
                                "(capacity raced)")
                continue
            ni.add_pod(pod)
            self.holds[key] = _Hold(pod, node_name, cid, batch_id=batch_id)
        if conflicts:
            self.commit_conflicts += len(conflicts)
            for i, reason in conflicts.items():
                telemetry.event("conflict", client=cid, batchId=batch_id,
                                pod=pods[i].key(), reason=reason)
        return conflicts

    def _schedule_batch_traced(self, pods: List[Pod], tie_seeds,
                               claims=None, session_req=None,
                               batch_id=None) -> dict:
        with self._lock:
            # re-validate the session at COMMIT time (the fencing-token
            # rule): a client fenced between accepting the request and
            # committing the batch must not mutate shared state
            s = self._session_for(session_req or {})
            s.batches += 1
            cid = s.client_id
            self._ensure_device()
            for _attempt in range(8):
                try:
                    with tracing.span("device.sync"):
                        self.device.sync(self.snap)
                    with tracing.span("device.encode", batch=len(pods)):
                        pb, et = self.device.encoder.encode_pods(
                            pods, tie_seeds=tie_seeds)
                        tb = self.device.sig_table.encode_topo(pods)
                    break
                except CapacityError as e:
                    self._grow(e)
            else:
                raise PermanentDeviceError(
                    "device capacities refuse to converge")
            host_pb = self.device.encoder.last_host_pb
            self.batch_counter += 1
            # sampling parity with the in-process batched path: explicit
            # percentage → exact rotating-window emulation; adaptive (0) →
            # full batch on accelerators, reference adaptive sample on CPU
            # (the tpu_scheduler._flush_batch rule)
            from ..scheduler.scheduler import num_feasible_nodes_to_find
            from .tpu_scheduler import _default_full_batch

            n_valid = len(self.infos)
            if self.percentage_of_nodes_to_score:
                k = num_feasible_nodes_to_find(n_valid,
                                               self.percentage_of_nodes_to_score)
            elif _default_full_batch():
                k = n_valid
            else:
                k = num_feasible_nodes_to_find(n_valid, 0)
            if k < n_valid:
                sample_k = np.int32(k)
                sample_start = (self._start_carry if self._start_carry is not None
                                else np.int32(0))
            else:
                sample_k = None
                sample_start = None
            # resource.k8s.io claims: the client ships pre-resolved selector
            # rows (it has the store; this process does not) and the mask
            # builds against THIS device's attribute table — the same
            # claim_feasibility_mask the in-process path dispatches
            dra_mask = None
            if claims:
                from .claim_mask import build_dra_mask, wire_claims_to_entries

                pad_to = len(host_pb["req"])
                dra_mask = build_dra_mask(
                    self.device, wire_claims_to_entries(claims), pad_to)
            # slice gangs: the server sees the actual Pod objects, so the
            # member bucketing mirrors the in-process _slice_batch_args and
            # the in-jit planner runs identically on both transports
            slice_members = slice_grid = None
            slice_groups: Dict[str, List[int]] = {}
            from ..framework.plugins.coscheduling import pod_group_key
            from ..ops.slice import is_slice_pod

            for i, pod in enumerate(pods):
                if is_slice_pod(pod):
                    gkey = pod_group_key(pod)
                    if gkey is not None:
                        slice_groups.setdefault(gkey, []).append(i)
            if slice_groups:
                from .claim_mask import _bucket

                g_cap = _bucket(len(slice_groups), floor=2)
                m_cap = _bucket(
                    max(len(v) for v in slice_groups.values()), floor=2)
                member_idx = np.full((g_cap, m_cap), -1, np.int32)
                member_valid = np.zeros((g_cap, m_cap), bool)
                for g, gkey in enumerate(slice_groups):
                    for m, i in enumerate(slice_groups[gkey]):
                        member_idx[g, m] = i
                        member_valid[g, m] = True
                slice_members = (member_idx, member_valid)
                slice_grid = (self.device.caps.superpods,
                              self.device.caps.sp_slots)
            bucket = int(getattr(pb, "capacity", len(pods)))
            # namespace-quota screen: sync the client-shipped ledger table
            # into this device and build the batch's ns/req columns — the
            # same builder the in-process dispatch uses, so both transports
            # screen identically
            quota_ns = quota_req = None
            if self.quota_table or self.device.nsq_slots:
                from ..ops.quota import build_quota_batch_args

                quota_ns, quota_req = build_quota_batch_args(
                    pods, self.device, table=self.quota_table,
                    pad_to=bucket)
            sig = f"{bucket}/" + (
                "general" if self.device.topo_enabled else "off")
            telemetry.event("dispatch", batchId=batch_id, client=cid,
                            epoch=self.epoch, bucket=bucket, sig=sig,
                            pods=len(pods))
            # deliberate blocking-under-lock: dispatch+commit must run against
            # exactly the synced mirror — releasing here would let a peer's
            # delta interleave between the kernel's view and the ownership
            # check, re-opening the double-bind window PR 6 closed
            locktrace.note_blocking(
                "device_dispatch", "DeviceService.schedule_batch",
                allowed="kernel must judge under the same lock as commit")
            with tracing.span("device.dispatch", batch=len(pods)):
                with telemetry.dispatch("schedule_batch", bucket=sig):
                    result = self.schedule_batch_fn(
                        pb, et, self.device.nt, self.device.tc, tb,
                        np.int32(self.batch_counter),
                        topo_enabled=self.device.topo_enabled,
                        sample_k=sample_k, sample_start=sample_start,
                        dra_mask=dra_mask, slice_members=slice_members,
                        slice_grid=slice_grid,
                        quota_ns=quota_ns, quota_req=quota_req,
                        quota_used=(self.device.nsq_used
                                    if quota_ns is not None else None),
                        quota_limit=(self.device.nsq_limit
                                     if quota_ns is not None else None))
            t_dispatch = self.now_fn()
            if result.final_sample_start is not None:
                self._start_carry = result.final_sample_start
            # adopt exactly like the in-process path: the client will assume
            # these placements; its next delta push re-encodes any row the
            # host view disagrees on and the content diff repairs it
            with tracing.span("device.commit", batch=len(pods),
                              packed="packed" if result.packed is not None
                              else "fallback"):
                # THE blocking read: the packed result block lands node_idx
                # AND first_fail in one materialization (the per-array reads
                # were one relay round-trip each on the TPU tunnel) — the
                # same commit-plane materializer the in-process commit runs
                from .commit_plane import materialize_profiled

                (node_idx, ff, slice_words, quota_words,
                 _), disp = materialize_profiled(
                    result, self.device.caps.nodes,
                    program="schedule_batch", bucket=sig,
                    t_submit=t_dispatch, now_fn=self.now_fn,
                    batch_id=batch_id, pods=len(pods),
                    quota_col=quota_ns is not None,
                    event_extra={"client": cid})
                self.device.adopt_device(result)
                self.device.adopt_commits(result, host_pb, node_idx)
            slot_names = self.device.slot_to_name()
            # ownership check: judge every proposed placement against
            # current ownership/occupancy; winners become holds (overlaid
            # into host truth so no later sync from a lagging replica can
            # erase them), losers get a typed conflict verdict. The device
            # arrays adopted the loser too — the next sync's content diff
            # repairs that row from the (hold-free) host truth, exactly the
            # PR-4 gang-surrender repair path.
            conflicts = self._validate_placements(cid, pods, node_idx,
                                                  slot_names,
                                                  batch_id=batch_id)
            if telemetry.get() is not None:
                # placed= is an O(batch) scan — keep it behind the enabled
                # check so the disabled hot path stays one global read
                extra = {}
                if disp is not None:
                    extra = {"device_ms": round(disp["execS"] * 1e3, 3),
                             "fetch_ms": round(disp["fetchS"] * 1e3, 3)}
                telemetry.event(
                    "commit", batchId=batch_id, client=cid, epoch=self.epoch,
                    bucket=bucket, pods=len(pods),
                    placed=int(sum(1 for i in range(len(pods))
                                   if int(node_idx[i]) >= 0
                                   and i not in conflicts)),
                    conflicts=len(conflicts), **extra)
            # device preemption screen for the batch's failures (ROADMAP
            # wire-hardening: hints ride back with unschedulable results so
            # the client's PostFilter skips hopeless candidates)
            screen = best = None
            if any(int(node_idx[i]) < 0 for i in range(len(pods))):
                try:
                    from ..ops.preempt import screen_prefix

                    self.device._refresh_class_prio()
                    with telemetry.dispatch("preempt_screen",
                                            bucket=str(bucket)):
                        pres = screen_prefix(pb, self.device.nt,
                                             result.static_masks,
                                             node_idx[:len(pods)] < 0)
                    screen = np.asarray(pres.screen)
                    best = np.asarray(pres.best)
                except Exception:  # noqa: BLE001 — hints are optional
                    screen = best = None
            results: List[dict] = []
            for i in range(len(pods)):
                idx = int(node_idx[i])
                if i in conflicts:
                    results.append({"nodeName": None, "conflict": True,
                                    "error": conflicts[i]})
                    continue
                if idx >= 0 and idx in slot_names:
                    results.append({"nodeName": slot_names[idx]})
                    continue
                if ff is None:  # packless (sharded-core) results only
                    ff = np.asarray(result.first_fail)
                # REAL slots only — padding slots fail the fit check and
                # would pollute the plugin attribution (queue gating)
                plugins = set()
                statuses = {}
                for slot, name in slot_names.items():
                    fid = int(ff[i][slot])
                    if fid > 0:
                        plugins.add(fid)
                        if len(statuses) < 64:  # payload-bounded sample
                            statuses[name] = _ATTRIBUTION_ORDER[fid - 1][0]
                r = {
                    "nodeName": None,
                    "unschedulablePlugins": [
                        _ATTRIBUTION_ORDER[fid - 1][0] for fid in sorted(plugins)],
                    "statuses": statuses,
                }
                if screen is not None:
                    all_cands = [name for slot, name in slot_names.items()
                                 if bool(screen[i][slot])]
                    best_name = (slot_names.get(int(best[i]))
                                 if best is not None and best[i] >= 0 else None)
                    if len(all_cands) <= 1024:
                        # an exact screen only: a truncated candidate list
                        # would wrongly mark the dropped nodes hopeless
                        # (defaultpreemption treats the screen as exact)
                        r["preempt"] = {"candidates": all_cands,
                                        "best": best_name}
                    elif best_name is not None:
                        # too many candidates to ship: the ranked best alone
                        # still helps (preferred-node fast path)
                        r["preempt"] = {"candidates": None, "best": best_name}
                results.append(r)
            if slice_words is not None and slice_groups:
                # ship each member's verdict word so the client can split
                # plan-infeasible from lost-in-flight without a second trip
                for idxs in slice_groups.values():
                    for i in idxs:
                        results[i]["slice"] = int(slice_words[i])
            if quota_words is not None:
                # every screened pod's quota verdict word rides back: the
                # client rejects flagged winners against its authoritative
                # ledger (screen staleness can only reject, never bind)
                for i in range(len(pods)):
                    w = int(quota_words[i])
                    if w:
                        results[i]["quota"] = w
            # stamp INSIDE the lock: epoch/deltaSeq are mutated by
            # concurrent apply_deltas calls from peer replicas — stamping
            # after release could pair this batch's results with a peer's
            # half-advanced deltaSeq (found by the locks pass)
            out = {"apiVersion": API_VERSION, "results": results,
                   "sessionGen": s.gen}
            if batch_id:
                # echo the idempotency key: a pipelined client matches
                # out-of-order replies to their requests by this id
                out["batchId"] = batch_id
            if disp is not None:
                # echo the server-side device time so the (pipelined)
                # client can attribute its round trip: device vs transport
                out["deviceTime"] = {
                    "dwellMs": round(disp["dwellS"] * 1e3, 3),
                    "execMs": round(disp["execS"] * 1e3, 3),
                    "fetchMs": round(disp["fetchS"] * 1e3, 3),
                    "deviceMs": round(
                        (disp["execS"] + disp["fetchS"]) * 1e3, 3),
                }
            return self._stamp(out)


# ---------------------------------------------------------------- transport


class ServiceBinding:
    """Mutable service slot behind a running server: the handler dispatches
    through it, so a crash-and-restart fault (or an operator restart) can
    swap in a FRESH DeviceService — new epoch, empty DeviceState — without
    tearing down the listener, exactly like a sidecar process restart
    behind a stable Service IP."""

    def __init__(self, service: DeviceService, fault_plan=None):
        self.service = service
        self.fault_plan = fault_plan
        self.restarts = 0

    def restart(self) -> DeviceService:
        old = self.service
        self.service = DeviceService(
            batch_size=old.batch_size,
            percentage_of_nodes_to_score=old.percentage_of_nodes_to_score,
            lease_ttl_s=old.lease_ttl_s, now_fn=old.now_fn)
        self.restarts += 1
        return self.service


_OPS = {"/v1/applyDeltas": "apply_deltas", "/v1/scheduleBatch": "schedule_batch",
        "/v1/health": "health", "/v1/heartbeat": "heartbeat",
        "/v1/sessions": "sessions_dump"}


class _Handler(BaseHTTPRequestHandler):
    binding: ServiceBinding = None  # set by serve()

    def log_message(self, *args):  # quiet
        pass

    def _json(self, code: int, out: dict) -> None:
        payload = json.dumps(out).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):  # noqa: N802 — stdlib naming
        op = _OPS.get(self.path)
        if op is None:
            self.send_error(404)
            return
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        plan = self.binding.fault_plan
        fault = plan.next_server(op) if plan is not None else None
        if fault is not None:
            if fault.kind == "crash":
                # the sidecar dies mid-request and supervision restarts it:
                # swap in a fresh service (new epoch, empty state) and sever
                # the connection — the client sees a reset, not a response
                self.binding.restart()
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            if fault.kind == "conflict":
                # scripted cross-client race: the 409-conflict body, so the
                # taxonomy tests can drive the client mapping without
                # staging a real two-replica collision
                self._json(409, {"error": "injected conflict",
                                 "conflict": True})
                return
            if fault.kind == "torn":
                # torn mid-stream disconnect: the request is PROCESSED (the
                # service's state advances — a batch commits, holds land)
                # but the reply never leaves. The client's transport retry
                # re-sends the same batchId and the idempotency cache
                # replays the committed result — the lost-response case.
                try:
                    getattr(self.binding.service, op)(body)
                except Exception:  # noqa: BLE001 — the reply is lost either way
                    pass
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            self._json(fault.status,
                       {"error": f"injected fault: {fault.kind}"})
            return
        try:
            out = getattr(self.binding.service, op)(body)
        except StaleEpochError as exc:
            # 409: the client must full-resync (distinct from 5xx so the
            # retry loop does not burn its budget re-sending stale deltas)
            self._json(409, {"error": str(exc), "staleEpoch": True,
                             "epoch": exc.epoch})
            return
        except ConflictError as exc:
            # 409 too, but a DIFFERENT 409: the state base is fine and a
            # resync cannot help — another client owns the pod/session.
            # The body's ``conflict`` flag is the discriminator.
            self._json(409, {"error": str(exc), "conflict": True})
            return
        except Exception as exc:  # noqa: BLE001 — wire errors must be JSON
            self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        self._json(200, out)


def serve(service: DeviceService, port: int = 0, fault_plan=None):
    """Start the HTTP binding on localhost; returns (server, port). The
    caller owns shutdown (server.shutdown()). ``server.binding`` exposes
    the live service slot (restartable; chaos tests script crashes through
    ``fault_plan``, a testing.faults.FaultPlan)."""
    binding = ServiceBinding(service, fault_plan=fault_plan)
    handler = type("BoundHandler", (_Handler,), {"binding": binding})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    server.binding = binding
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


class WireClient:
    """HTTP/JSON transport with the full fault story: split connect/read
    deadlines (a hung accept and a slow batch are different failures), the
    typed error taxonomy (backend/errors.py), and retry-with-backoff for
    transient failures inside the RetryPolicy's per-call deadline budget.
    ``fault_plan`` intercepts calls before the socket for deterministic
    chaos tests."""

    def __init__(self, endpoint: str, connect_timeout: float = 5.0,
                 read_timeout: float = 60.0, retry: Optional[RetryPolicy] = None,
                 fault_plan=None):
        self.endpoint = endpoint.rstrip("/")
        u = urllib.parse.urlsplit(self.endpoint)
        scheme = u.scheme or "http"
        if scheme not in ("http", "https") or not u.netloc:
            # a scheme-less endpoint ('127.0.0.1:5000', the gRPC form)
            # would silently parse as a PATH and hit port 80 forever —
            # loud error now beats permanent breaker-open later
            raise ValueError(
                f"device-service endpoint must be http(s)://host:port, "
                f"got {endpoint!r}")
        self._conn_cls = (http.client.HTTPSConnection if scheme == "https"
                          else http.client.HTTPConnection)
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if scheme == "https" else 80)
        self._base_path = u.path.rstrip("/")
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self.retry = retry if retry is not None else RetryPolicy()
        self.fault_plan = fault_plan

    def _do_post(self, path: str, data: bytes) -> dict:
        # socket IO must never run under a traced lock (a slow device
        # service would wedge whatever component held it)
        locktrace.note_blocking("http", path)
        conn = self._conn_cls(self._host, self._port,
                              timeout=self.connect_timeout)
        try:
            try:
                conn.connect()
                # connected: the remaining budget is the READ deadline
                conn.sock.settimeout(self.read_timeout)
                conn.request("POST", self._base_path + path, body=data,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                status = resp.status
                body = resp.read()
            except (ConnectionError, http.client.HTTPException, socket.timeout,
                    TimeoutError, OSError) as e:
                # refused/reset/timeout/torn response: the transient family
                raise TransientDeviceError(
                    f"device service unreachable: {type(e).__name__}: {e}") from e
        finally:
            conn.close()
        try:
            out = json.loads(body or b"{}")
        except ValueError as e:
            # classify by status first: a torn/HTML body on an
            # infrastructure 5xx is still the transient family
            if status in (502, 503, 504):
                raise TransientDeviceError(
                    f"device service {status}: non-JSON body") from e
            raise PermanentDeviceError(f"malformed device response: {e}") from e
        if status == 409 and out.get("staleEpoch"):
            raise StaleEpochError(out.get("epoch", ""), out.get("error", ""))
        if status == 409 and out.get("conflict"):
            raise ConflictError(out.get("error", "commit conflict"))
        if status in (502, 503, 504):
            # infrastructure-flavored 5xx (overload, proxy, restart in
            # progress) MAY clear: give the retry loop a chance before the
            # breaker counts it
            raise TransientDeviceError(
                f"device service {status}: {out.get('error', '')}")
        if status >= 400:
            # includes 500: the handler answers it only for a service-side
            # exception, which is deterministic — re-sending the identical
            # batch re-raises it (matches gRPC's UNKNOWN → permanent)
            raise PermanentDeviceError(
                f"device service {status}: {out.get('error', '')}")
        if "error" in out:
            raise PermanentDeviceError(out["error"])
        return out

    def _post(self, path: str, payload: dict, op: str) -> dict:
        data = json.dumps(payload).encode()

        def attempt():
            raise_injected_fault(self.fault_plan, op, self.read_timeout)
            return self._do_post(path, data)

        return self.retry.run(op, attempt)

    # the JSON transport is schema-free: claim rows ride the request as-is
    supports_dra = True
    supports_health = True
    supports_sessions = True

    def apply_deltas(self, payload: dict) -> dict:
        return self._post("/v1/applyDeltas", payload, "apply_deltas")

    def schedule_batch(self, payload: dict) -> dict:
        return self._post("/v1/scheduleBatch", payload, "schedule_batch")

    def health(self) -> dict:
        """The cheap identity/liveness verb (half-open probe)."""
        return self._post("/v1/health", {"apiVersion": API_VERSION}, "health")

    def heartbeat(self, payload: dict) -> dict:
        """Lease renewal for this client's session (HA topology)."""
        return self._post("/v1/heartbeat", payload, "heartbeat")

    def sessions_dump(self) -> dict:
        """Session-table introspection (/debug/sessions passthrough)."""
        return self._post("/v1/sessions", {"apiVersion": API_VERSION},
                          "sessions")


# ---------------------------------------------------------------- pipeline


class _WireInflight:
    """One wire batch submitted but whose reply has not been processed —
    the wire twin of tpu_scheduler._Inflight (a dispatched-but-uncommitted
    ring entry). ``payload`` is kept whole so a stale-epoch drain can
    re-send the identical logical batch (same idempotent batchId) after
    the resync."""

    __slots__ = ("qps", "payload", "batch_id", "pod_cycle", "t0", "t_sent",
                 "era")

    def __init__(self, qps: List[QueuedPodInfo], payload: dict,
                 pod_cycle: int, t0: float, t_sent: float, era: int):
        self.qps = qps
        self.payload = payload
        self.batch_id = payload["batchId"]
        self.pod_cycle = pod_cycle
        self.t0 = t0          # pop time: the attempt-latency clock
        self.t_sent = t_sent  # submit time: the sizer's service-span clock
        self.era = era        # sync era at submit (see _wire_sync_era)


class WirePipeline:
    """Concurrent transport lanes for the pipelined wire path: up to
    ``depth`` ScheduleBatch calls ride their own connections at once (the
    "second connection" form of the streaming channel), and every reply is
    deposited into a completion map keyed by the batchId the server echoes
    — so replies that arrive OUT OF ORDER, duplicated, or on the wrong
    lane (testing/faults.py ``reorder``/``dup_reply``) still route to
    exactly the in-flight batch they answer.

    Lane threads run ONLY transport work (``send_fn`` — the full
    retry/taxonomy client call); every scheduler-state mutation (commit,
    resync, requeue, breaker) stays on the scheduling thread, which blocks
    in ``claim`` for the batch it wants next. Lanes are spawned on demand
    and exit when the submit queue drains — no idle threads linger."""

    OP = "schedule_batch"

    def __init__(self, send_fn, depth: int, fault_plan=None):
        self._send = send_fn
        self.depth = max(1, int(depth))
        self.fault_plan = fault_plan
        self._cv = threading.Condition(locktrace.make_lock("WirePipeline"))
        self._submitted: Deque[dict] = deque()
        # batchId -> ("ok", reply) | ("err", exc); claimable while expected
        self._completions: Dict[str, tuple] = {}
        self._expected: set = set()
        self._lanes = 0
        self.duplicate_replies = 0  # late/duplicate/foreign deliveries dropped

    def submit(self, payload: dict) -> None:
        with self._cv:
            self._expected.add(payload["batchId"])
            self._submitted.append(payload)
            if self._lanes < self.depth:
                self._lanes += 1
                threading.Thread(target=self._lane, name="ktpu-wire-lane",
                                 daemon=True).start()

    def claim(self, batch_id: str, timeout: Optional[float] = None):
        """Block until the reply for ``batch_id`` arrives, then return it
        (or raise the transport error that ended its call). The wait is on
        the COMPLETION of that id, not on any particular lane — replies
        for newer batches landing first are simply left for their own
        claims (out-of-order tolerated by construction)."""
        with self._cv:
            self._cv.wait_for(
                lambda: batch_id in self._completions,  # ktpu: unguarded-ok(wait_for predicate is evaluated by Condition with its lock held)
                timeout=timeout)
            self._expected.discard(batch_id)
            outcome = self._completions.pop(batch_id, None)
        if outcome is None:
            raise TransientDeviceError(
                f"pipelined reply for batch {batch_id} never arrived")
        kind, value = outcome
        if kind == "err":
            raise value
        return value

    def inflight(self) -> int:
        with self._cv:
            return len(self._expected)

    # ------------------------------------------------------------ internals

    def _lane(self) -> None:
        while True:
            with self._cv:
                if not self._submitted:
                    self._lanes -= 1
                    return
                payload = self._submitted.popleft()
            sent_id = payload["batchId"]
            fault = (self.fault_plan.next_reply(self.OP)
                     if self.fault_plan is not None else None)
            try:
                out = self._send(payload)
            except BaseException as exc:  # noqa: BLE001 — routed, not raised here
                # transport errors carry no reply id: they belong to the
                # batch THIS lane was sending
                self._deposit(sent_id, ("err", exc))
                continue
            if (fault is not None and fault.kind == "reorder"
                    and fault.rendezvous is not None):
                # scripted cross-lane delivery: this lane receives the
                # OTHER call's reply — the router below must still pair it
                # with the right in-flight batch via the echoed batchId
                out = fault.rendezvous.swap(out)
            reply_id = out.get("batchId") or sent_id
            self._deposit(reply_id, ("ok", out))
            if fault is not None and fault.kind == "dup":
                self._deposit(reply_id, ("ok", out))  # duplicated delivery

    def _deposit(self, batch_id: str, outcome: tuple) -> None:
        with self._cv:
            if batch_id not in self._expected or batch_id in self._completions:
                # a reply nobody is (still) waiting on: a duplicate
                # delivery, a reply after its claim, or a foreign id —
                # dropping it is the only safe move (idempotent batchIds
                # mean the real reply was or will be processed exactly once)
                self.duplicate_replies += 1
                telemetry.event("pipeline_dup_reply", batchId=batch_id)
                return
            self._completions[batch_id] = outcome
            self._cv.notify_all()


# ---------------------------------------------------------------- scheduler


class WireScheduler(Scheduler):
    """Control plane driving the device service over the wire: the batched
    analog of the HTTP extender, with the same host machinery around it as
    TPUScheduler (queue order, assume/bind, failure handling + backoff)."""

    def __init__(self, *args, endpoint, batch_size: int = 256,
                 transport: str = "http",
                 connect_timeout: float = 5.0, read_timeout: float = 60.0,
                 wire_max_retries: int = 3, wire_backoff_base: float = 0.05,
                 wire_backoff_max: float = 2.0, wire_deadline_s: float = 90.0,
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 client_id: Optional[str] = None,
                 heartbeat_interval_s: float = 5.0,
                 fabric_probe_interval_s: float = 5.0,
                 wire_pipeline_depth: Optional[int] = None,
                 batch_deadline_ms: Optional[float] = None,
                 standby_replication: bool = True,
                 fault_plan=None, sleep_fn=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.retry_policy = RetryPolicy(
            max_retries=wire_max_retries, backoff_base=wire_backoff_base,
            backoff_max=wire_backoff_max, deadline_s=wire_deadline_s,
            sleep_fn=sleep_fn if sleep_fn is not None else time.sleep,
            now_fn=self.now_fn,
            on_retry=lambda op: self.smetrics.wire_retries.inc(op))
        # ``endpoint`` names one device service ("http://host:port"), a
        # comma-separated list, or a sequence — more than one enables the
        # device-side HA fabric (backend/fabric.py): primary/standby
        # selection with failover riding the epoch-resync machinery.
        # ``fault_plan`` may be a matching list for per-endpoint chaos.
        endpoints = ([e.strip() for e in endpoint.split(",") if e.strip()]
                     if isinstance(endpoint, str)
                     else [str(e) for e in endpoint])
        if not endpoints:
            raise ValueError("WireScheduler needs at least one endpoint")
        plans = (list(fault_plan) if isinstance(fault_plan, (list, tuple))
                 else [fault_plan] * len(endpoints))
        if len(plans) != len(endpoints):
            raise ValueError(
                f"fault_plan list ({len(plans)}) must match endpoints "
                f"({len(endpoints)})")
        if transport == "grpc":
            from .grpc_service import GrpcClient

            def make_client(ep, plan, retry=None):
                return GrpcClient(ep, read_timeout=read_timeout,
                                  retry=retry or self.retry_policy,
                                  fault_plan=plan)
        else:
            def make_client(ep, plan, retry=None):
                return WireClient(ep, connect_timeout=connect_timeout,
                                  read_timeout=read_timeout,
                                  retry=retry or self.retry_policy,
                                  fault_plan=plan)
        if len(endpoints) > 1:
            from .fabric import DeviceFabric

            # fabric health probes of maybe-dead replicas run on the
            # scheduling thread: a single-attempt probe client (no retry,
            # no backoff sleeps) bounds a blackholed standby's cost to one
            # connect timeout per probe window, not the full retry budget
            probe_retry = RetryPolicy(
                max_retries=0, backoff_base=wire_backoff_base,
                backoff_max=wire_backoff_max, deadline_s=wire_deadline_s,
                sleep_fn=sleep_fn if sleep_fn is not None else time.sleep,
                now_fn=self.now_fn)
            self.client = DeviceFabric(
                endpoints,
                lambda ep, i: make_client(ep, plans[i]),
                probe_client_factory=lambda ep, i: make_client(
                    ep, plans[i], retry=probe_retry),
                metrics=self.smetrics, now_fn=self.now_fn,
                probe_interval_s=fabric_probe_interval_s,
                # warm standbys: background delta fan-out so a promoted
                # standby resyncs O(dirty) instead of O(cluster)
                replication=standby_replication)
        else:
            # single-replica fast path: the plain transport client, zero
            # fabric indirection on the per-batch hot path
            self.client = make_client(endpoints[0], plans[0])
        self.batch_size = batch_size
        # circuit breaker + oracle degradation: N consecutive transport
        # failures open the breaker and every pod takes the sequential
        # oracle path until a half-open probe heals the wire (scheduling
        # never stops with a dead sidecar)
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s, now_fn=self.now_fn,
            on_state_change=self._on_breaker_state)
        self.smetrics.backend_circuit_state.set(value=0)
        self._degraded_since: Optional[float] = None
        self.degraded_pods = 0
        # state-resync protocol: last epoch the device answered with; a
        # mismatch (restart) surfaces as StaleEpochError → full resync
        self._device_epoch: Optional[str] = None
        self.resyncs = 0
        # idempotency keys for schedule_batch: one id per LOGICAL batch
        # (transport retries re-send the same id, so a server that already
        # committed replays its response instead of double-committing)
        self._batch_id_prefix = _new_epoch()
        self._batch_ids = itertools.count(1)
        self._sent_gens: Dict[str, int] = {}
        # names ever pushed to the CURRENT device base: the removal list is
        # computed from this set, not _sent_gens — _invalidate_node pops a
        # node's sent gen to force a re-send, and a node deleted in that
        # window would otherwise never be named in `removed` (a ghost row
        # on the service swept only by a full resync)
        self._pushed_nodes: set = set()
        self._sent_ns: Dict[str, dict] = {}
        # last quotaTable payload acknowledged by the service — change-
        # tracked whole (the table is tiny), like _sent_ns for labels
        self._sent_quota: Dict[str, dict] = {}
        self._batchable_cache: Dict[str, bool] = {}
        self.settle_abandoned = False
        # HA session: this replica's identity on the shared device service.
        # sessionGen is learned from the first response; a ConflictError
        # (fenced/zombie session, or a raced pod) never counts against the
        # breaker — the service is healthy, another replica just won.
        self.client_id = client_id or f"ktpu-{_new_epoch()}"
        self.heartbeat_interval_s = heartbeat_interval_s
        self._session_gen: Optional[int] = None
        self._last_heartbeat = self.now_fn()
        self.session_rejoins = 0
        self.ha_takeovers = 0
        # claim resolution for the wire dra_mask path (the builder only
        # reads the store; the mask itself builds server-side)
        from .claim_mask import ClaimMaskBuilder

        self._claim_masks = ClaimMaskBuilder(self.store)
        # ---- pipelined wire transport (ROADMAP item 2, wire half) ----
        # Up to K logical batches ride the wire at once, each on its own
        # connection lane, replies matched by the server-echoed batchId —
        # the wire twin of the in-process in-flight ring (_Inflight/
        # _drain_inflight): batch K's server-side device work overlaps
        # batch K-1's host commit AND the next pop/encode, instead of the
        # strictly request/response transport forfeiting the overlap.
        # Depth semantics mirror KTPU_PIPELINE_DEPTH; 0 = synchronous.
        # Default 3: the wire RTT is long relative to host work, so the
        # wire ring runs one deeper than the in-process default (bench
        # A/B: depth 3 > 2 > 0 on both transports at iso-conditions).
        if wire_pipeline_depth is None:
            if os.environ.get("KTPU_WIRE_PIPELINE", "1") == "0":
                wire_pipeline_depth = 0
            else:
                wire_pipeline_depth = max(0, int(os.environ.get(
                    "KTPU_WIRE_PIPELINE_DEPTH", "3")))
        self.wire_pipeline_depth = wire_pipeline_depth
        self._wire_inflight: Deque[_WireInflight] = deque()
        self._wire_pipeline: Optional[WirePipeline] = None
        if wire_pipeline_depth:
            # lanes run the raw transport call only (full retry/taxonomy);
            # every recovery move — resync, rejoin, requeue, breaker —
            # happens at claim time on the scheduling thread
            self._wire_pipeline = WirePipeline(
                self.client.schedule_batch, wire_pipeline_depth,
                fault_plan=plans[0] if len(endpoints) == 1 else None)
        self.pipelined_wire_batches = 0
        # sync ERA: bumped by every full resync and session rejoin. A
        # pipelined reply completed before the bump carries epoch/session
        # stamps of the pre-resync world — its RESULTS are valid (the
        # server committed them under a then-live session), but adopting
        # its stamps would regress the freshly-learned epoch/sessionGen
        self._wire_sync_era = 0
        # the stall-aware sizer, reused from the in-process ring: the
        # controlled quantity is the same pop→processed attempt latency,
        # and the claim-blocked residual feeds the stall model so the
        # batch size settles where wire round-trip time balances the
        # overlapped host window
        from .sizer import BatchSizer

        if batch_deadline_ms is None:
            batch_deadline_ms = float(os.environ.get(
                "KTPU_BATCH_DEADLINE_MS", "500"))
        self.wire_sizer = BatchSizer(batch_size, batch_deadline_ms / 1000.0)

    # ------------------------------------------------------- degraded mode

    def _on_breaker_state(self, old: str, new: str) -> None:
        self.smetrics.backend_circuit_state.set(value=STATE_VALUES[new])
        now = self.now_fn()
        if new == "open" and self._degraded_since is None:
            self._degraded_since = now
        elif new == "closed" and self._degraded_since is not None:
            self.smetrics.degraded_seconds.inc(value=now - self._degraded_since)
            self._degraded_since = None

    def _accrue_degraded(self) -> None:
        """Fold elapsed degraded time into the counter incrementally so a
        long-open breaker is visible before it heals."""
        if self._degraded_since is not None:
            now = self.now_fn()
            self.smetrics.degraded_seconds.inc(value=now - self._degraded_since)
            self._degraded_since = now

    def _wire_supported(self, pod: Pod) -> bool:
        """Same gating as TPUScheduler.batch_supported: the service runs the
        compiled DEFAULT plugin set — volume pods and custom profiles take
        the local sequential path. Claim pods ride the wire when every
        claim resolves AND the transport carries the dra_mask input
        (ROADMAP PR 1 follow-up: the request schema ships resolved
        selector rows; the server builds the mask against its own
        attribute table)."""
        if pod.spec.volumes:
            return False
        if pod.spec.resource_claims:
            if not getattr(self.client, "supports_dra", False):
                return False
            if not self._claim_masks.batchable(pod):
                return False
        fwk = self.framework_for_pod(pod)
        cached = self._batchable_cache.get(fwk.profile_name)
        if cached is None:
            from ..framework.registry import DEFAULT_PLUGINS

            cached = all(
                [(p.name(), w) for p, w in fwk.points.get(point, [])]
                == list(DEFAULT_PLUGINS.get(point, []))
                for point in ("pre_filter", "filter", "pre_score", "score")
            )
            self._batchable_cache[fwk.profile_name] = cached
        return cached

    def _build_entries(self, skip_unsent_check: bool = False):
        """(entries, pending_gens) over the current snapshot — the one wire
        shape for per-node deltas, shared by the incremental push and the
        full resync so the two payloads can never drift apart."""
        entries: List[dict] = []
        pending_gens: Dict[str, int] = {}
        for name, ni in self.snapshot.node_info_map.items():
            if ni.node is None:
                continue
            if not skip_unsent_check and self._sent_gens.get(name) == ni.generation:
                continue
            entries.append({
                "gen": ni.generation,
                "node": to_wire(ni.node),
                "pods": [to_wire(p) for p in ni.pods],
            })
            pending_gens[name] = ni.generation
        return entries, pending_gens

    def _push_deltas(self) -> None:
        """Incremental state sync. Bookkeeping (_sent_gens/_sent_ns) commits
        only AFTER the wire call succeeds: a failed push must leave the rows
        marked unsent, or the retry after recovery would skip them and the
        device mirror would silently diverge from host truth."""
        self.cache.update_snapshot(self.snapshot)
        current = self.snapshot.node_info_map
        removed = [n for n in self._pushed_nodes if n not in current]
        entries, pending_gens = self._build_entries()
        namespaces = {}
        for ns, obj in self.store.namespaces.items():
            labels = dict(obj.meta.labels)
            if self._sent_ns.get(ns) != labels:
                namespaces[ns] = labels
        quota_table = self._wire_quota_table()
        if not (entries or removed or namespaces) and quota_table is None:
            return
        payload = {"apiVersion": API_VERSION, "nodes": entries,
                   "removed": removed, "namespaces": namespaces}
        if quota_table is not None:
            payload["quotaTable"] = quota_table
        self._stamp_session(payload)
        self._stamp_inflight(payload)
        if self._device_epoch:
            payload["expectEpoch"] = self._device_epoch
        else:
            # epoch unknown = WE are the fresh process (client restart): a
            # surviving device may hold a mirror from our predecessor —
            # ghost nodes we cannot name in `removed` (_sent_gens is empty).
            # The first contact is therefore a FULL sync, establishing a
            # clean base exactly like the informer relist on startup.
            payload["full"] = True
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        try:
            out = self.client.apply_deltas(payload)
        except StaleEpochError as exc:
            # the device restarted under us: its mirror is a fresh empty
            # state — incremental deltas are meaningless against it
            self._full_resync(exc.epoch)
            return
        self._device_epoch = out.get("epoch", self._device_epoch)
        self._session_gen = out.get("sessionGen", self._session_gen)
        self._sent_gens.update(pending_gens)
        self._pushed_nodes.update(pending_gens)
        for n in removed:
            self._sent_gens.pop(n, None)
            self._pushed_nodes.discard(n)
        for ns, labels in namespaces.items():
            self._sent_ns[ns] = labels
        if quota_table is not None:
            self._sent_quota = quota_table

    def _wire_quota_table(self) -> Optional[Dict[str, dict]]:
        """The COMPLETE quota-ledger export for the device screen when it
        changed since the last acknowledged push, else None. Shipped whole
        (it is tiny — one used/limit row pair per quota'd namespace), so
        apply_deltas can treat every payload as the full desired state;
        limits already fold in borrowable cohort headroom."""
        plugin = self._quota_plugin()
        if plugin is None:
            return None
        table = {ns: {"used": list(used), "limit": list(limit)}
                 for ns, (used, limit)
                 in plugin.device_quota_table().items()}
        if table == self._sent_quota:
            return None
        return table

    def _full_resync(self, new_epoch: Optional[str] = None) -> None:
        """Epoch-mismatch recovery: forget everything we believe the device
        holds and ship the complete host truth as one ``full`` delta (the
        informer relist of the crash-only contract, pointed at the device)."""
        self.resyncs += 1
        self._wire_sync_era += 1
        self._sent_gens.clear()
        self._pushed_nodes.clear()
        self._sent_ns.clear()
        self._sent_quota = {}
        self._device_epoch = new_epoch
        # a new epoch = a new service INSTANCE: no session of ours survived
        # it. Stamping the dead incarnation's sessionGen would read as a
        # zombie (ConflictError) — rejoin fresh and learn the new gen from
        # the resync response.
        self._session_gen = None
        self.cache.update_snapshot(self.snapshot)
        entries, pending_gens = self._build_entries(skip_unsent_check=True)
        namespaces = {ns: dict(obj.meta.labels)
                      for ns, obj in self.store.namespaces.items()}
        payload = {"apiVersion": API_VERSION, "full": True, "nodes": entries,
                   "removed": [], "namespaces": namespaces}
        quota_table = self._wire_quota_table()
        if quota_table is not None:
            payload["quotaTable"] = quota_table
        self._stamp_session(payload)
        self._stamp_inflight(payload)
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        out = self.client.apply_deltas(payload)
        self._device_epoch = out.get("epoch", new_epoch)
        self._session_gen = out.get("sessionGen", self._session_gen)
        self._sent_gens.update(pending_gens)
        self._pushed_nodes.update(pending_gens)
        self._sent_ns.update(namespaces)
        if quota_table is not None:
            self._sent_quota = quota_table

    # ------------------------------------------------------------ HA session

    def _stamp_session(self, payload: dict) -> None:
        payload["clientId"] = self.client_id
        if self._session_gen is not None:
            payload["sessionGen"] = self._session_gen
        else:
            payload.pop("sessionGen", None)  # re-stamp after a rejoin

    def _stamp_inflight(self, payload: dict) -> None:
        """Name the batches whose replies this client has not yet processed
        (pipelined transport): the service must keep their commit holds
        alive through this push's owner-content reconciliation — our truth
        cannot include placements we have not seen yet."""
        if self._wire_inflight:
            payload["inflightBatchIds"] = [e.batch_id
                                           for e in self._wire_inflight]

    def _session_rejoin(self) -> None:
        """This incarnation was fenced (or superseded): forget the session
        AND everything we believe the service holds for us, so the next
        push re-establishes a fresh session with a full resync — the
        scheduler-side twin of the stale-epoch recovery."""
        self.session_rejoins += 1
        self._wire_sync_era += 1
        self._session_gen = None
        self._device_epoch = None
        self._sent_gens.clear()
        self._pushed_nodes.clear()
        self._sent_ns.clear()

    def _periodic_housekeeping(self, now: Optional[float] = None) -> None:
        super()._periodic_housekeeping(now)
        if not getattr(self.client, "supports_sessions", False):
            return
        if self.breaker.state == OPEN:
            # device presumed down: a heartbeat would just burn the retry
            # budget's backoff sleeps inside the degraded loop. The breaker
            # probe owns re-discovery; if our lease died meanwhile, the
            # first post-heal request gets fenced and rejoins.
            return
        now = self.now_fn()
        if (self.heartbeat_interval_s
                and now - self._last_heartbeat >= self.heartbeat_interval_s):
            self._last_heartbeat = now
            self._heartbeat()

    def _heartbeat(self) -> None:
        payload = {"apiVersion": API_VERSION}
        self._stamp_session(payload)
        try:
            out = self.client.heartbeat(payload)
        except ConflictError:
            self._session_rejoin()
            return
        except DeviceServiceError:
            return  # transport trouble: the breaker path owns the wire story
        self._session_gen = out.get("sessionGen", self._session_gen)
        self.smetrics.client_sessions.set(value=out.get("sessions", 1))
        for cid in out.get("fenced", ()):
            self.ha_takeovers += 1
            self.smetrics.ha_takeovers.inc()
            telemetry.event("takeover", client=self.client_id,
                            fencedPeer=cid)
            self._adopt_after_takeover(cid)

    def _adopt_after_takeover(self, dead_client: str) -> None:
        """A peer replica was fenced: its uncommitted capacity is already
        released server-side; adopt its orphaned queue slice. Unbound pods
        this replica is (now) responsible for but is not tracking re-enter
        the queue, and parked unschedulable pods get the capacity-freed
        wake-up (the fence released real capacity, like an assigned-pod
        delete)."""
        from ..queue import events as qevents

        pending = {qp.pod.key() for qp in self.queue.pending_pod_infos()}
        for pod in list(self.store.pods.values()):
            if pod.spec.node_name or not self._responsible_for(pod):
                continue
            key = pod.key()
            if key in pending or key in self.waiting_pods:
                continue
            self.queue.add(pod)
        self.queue.move_all_to_active_or_backoff_queue(
            qevents.SCHEDULER_TAKEOVER)

    def schedule_batch_cycle(self) -> int:
        if self.informer_factory is not None:
            self.informer_factory.pump()  # see TPUScheduler: the batched
            # loop pumps the informer bus exactly like schedule_one
        self._periodic_housekeeping()
        # the stall-aware sizer bounds the SYNCHRONOUS pop exactly like
        # the in-process ring's cycle (deadline-cut batches keep the
        # pop→processed p99 inside the budget). The PIPELINED pop takes
        # the full batch: the server serializes batches under its service
        # lock, so a pipelined batch's latency is dominated by its ~K-cycle
        # ring dwell — cutting the batch cannot shorten it (measured: the
        # deadline model collapses the target to min_batch and costs ~2.5x
        # wire throughput); the latency lever there is the DEPTH, and the
        # sizer keeps recording spans/waits as evidence.
        target = (self.batch_size if self._wire_pipeline is not None
                  else min(self.batch_size, self.wire_sizer.target()))
        qps = self.queue.pop_batch(target)
        if not qps:
            # nothing new to overlap with: land the in-flight wire batches
            # so their binds/failures settle before the caller judges
            # settlement (the ring's empty-pop drain, on the wire)
            self._drain_wire_inflight()
            return 0
        t0 = self.now_fn()
        pod_cycle = self.queue.scheduling_cycle
        buffer: List[QueuedPodInfo] = []
        for qp in qps:
            pod = self.store.get_pod(qp.pod.key())
            if pod is None or pod.spec.node_name or not self._responsible_for(pod):
                # deleted/bound meanwhile: drop the pop-opened ledger entry
                latency_ledger.close_skipped(qp.pod.key(), pod)
                continue
            qp.pod = pod
            # host-side gang quorum + namespace-quota gates (the remote
            # program models neither) — same rules as the in-process path
            from ..framework.plugins.coscheduling import gang_precheck_status
            from ..framework.plugins.quota import quota_precheck_status

            fwk = self.framework_for_pod(pod)
            quota_st = quota_precheck_status(fwk, pod)
            if quota_st is not None:
                self.metrics.inc("schedule_attempts")
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, quota_st,
                    Diagnosis(unschedulable_plugins={"QuotaAdmission"}),
                    pod_cycle)
                continue
            gang_st = gang_precheck_status(fwk, pod)
            if gang_st is not None:
                self.metrics.inc("schedule_attempts")
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, gang_st,
                    Diagnosis(unschedulable_plugins={"Coscheduling"}),
                    pod_cycle)
                continue
            if self._wire_supported(pod):
                buffer.append(qp)
                continue
            # strict pop order: flush the wire batch before a fallback pod so
            # a lower-priority local pod never jumps a batched one — and
            # land everything in flight first (same rule on the pipeline)
            self._flush_wire(buffer, pod_cycle, t0)
            buffer = []
            self._drain_wire_inflight()
            self.cache.update_snapshot(self.snapshot)
            self.schedule_one_pod(qp, pod_cycle)
        self._flush_wire(buffer, pod_cycle, t0)
        return len(qps)

    def _flush_wire(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        if not batch:
            return
        # one scheduling.cycle span per wire batch: the traceparent injected
        # below makes the server's device.sync/encode/dispatch/commit spans
        # children of this span — a single trace from pop to device commit
        with tracing.span("scheduling.cycle", batch=len(batch),
                          transport=type(self.client).__name__):
            self._flush_wire_traced(batch, pod_cycle, t0)

    def _flush_wire_traced(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        if not self.breaker.allow():
            # breaker open: the device is presumed down — land what is
            # already in flight (the entries fail with their own errors and
            # requeue), then route the whole batch through the sequential
            # oracle path (scheduling never stops); the next allow() past
            # the reset timeout probes
            self._drain_wire_inflight()
            self._accrue_degraded()
            self._schedule_degraded(batch, pod_cycle)
            return
        from .circuit import HALF_OPEN

        if (self.breaker.state == HALF_OPEN
                and getattr(self.client, "supports_health", False)):
            # half-open probe = the cheap health RPC, not a full batch
            # pushed through a maybe-dead service: a dead sidecar costs one
            # tiny request and this batch degrades immediately; a live one
            # answers in microseconds and the real push proceeds
            try:
                self.client.health()
            except DeviceServiceError as exc:
                self.breaker.record_failure(exc)  # half-open: re-opens
                self._accrue_degraded()
                self._schedule_degraded(batch, pod_cycle)
                return
        try:
            self._push_deltas()
            if self._wire_pipeline is not None:
                # pipelined: the batch rides a transport lane; replies are
                # claimed oldest-first once the ring exceeds its depth, so
                # K batches stay in flight across the wire while this
                # thread pops/encodes the next one
                payload = self._build_batch_payload(batch)
                entry = _WireInflight(batch, payload, pod_cycle, t0,
                                      self.now_fn(), self._wire_sync_era)
                self._wire_inflight.append(entry)
                if len(self._wire_inflight) > 1:
                    self.pipelined_wire_batches += 1
                self.smetrics.wire_inflight.set(
                    value=len(self._wire_inflight))
                # ledger: the batch rides a transport lane — device.inflight
                # dwell is the wire ring's K-cycle residency, correlated by
                # the idempotent batchId
                latency_ledger.transition_many(
                    [qp.pod.key() for qp in batch], "device.inflight",
                    batch_id=entry.batch_id)
                self._wire_pipeline.submit(payload)
                while len(self._wire_inflight) > self.wire_pipeline_depth:
                    self._drain_oldest_wire()
                return
            payload = self._build_batch_payload(batch)
            latency_ledger.transition_many(
                [qp.pod.key() for qp in batch], "device.inflight",
                batch_id=payload["batchId"])
            t_send = self.now_fn()
            res = self._send_batch_payload(payload)
        except ConflictError as exc:
            # fenced session / cross-client race: the service is HEALTHY, so
            # this never counts against the breaker. Rejoin under a fresh
            # session and give the pods back to the backoffQ — the next
            # attempt runs on a clean session against whatever the winning
            # replica left behind.
            self._wire_conflict(batch, exc, pod_cycle, t0)
            return
        except DeviceServiceError as exc:
            self._wire_transport_failure(batch, exc, pod_cycle, t0)
            return
        self.breaker.record_success()
        self._note_device_time(res, len(batch), payload["batchId"],
                               self.now_fn() - t_send)
        self._process_wire_results(batch, res, pod_cycle, t0)
        # feed the deadline model on the synchronous path too — it is the
        # mode whose pop the sizer actually cuts, so it must observe real
        # pop→processed spans (not run forever on its seeds)
        bucket = self.wire_sizer.bucket_for(len(batch))
        self.wire_sizer.update(bucket, self.now_fn() - t0)

    def _wire_conflict(self, batch: List[QueuedPodInfo], exc: Exception,
                       pod_cycle: int, t0: float) -> None:
        """Typed conflict verdict (fenced session / cross-client race):
        rejoin + backoffQ requeue, never a breaker count — identical for
        the synchronous path and a pipelined entry's claimed reply."""
        self.smetrics.commit_conflicts.inc(self.client_id)
        telemetry.event("conflict", client=self.client_id,
                        pods=len(batch), reason=str(exc)[:200])
        self._session_rejoin()
        self._requeue_wire_failure(batch, exc, pod_cycle, t0)

    def _wire_transport_failure(self, batch: List[QueuedPodInfo],
                                exc: Exception, pod_cycle: int,
                                t0: float,
                                batch_id: Optional[str] = None) -> None:
        """Transport-failure tail shared by both paths. Deliberately counts
        PERMANENT errors too: a deterministically broken device (version
        skew answering 4xx forever) should open the breaker and degrade to
        the oracle — the alternative is an endless requeue→fail loop with
        zero wire throughput. The breaker's lastError (/debug/circuit)
        keeps the bug visible."""
        self.breaker.record_failure(exc)
        if self.breaker.state == OPEN:
            # threshold crossed (or a failed half-open probe): degrade
            # THIS batch immediately rather than bouncing it off backoff
            self._accrue_degraded()
            self._schedule_degraded(batch, pod_cycle)
        else:
            # breaker still counting: rate-limited requeue — the pods
            # re-enter via the backoff queue with their attempt counts,
            # never hot-looping the active queue
            self._requeue_wire_failure(batch, exc, pod_cycle, t0,
                                       batch_id=batch_id)

    # ------------------------------------------------------ pipelined drain

    def _drain_wire_inflight(self) -> int:
        """Land every in-flight wire batch, oldest first — the wire twin of
        the ring's _drain_inflight: the synchronization point before
        fallback pods, degraded mode, and settlement judgment."""
        n = 0
        while self._wire_inflight:
            n += self._drain_oldest_wire()
        return n

    def _drain_oldest_wire(self) -> int:
        """Claim and process the OLDEST in-flight batch's reply. Replies
        arriving out of order are matched by batchId inside the pipeline's
        completion router; recovery (stale resync + re-send, conflict
        rejoin, breaker/requeue) runs here on the scheduling thread with
        semantics identical to the synchronous path."""
        entry = self._wire_inflight.popleft()
        self.smetrics.wire_inflight.set(value=len(self._wire_inflight))
        batch, pod_cycle, t0 = entry.qps, entry.pod_cycle, entry.t0
        t_wait0 = self.now_fn()
        try:
            try:
                res = self._wire_pipeline.claim(entry.batch_id)
                # adopt the reply's epoch/session only when no resync or
                # rejoin happened since this batch was SUBMITTED (the sync
                # era matches): an earlier entry's drain may have moved to
                # a fresh incarnation/session while this (older) reply was
                # already complete — re-adopting its stamps would cost a
                # spurious second full resync on the next push, or restore
                # a superseded sessionGen that then reads as a zombie
                if entry.era == self._wire_sync_era:
                    ep = res.get("epoch")
                    if ep:
                        self._device_epoch = ep
                        self._session_gen = res.get("sessionGen",
                                                    self._session_gen)
            except StaleEpochError as exc:
                # the device restarted (or a fabric failover promoted a
                # fresh standby) while this batch was in flight: re-seed
                # via the existing full resync — unless an earlier entry's
                # drain ALREADY resynced to exactly this epoch (K in-flight
                # batches all bounce off the same restart; one O(cluster)
                # resync suffices) — then re-send the SAME logical batch
                # (same idempotent batchId — nothing can double-commit)
                # through the bounded stale-retry loop
                if not (exc.epoch and exc.epoch == self._device_epoch):
                    self._full_resync(exc.epoch)
                self._restamp_batch_payload(entry.payload)
                res = self._send_batch_payload(entry.payload)
        except ConflictError as exc:
            self._wire_conflict(batch, exc, pod_cycle, t0)
            return len(batch)
        except DeviceServiceError as exc:
            # the in-flight batch died with its transport (replica loss,
            # torn stream, retry budget exhausted): the typed poison —
            # requeue via backoffQ exactly like in-process ring poison,
            # zero replays thanks to the per-client idempotent batchId
            telemetry.event("pipeline_poison", batchId=entry.batch_id,
                            pods=len(batch),
                            error=f"{type(exc).__name__}: {exc}"[:200])
            self._wire_transport_failure(batch, exc, pod_cycle, t0,
                                         batch_id=entry.batch_id)
            return len(batch)
        wait = self.now_fn() - t_wait0
        self.breaker.record_success()
        self._note_device_time(res, len(batch), entry.batch_id,
                               self.now_fn() - entry.t_sent)
        self._process_wire_results(batch, res, pod_cycle, t0)
        # stall-aware sizing, the in-process ring's controller: the span
        # fed is the batch's SERVICE time (submit → claimed), not its full
        # pop→processed attempt latency — a pipelined batch deliberately
        # dwells ~K cycles in the ring, and feeding that dwell into the
        # a+b·B fit reads as per-pod cost and collapses the target (a
        # measured 2.5x wire-throughput loss). The claim-blocked residual
        # still feeds the stall model, capping the batch where wire
        # latency outruns the overlapped host window.
        bucket = self.wire_sizer.bucket_for(len(batch))
        self.wire_sizer.update(bucket, self.now_fn() - entry.t_sent)
        self.wire_sizer.update_wait(bucket, wait)
        return len(batch)

    def _note_device_time(self, res: dict, pods: int, batch_id: str,
                          rtt_s: float) -> None:
        """Attribute the server-echoed per-batch device time against this
        client's round trip: the residual (rtt − server device time) is the
        TRANSPORT dwell — serialization, the wire, and (pipelined) ring
        residency — which no server-side profiler can see. One global read
        when the profiler is off or the server didn't echo (older server:
        degrade silently, same rule as every wire feature)."""
        rec = telemetry.get()
        if rec is None:
            return
        dt = res.get("deviceTime")
        if not isinstance(dt, dict):
            return
        try:
            exec_s = float(dt.get("execMs") or 0.0) / 1e3
            fetch_s = float(dt.get("fetchMs") or 0.0) / 1e3
            device_s = float(dt.get("deviceMs") or 0.0) / 1e3
        except (TypeError, ValueError):
            return
        transport_s = max(0.0, rtt_s - device_s)
        rec.dispatch_ledger.record_phases(
            "wire_schedule_batch", str(self.wire_sizer.bucket_for(pods)),
            dwell_s=transport_s, exec_s=exec_s, fetch_s=fetch_s,
            wait_s=max(rtt_s, device_s), batch_id=batch_id, pods=pods)
        telemetry.event("wire_device_time", batchId=batch_id,
                        device_ms=round(device_s * 1e3, 3),
                        transport_ms=round(transport_s * 1e3, 3))

    def _build_batch_payload(self, batch: List[QueuedPodInfo]) -> dict:
        """The ScheduleBatch request for one logical batch, stamped with a
        fresh idempotent batchId — the one payload shape shared by the
        synchronous path, the pipelined lanes, and stale-epoch re-sends."""
        from ..ops.tiebreak import seeds_for
        from .claim_mask import wire_claims_for_batch

        payload = {"apiVersion": API_VERSION,
                   "pods": [to_wire(qp.pod) for qp in batch],
                   "tieSeeds": [int(s) for s in seeds_for(batch)],
                   "batchId": f"{self._batch_id_prefix}-{next(self._batch_ids)}"}
        self._stamp_session(payload)
        claims = wire_claims_for_batch(self.store, [qp.pod for qp in batch])
        if claims:
            payload["claims"] = claims
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        if self._device_epoch:
            payload["expectEpoch"] = self._device_epoch
        return payload

    def _send_batch_payload(self, payload: dict) -> dict:
        """Send one batch payload with the bounded stale-epoch recovery
        loop; commits epoch/session learned from the response. Runs on the
        SCHEDULING thread only (resync/rejoin mutate scheduler state)."""
        # device restarted between the delta push and this batch (or again
        # mid-recovery — a crash-looping sidecar): each stale answer costs
        # one cheap full resync, bounded so a restart storm falls through to
        # the breaker instead of spinning here
        stale_retries = 0
        while True:
            try:
                res = self.client.schedule_batch(payload)
                break
            except StaleEpochError as exc:
                stale_retries += 1
                if stale_retries > 2:
                    raise
                self._full_resync(exc.epoch)
                self._restamp_batch_payload(payload)
        self._device_epoch = res.get("epoch", self._device_epoch)
        self._session_gen = res.get("sessionGen", self._session_gen)
        return res

    def _restamp_batch_payload(self, payload: dict) -> None:
        """Refresh a payload's epoch/session stamps after a resync or
        rejoin changed them (the batchId stays — same logical batch)."""
        if self._device_epoch:
            payload["expectEpoch"] = self._device_epoch
        else:
            payload.pop("expectEpoch", None)
        self._stamp_session(payload)

    def _schedule_degraded(self, batch: List[QueuedPodInfo], pod_cycle: int) -> None:
        telemetry.event("degrade", client=self.client_id, pods=len(batch),
                        reason="wire breaker open")
        self.degraded_pods += len(batch)
        self.cache.update_snapshot(self.snapshot)
        for qp in batch:
            self.schedule_one_pod(qp, pod_cycle)

    def _requeue_wire_failure(self, batch: List[QueuedPodInfo],
                              exc: Exception, pod_cycle: int, t0: float,
                              batch_id: Optional[str] = None) -> None:
        telemetry.event("requeue", client=self.client_id, pods=len(batch),
                        batchId=batch_id,
                        error=f"{type(exc).__name__}: {exc}"[:200])
        for qp in batch:
            fwk = self.framework_for_pod(qp.pod)
            self.metrics.inc("schedule_attempts")
            self.metrics.inc("errors")
            self.smetrics.observe_attempt(
                "error", fwk.profile_name, self.now_fn() - t0)
            self._handle_scheduling_failure(
                fwk, self._new_cycle_state(), qp,
                Status.error(f"device service: {exc}"), Diagnosis(), pod_cycle)

    def _invalidate_node(self, node_name: str) -> None:
        """Force ``node_name``'s row back through the delta channel: the
        device adopted a placement the host is rejecting, and the host
        generation did NOT advance (nothing was assumed), so without this
        the server would keep the phantom commit forever — its sync skips
        rows whose generation matches and its mirror already holds the
        adopted state. Bumping the cache generation makes the next push
        re-send host truth; the server's content diff then repairs the row
        (the wire twin of TPUScheduler's ``_uploaded_gen`` pop)."""
        from ..framework.types import next_generation

        with self.cache._lock:
            ni = self.cache.nodes.get(node_name)
            if ni is not None:
                ni.generation = next_generation()
                # the incremental snapshot walks the dirty set, not raw
                # generations — without this the bump is never revisited
                self.cache._dirty.add(node_name)
        self._sent_gens.pop(node_name, None)

    def _process_wire_results(self, batch: List[QueuedPodInfo], res: dict,
                              pod_cycle: int, t0: float) -> None:
        # the whole wire commit (binds + requeues) coalesces its queue
        # moves, and the winners land through the batched commit engine —
        # the same commit data plane the in-process path runs
        with self.queue.coalesce_moves():
            self._process_wire_results_coalesced(batch, res, pod_cycle, t0)

    def _process_wire_results_coalesced(self, batch: List[QueuedPodInfo],
                                        res: dict, pod_cycle: int,
                                        t0: float) -> None:
        from ..framework.plugins.coscheduling import pod_group_key
        from .commit_plane import BindItem

        # ledger: the reply is claimed — the batch leaves the wire ring and
        # enters the host commit tail
        latency_ledger.transition_many(
            [qp.pod.key() for qp in batch], "commit.host")

        bind_items: List[BindItem] = []
        # hint-screen scaffolding, shared by every failed pod in the batch
        hint_names = hint_slot_of = None
        # gang all-or-nothing: a gang with any unplaced member is rejected
        # WHOLE — placed members surrender their slots instead of parking a
        # partial gang at Permit (mirror of the in-process _judge_gangs)
        gang_rejected: Dict[int, str] = {}
        groups: Dict[str, List[int]] = {}
        slice_groups: Dict[str, List[int]] = {}
        from ..ops.slice import is_slice_pod
        from .batch import SLICE_PLAN_OK_BIT

        # device over-quota screen verdicts (echoed words): a flagged winner
        # surrenders its placement and requeues through the quota gate —
        # the host ledger stays authoritative, so staleness only retries
        from ..ops.quota import QUOTA_OK_BIT, QUOTA_SCREEN_BIT

        quota_rejected: set = set()
        for i, r in enumerate(res["results"]):
            w = int(r.get("quota") or 0)
            if (r.get("nodeName") and (w & QUOTA_SCREEN_BIT)
                    and not (w & QUOTA_OK_BIT)):
                quota_rejected.add(i)
        for i, qp in enumerate(batch):
            gkey = pod_group_key(qp.pod)
            if gkey is not None:
                if is_slice_pod(qp.pod):
                    slice_groups.setdefault(gkey, []).append(i)
                else:
                    groups.setdefault(gkey, []).append(i)
        for gkey, idxs in groups.items():
            # a quota-screened member is unlandable: all-or-nothing means
            # the whole gang surrenders (never half-admitted past quota)
            if any(not res["results"][i].get("nodeName")
                   or i in quota_rejected for i in idxs):
                for i in idxs:
                    gang_rejected[i] = gkey
                plugin = self.framework_for_pod(
                    batch[idxs[0]].pod).plugin("Coscheduling")
                if plugin is not None:
                    plugin.reject_gang(gkey, "incomplete")
        # slice gangs, the wire twin of _judge_slice_gangs: verdict from the
        # reply alone (every member placed ⟺ the pinned window landed), the
        # echoed verdict word splitting plan-infeasible from lost-in-flight
        for gkey, idxs in slice_groups.items():
            now = self.now_fn()
            if all(res["results"][i].get("nodeName") and i not in quota_rejected
                   for i in idxs):
                telemetry.event("slice_assign", client=self.client_id,
                                gang=gkey, members=len(idxs))
                self.smetrics.slice_wait_duration.observe(
                    now - t0, "scheduled")
                continue
            plan_ok = all(
                res["results"][i].get("slice", SLICE_PLAN_OK_BIT)
                & SLICE_PLAN_OK_BIT for i in idxs)
            reason = "incomplete" if plan_ok else "infeasible"
            telemetry.event("slice_reject", client=self.client_id,
                            gang=gkey, members=len(idxs), reason=reason)
            self.smetrics.slice_wait_duration.observe(now - t0, "rejected")
            for i in idxs:
                gang_rejected[i] = gkey
            fwk = self.framework_for_pod(batch[idxs[0]].pod)
            plugin = fwk.plugin("Coscheduling")
            if plugin is not None:
                plugin.reject_gang(gkey, reason)
            sp = fwk.plugin("SlicePacking")
            if sp is not None:
                # a rejected gang's oracle plan (if any) must not keep its
                # node reservations pinned across the retry
                sp.forget_gang(gkey)
        for i, (qp, r) in enumerate(zip(batch, res["results"])):
            fwk = self.framework_for_pod(qp.pod)
            self.metrics.inc("schedule_attempts")
            node_name = r.get("nodeName")
            if r.get("conflict") and i not in gang_rejected:
                # another replica owns the pod (or won the capacity): the
                # typed verdict maps to a rate-limited backoffQ requeue —
                # by the retry either the winner's bind is visible (pod
                # skipped at pop) or this replica gets a clean shot
                self.smetrics.commit_conflicts.inc(self.client_id)
                telemetry.event("conflict", client=self.client_id,
                                pod=qp.pod.key(),
                                reason=(r.get("error") or "raced")[:200])
                self.metrics.inc("errors")
                self.smetrics.observe_attempt(
                    "error", fwk.profile_name, self.now_fn() - t0)
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp,
                    Status.error(
                        f"commit conflict: {r.get('error') or 'raced'}"),
                    Diagnosis(), pod_cycle)
                continue
            if i in gang_rejected:
                if node_name:
                    # the device already adopted this member's placement;
                    # surrendering it must re-send the node's host truth
                    self._invalidate_node(node_name)
                d = Diagnosis(unschedulable_plugins={"Coscheduling"})
                d.unschedulable_plugins.update(
                    r.get("unschedulablePlugins") or ())
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, Status.unschedulable(
                        f'gang "{gang_rejected[i]}" could not be fully '
                        "placed"), d, pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                continue
            if i in quota_rejected:
                # the device adopted the placement before the screen flagged
                # it: surrender the slot and requeue through the quota gate
                # (host ledger re-admits once headroom is real)
                from ..framework.plugins.quota import ERR_REASON_QUOTA_EXCEEDED
                if node_name:
                    self._invalidate_node(node_name)
                self._handle_scheduling_failure(
                    fwk, self._new_cycle_state(), qp, Status.unresolvable(
                        f'{ERR_REASON_QUOTA_EXCEEDED}: namespace '
                        f'"{qp.pod.meta.namespace}" over quota at decision '
                        "time (device screen)"),
                    Diagnosis(unschedulable_plugins={"QuotaAdmission"}),
                    pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
                continue
            if node_name:
                if self.snapshot.get(node_name) is None:
                    # ghost placement: the device named a node the host no
                    # longer knows (a desync window the resync protocol
                    # hasn't closed yet) — error-requeue the pod instead of
                    # binding it to a nonexistent node
                    self.metrics.inc("errors")
                    self.smetrics.observe_attempt(
                        "error", fwk.profile_name, self.now_fn() - t0)
                    self._handle_scheduling_failure(
                        fwk, self._new_cycle_state(), qp,
                        Status.error(f"device placed pod on unknown node "
                                     f"{node_name}"), Diagnosis(), pod_cycle)
                    continue
                state = self._new_cycle_state()
                if qp.pod.spec.resource_claims or qp.pod.spec.volumes:
                    # Reserve allocates claims from PreFilter cycle state
                    # (and re-verifies the claims still exist) — exactly
                    # the in-process commit rule
                    _, pre_st = fwk.run_pre_filter_plugins(state, qp.pod)
                    if not pre_st.is_success():
                        # host rejected what the device adopted: re-send
                        # the node's truth on the next push
                        self._invalidate_node(node_name)
                        self.cache.update_snapshot(self.snapshot)
                        self.schedule_one_pod(qp, pod_cycle)
                        continue
                bind_items.append(BindItem(fwk, qp, qp.pod, node_name, state))
            else:
                d = Diagnosis()
                for name, plugin in (r.get("statuses") or {}).items():
                    reason = dict(_ATTRIBUTION_ORDER).get(plugin, "unschedulable")
                    d.node_to_status[name] = Status.unschedulable(reason).with_plugin(plugin)
                d.unschedulable_plugins.update(r.get("unschedulablePlugins") or ())
                state = self._new_cycle_state()
                hint = r.get("preempt")
                if hint is not None:
                    # rebuild the screen over OUR node names: candidates the
                    # service listed pass, every other known node fails,
                    # unknown (post-snapshot) nodes stay permissive. A None
                    # candidate list means the service truncated (screen
                    # inexact): pass everything and keep only the ranked
                    # best as the preferred-node fast path.
                    from ..framework.plugins.defaultpreemption import DefaultPreemption

                    if hint_slot_of is None:  # loop-invariant: build once
                        hint_names = list(self._sent_gens)
                        hint_slot_of = {n: i for i, n in enumerate(hint_names)}
                    if hint.get("candidates") is None:
                        row = np.ones(len(hint_names), bool)
                    else:
                        row = np.zeros(len(hint_names), bool)
                        for n in hint["candidates"]:
                            if n in hint_slot_of:
                                row[hint_slot_of[n]] = True
                    state.write(DefaultPreemption.HINTS_KEY,
                                (row, hint_slot_of, hint.get("best")))
                self._handle_scheduling_failure(
                    fwk, state, qp, Status.unschedulable("no feasible node"),
                    d, pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)
        if bind_items:
            self.commit_plane.commit_bindings(bind_items, pod_cycle, t0)
            for item in bind_items:
                if item.outcome == "failed":
                    # host rejected what the device adopted: re-send the
                    # node's truth on the next push
                    self._invalidate_node(item.node_name)

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                          idle_wait: float = 0.005, max_no_progress: int = 200) -> int:
        # the shared batched settle loop (Scheduler.run_batched_until_settled),
        # incl. the idle-wait backoff for flapping pods
        return self.run_batched_until_settled(
            max_cycles=max_cycles, flush=flush, idle_wait=idle_wait,
            max_no_progress=max_no_progress)

    def debug_sessions(self) -> dict:
        """/debug/sessions body: this replica's session identity plus the
        device service's whole session table (lease ages, per-client
        deltaSeq, in-flight hold counts) fetched over the wire."""
        out = {
            "enabled": True,
            "clientId": self.client_id,
            "sessionGen": self._session_gen,
            "sessionRejoins": self.session_rejoins,
            "haTakeovers": self.ha_takeovers,
            "heartbeatIntervalS": self.heartbeat_interval_s,
        }
        if getattr(self.client, "supports_sessions", False):
            try:
                out["service"] = self.client.sessions_dump()
            except DeviceServiceError as exc:
                out["service"] = {"error": f"{type(exc).__name__}: {exc}"}
        else:
            out["service"] = {"error": "transport lacks the sessions verb"}
        return out

    def debug_fabric(self) -> dict:
        """/debug/fabric body: the device-side HA fabric's replica table
        (active endpoint, per-endpoint health/breaker/epoch) plus the
        bounded failover journal; a single-endpoint transport reports
        enabled=False (no fabric in the path)."""
        dump = getattr(self.client, "dump", None)
        if dump is None:
            return {"enabled": False,
                    "endpoint": getattr(self.client, "endpoint", None)}
        return dump()

    def debug_circuit(self) -> dict:
        """/debug/circuit body: breaker state + resync/degradation story +
        the pipelined-transport occupancy."""
        out = self.breaker.dump()
        out.update({
            "enabled": True,
            "deviceEpoch": self._device_epoch,
            "resyncs": self.resyncs,
            "degradedPods": self.degraded_pods,
            "wirePipelineDepth": self.wire_pipeline_depth,
            "wireInflight": len(self._wire_inflight),
            "pipelinedBatches": self.pipelined_wire_batches,
            "duplicateReplies": (self._wire_pipeline.duplicate_replies
                                 if self._wire_pipeline is not None else 0),
            "retryPolicy": {
                "maxRetries": self.retry_policy.max_retries,
                "backoffBase": self.retry_policy.backoff_base,
                "backoffMax": self.retry_policy.backoff_max,
                "deadlineS": self.retry_policy.deadline_s,
            },
        })
        return out
