"""The batched device service seam (SURVEY §5.8 hop 6).

The reference's only out-of-process scheduling extension is the per-pod JSON
extender webhook (extender.go:42,247) — one HTTP POST per pod per extender,
which is exactly its performance failure. This service batches and adds
state: the control plane streams generation-keyed node deltas
(``ApplyDeltas``) and submits whole pod micro-batches (``ScheduleBatch``);
the device side keeps the encoded mirror across calls, so steady-state
requests carry only dirty rows and the pod batch.

Three pieces:
  * ``DeviceService`` — transport-agnostic server core owning a DeviceState
    and the compiled batch program; the hot path mirrors TPUScheduler's
    device half (delta sync, capacity growth, adopt-on-dispatch).
  * ``serve``/``DeviceServiceHTTP`` — stdlib HTTP/JSON binding on localhost
    (the in-process path stays the fast mode; this seam exists to measure
    and bound the serialization/transport cost the reference pays at
    QPS-5000, scheduler_perf util.go:86-90).
  * ``WireScheduler`` — a Scheduler whose filter/score middle goes over the
    wire; queue/cache/assume/bind/failure handling stay the same host
    machinery (the north-star seam: the control plane does not know whether
    the backend is in-process or remote).

Wire envelope: {"apiVersion": "ktpu/v1", ...}; objects use api/codec.py.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace
from typing import Dict, List, Optional

import numpy as np

from ..api.codec import from_wire, to_wire
from ..api.types import Node, Pod
from ..framework.types import Diagnosis, NodeInfo, QueuedPodInfo
from ..framework.interface import CycleState, Status
from ..ops.encode import CapacityError
from ..scheduler.scheduler import Scheduler
from ..utils import tracing
from .batch import build_schedule_batch_fn
from .device_state import DeviceState, caps_for_cluster
from .tpu_scheduler import _ATTRIBUTION_ORDER, TPUScheduler

API_VERSION = "ktpu/v1"


class DeviceService:
    """Server core: node mirror + device state + one compiled batch program."""

    def __init__(self, batch_size: int = 512,
                 percentage_of_nodes_to_score: int = 0):
        self.batch_size = batch_size
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.infos: Dict[str, NodeInfo] = {}
        # duck-typed Snapshot: the wire service mirrors nodes wholesale per
        # delta, so every sync is a "structure changed" full walk — the
        # changed_names/structure_version fields exist only to satisfy
        # DeviceState's O(changes) bookkeeping (a fresh version each sync
        # forces the full path, which is correct here)
        self.snap = SimpleNamespace(node_info_map=self.infos,
                                    changed_names=set(), structure_version=0)
        self.ns_labels: Dict[str, Dict[str, str]] = {}
        self.device: Optional[DeviceState] = None
        self.schedule_batch_fn = build_schedule_batch_fn()
        self.batch_counter = 0
        self._start_carry = None  # adaptive-sampling rotation (device scalar)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- deltas

    def apply_deltas(self, req: dict) -> dict:
        # server half of W3C-traceparent propagation: the delta sync parents
        # under the client's scheduling.cycle span (no-op, one global read,
        # when tracing is disabled)
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.apply_deltas",
                                      nodes=len(req.get("nodes", ()))):
            return self._apply_deltas_traced(req)

    def _apply_deltas_traced(self, req: dict) -> dict:
        with self._lock:
            if req.get("full"):
                self.infos.clear()
                self.device = None
            for e in req.get("nodes", ()):
                node = from_wire(Node, e["node"])
                ni = NodeInfo(node)
                for pw in e.get("pods", ()):
                    ni.add_pod(from_wire(Pod, pw))
                ni.generation = e.get("gen", ni.generation)
                self.infos[node.meta.name] = ni
            for name in req.get("removed", ()):
                self.infos.pop(name, None)
            # namespace labels ride along so namespaceSelector terms match
            # identically to the in-process path (sig_table ns_labels_fn)
            for ns, labels in (req.get("namespaces") or {}).items():
                self.ns_labels[ns] = dict(labels)
            self._sync()
            return {"apiVersion": API_VERSION, "nodes": len(self.infos)}

    def _ensure_device(self) -> None:
        import dataclasses

        n = max(len(self.infos), 1)
        ns_fn = lambda ns: self.ns_labels.get(ns, {})  # noqa: E731
        if self.device is None:
            self.device = DeviceState(caps_for_cluster(n, batch=self.batch_size),
                                      ns_labels_fn=ns_fn)
        elif self.device.caps.nodes < n:
            caps = self.device.caps
            nodes = caps.nodes
            while nodes < n:
                nodes *= 2
            self.device = DeviceState(dataclasses.replace(
                caps, nodes=nodes,
                value_words=max(caps.value_words, (nodes + 2 + 31) // 32)),
                ns_labels_fn=ns_fn)

    def _sync(self) -> None:
        self._ensure_device()
        for _attempt in range(8):
            try:
                with tracing.span("device.sync"):
                    self.device.sync(self.snap)
                return
            except CapacityError as e:
                self._grow(e)
        raise RuntimeError("device capacities refuse to converge")

    def _grow(self, err: CapacityError) -> None:
        import dataclasses

        caps = self.device.caps
        fields = TPUScheduler._GROW_FIELDS.get(err.dimension)
        if fields is None and err.dimension.startswith("value vocab"):
            fields = ("value_words",)
        if fields is None:
            raise RuntimeError(f"unknown capacity dimension {err.dimension!r}") from err
        updates = {}
        for f in fields:
            v = getattr(caps, f)
            while v < err.needed:
                v *= 2
            updates[f] = v
        self.device = DeviceState(
            dataclasses.replace(caps, **updates),
            ns_labels_fn=lambda ns: self.ns_labels.get(ns, {}))

    # ------------------------------------------------------------- schedule

    def schedule_batch(self, req: dict) -> dict:
        pods = [from_wire(Pod, pw) for pw in req.get("pods", ())]
        tie_seeds = req.get("tieSeeds") or None
        # parent the whole server-side batch under the client's
        # scheduling.cycle span (W3C traceparent riding the request dict):
        # one trace then covers scheduler pop → wire → device commit
        with tracing.span_from_remote(req.get("traceparent"),
                                      "device.schedule_batch",
                                      batch=len(pods)):
            return self._schedule_batch_traced(pods, tie_seeds)

    def _schedule_batch_traced(self, pods: List[Pod], tie_seeds) -> dict:
        with self._lock:
            self._ensure_device()
            for _attempt in range(8):
                try:
                    with tracing.span("device.sync"):
                        self.device.sync(self.snap)
                    with tracing.span("device.encode", batch=len(pods)):
                        pb, et = self.device.encoder.encode_pods(
                            pods, tie_seeds=tie_seeds)
                        tb = self.device.sig_table.encode_topo(pods)
                    break
                except CapacityError as e:
                    self._grow(e)
            else:
                raise RuntimeError("device capacities refuse to converge")
            host_pb = self.device.encoder.last_host_pb
            self.batch_counter += 1
            # sampling parity with the in-process batched path: explicit
            # percentage → exact rotating-window emulation; adaptive (0) →
            # full batch on accelerators, reference adaptive sample on CPU
            # (the tpu_scheduler._flush_batch rule)
            from ..scheduler.scheduler import num_feasible_nodes_to_find
            from .tpu_scheduler import _default_full_batch

            n_valid = len(self.infos)
            if self.percentage_of_nodes_to_score:
                k = num_feasible_nodes_to_find(n_valid,
                                               self.percentage_of_nodes_to_score)
            elif _default_full_batch():
                k = n_valid
            else:
                k = num_feasible_nodes_to_find(n_valid, 0)
            if k < n_valid:
                sample_k = np.int32(k)
                sample_start = (self._start_carry if self._start_carry is not None
                                else np.int32(0))
            else:
                sample_k = None
                sample_start = None
            with tracing.span("device.dispatch", batch=len(pods)):
                result = self.schedule_batch_fn(
                    pb, et, self.device.nt, self.device.tc, tb,
                    np.int32(self.batch_counter),
                    topo_enabled=self.device.topo_enabled,
                    sample_k=sample_k, sample_start=sample_start)
            if result.final_sample_start is not None:
                self._start_carry = result.final_sample_start
            # adopt exactly like the in-process path: the client will assume
            # these placements; its next delta push re-encodes any row the
            # host view disagrees on and the content diff repairs it
            with tracing.span("device.commit", batch=len(pods)):
                node_idx = np.asarray(result.node_idx)  # THE blocking read
                self.device.adopt_device(result)
                self.device.adopt_commits(result, host_pb, node_idx)
            slot_names = self.device.slot_to_name()
            # device preemption screen for the batch's failures (ROADMAP
            # wire-hardening: hints ride back with unschedulable results so
            # the client's PostFilter skips hopeless candidates)
            screen = best = None
            if any(int(node_idx[i]) < 0 for i in range(len(pods))):
                try:
                    from ..ops.preempt import screen_prefix

                    self.device._refresh_class_prio()
                    pres = screen_prefix(pb, self.device.nt,
                                         result.static_masks,
                                         node_idx[:len(pods)] < 0)
                    screen = np.asarray(pres.screen)
                    best = np.asarray(pres.best)
                except Exception:  # noqa: BLE001 — hints are optional
                    screen = best = None
            ff = None
            results: List[dict] = []
            for i in range(len(pods)):
                idx = int(node_idx[i])
                if idx >= 0 and idx in slot_names:
                    results.append({"nodeName": slot_names[idx]})
                    continue
                if ff is None:
                    ff = np.asarray(result.first_fail)
                # REAL slots only — padding slots fail the fit check and
                # would pollute the plugin attribution (queue gating)
                plugins = set()
                statuses = {}
                for slot, name in slot_names.items():
                    fid = int(ff[i][slot])
                    if fid > 0:
                        plugins.add(fid)
                        if len(statuses) < 64:  # payload-bounded sample
                            statuses[name] = _ATTRIBUTION_ORDER[fid - 1][0]
                r = {
                    "nodeName": None,
                    "unschedulablePlugins": [
                        _ATTRIBUTION_ORDER[fid - 1][0] for fid in sorted(plugins)],
                    "statuses": statuses,
                }
                if screen is not None:
                    all_cands = [name for slot, name in slot_names.items()
                                 if bool(screen[i][slot])]
                    best_name = (slot_names.get(int(best[i]))
                                 if best is not None and best[i] >= 0 else None)
                    if len(all_cands) <= 1024:
                        # an exact screen only: a truncated candidate list
                        # would wrongly mark the dropped nodes hopeless
                        # (defaultpreemption treats the screen as exact)
                        r["preempt"] = {"candidates": all_cands,
                                        "best": best_name}
                    elif best_name is not None:
                        # too many candidates to ship: the ranked best alone
                        # still helps (preferred-node fast path)
                        r["preempt"] = {"candidates": None, "best": best_name}
                results.append(r)
        return {"apiVersion": API_VERSION, "results": results}


# ---------------------------------------------------------------- transport


class _Handler(BaseHTTPRequestHandler):
    service: DeviceService = None  # set by serve()

    def log_message(self, *args):  # quiet
        pass

    def do_POST(self):  # noqa: N802 — stdlib naming
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        try:
            if self.path == "/v1/applyDeltas":
                out = self.service.apply_deltas(body)
            elif self.path == "/v1/scheduleBatch":
                out = self.service.schedule_batch(body)
            else:
                self.send_error(404)
                return
        except Exception as exc:  # noqa: BLE001 — wire errors must be JSON
            payload = json.dumps({"error": f"{type(exc).__name__}: {exc}"}).encode()
            self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        payload = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


def serve(service: DeviceService, port: int = 0):
    """Start the HTTP binding on localhost; returns (server, port). The
    caller owns shutdown (server.shutdown())."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


class WireClient:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.endpoint + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # surface the handler's JSON diagnostic, not the bare status line
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:  # noqa: BLE001
                detail = ""
            raise RuntimeError(f"device service {e.code}: {detail}") from e
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def apply_deltas(self, payload: dict) -> dict:
        return self._post("/v1/applyDeltas", payload)

    def schedule_batch(self, payload: dict) -> dict:
        return self._post("/v1/scheduleBatch", payload)


# ---------------------------------------------------------------- scheduler


class WireScheduler(Scheduler):
    """Control plane driving the device service over the wire: the batched
    analog of the HTTP extender, with the same host machinery around it as
    TPUScheduler (queue order, assume/bind, failure handling + backoff)."""

    def __init__(self, *args, endpoint: str, batch_size: int = 256,
                 transport: str = "http", **kwargs):
        super().__init__(*args, **kwargs)
        if transport == "grpc":
            from .grpc_service import GrpcClient

            self.client = GrpcClient(endpoint)
        else:
            self.client = WireClient(endpoint)
        self.batch_size = batch_size
        self._sent_gens: Dict[str, int] = {}
        self._sent_ns: Dict[str, dict] = {}
        self._batchable_cache: Dict[str, bool] = {}
        self.settle_abandoned = False

    def _wire_supported(self, pod: Pod) -> bool:
        """Same gating as TPUScheduler.batch_supported: the service runs the
        compiled DEFAULT plugin set — volume pods, resource.k8s.io claim
        pods (the wire protocol carries no dra_mask yet), and custom
        profiles take the local sequential path."""
        if pod.spec.volumes or pod.spec.resource_claims:
            return False
        fwk = self.framework_for_pod(pod)
        cached = self._batchable_cache.get(fwk.profile_name)
        if cached is None:
            from ..framework.registry import DEFAULT_PLUGINS

            cached = all(
                [(p.name(), w) for p, w in fwk.points.get(point, [])]
                == list(DEFAULT_PLUGINS.get(point, []))
                for point in ("pre_filter", "filter", "pre_score", "score")
            )
            self._batchable_cache[fwk.profile_name] = cached
        return cached

    def _push_deltas(self) -> None:
        self.cache.update_snapshot(self.snapshot)
        entries = []
        current = self.snapshot.node_info_map
        removed = [n for n in self._sent_gens if n not in current]
        for name, ni in current.items():
            if self._sent_gens.get(name) == ni.generation or ni.node is None:
                continue
            entries.append({
                "gen": ni.generation,
                "node": to_wire(ni.node),
                "pods": [to_wire(p) for p in ni.pods],
            })
            self._sent_gens[name] = ni.generation
        for n in removed:
            del self._sent_gens[n]
        namespaces = {}
        for ns, obj in self.store.namespaces.items():
            labels = dict(obj.meta.labels)
            if self._sent_ns.get(ns) != labels:
                namespaces[ns] = labels
                self._sent_ns[ns] = labels
        if entries or removed or namespaces:
            payload = {"apiVersion": API_VERSION, "nodes": entries,
                       "removed": removed, "namespaces": namespaces}
            tp = tracing.format_traceparent()
            if tp:
                payload["traceparent"] = tp
            self.client.apply_deltas(payload)

    def schedule_batch_cycle(self) -> int:
        self._periodic_housekeeping()
        qps = self.queue.pop_batch(self.batch_size)
        if not qps:
            return 0
        t0 = self.now_fn()
        pod_cycle = self.queue.scheduling_cycle
        buffer: List[QueuedPodInfo] = []
        for qp in qps:
            pod = self.store.get_pod(qp.pod.key())
            if pod is None or pod.spec.node_name or not self._responsible_for(pod):
                continue
            qp.pod = pod
            if self._wire_supported(pod):
                buffer.append(qp)
                continue
            # strict pop order: flush the wire batch before a fallback pod so
            # a lower-priority local pod never jumps a batched one
            self._flush_wire(buffer, pod_cycle, t0)
            buffer = []
            self.cache.update_snapshot(self.snapshot)
            self.schedule_one_pod(qp, pod_cycle)
        self._flush_wire(buffer, pod_cycle, t0)
        return len(qps)

    def _flush_wire(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        if not batch:
            return
        # one scheduling.cycle span per wire batch: the traceparent injected
        # below makes the server's device.sync/encode/dispatch/commit spans
        # children of this span — a single trace from pop to device commit
        with tracing.span("scheduling.cycle", batch=len(batch),
                          transport=type(self.client).__name__):
            self._flush_wire_traced(batch, pod_cycle, t0)

    def _flush_wire_traced(self, batch: List[QueuedPodInfo], pod_cycle: int, t0: float) -> None:
        self._push_deltas()
        from ..ops.tiebreak import seeds_for

        payload = {"apiVersion": API_VERSION,
                   "pods": [to_wire(qp.pod) for qp in batch],
                   "tieSeeds": [int(s) for s in seeds_for(batch)]}
        tp = tracing.format_traceparent()
        if tp:
            payload["traceparent"] = tp
        res = self.client.schedule_batch(payload)
        # hint-screen scaffolding, shared by every failed pod in the batch
        hint_names = hint_slot_of = None
        for qp, r in zip(batch, res["results"]):
            fwk = self.framework_for_pod(qp.pod)
            self.metrics["schedule_attempts"] += 1
            node_name = r.get("nodeName")
            if node_name:
                self.assume_and_bind(fwk, self._new_cycle_state(), qp, qp.pod,
                                     node_name, pod_cycle, t0=t0)
            else:
                d = Diagnosis()
                for name, plugin in (r.get("statuses") or {}).items():
                    reason = dict(_ATTRIBUTION_ORDER).get(plugin, "unschedulable")
                    d.node_to_status[name] = Status.unschedulable(reason).with_plugin(plugin)
                d.unschedulable_plugins.update(r.get("unschedulablePlugins") or ())
                state = self._new_cycle_state()
                hint = r.get("preempt")
                if hint is not None:
                    # rebuild the screen over OUR node names: candidates the
                    # service listed pass, every other known node fails,
                    # unknown (post-snapshot) nodes stay permissive. A None
                    # candidate list means the service truncated (screen
                    # inexact): pass everything and keep only the ranked
                    # best as the preferred-node fast path.
                    from ..framework.plugins.defaultpreemption import DefaultPreemption

                    if hint_slot_of is None:  # loop-invariant: build once
                        hint_names = list(self._sent_gens)
                        hint_slot_of = {n: i for i, n in enumerate(hint_names)}
                    if hint.get("candidates") is None:
                        row = np.ones(len(hint_names), bool)
                    else:
                        row = np.zeros(len(hint_names), bool)
                        for n in hint["candidates"]:
                            if n in hint_slot_of:
                                row[hint_slot_of[n]] = True
                    state.write(DefaultPreemption.HINTS_KEY,
                                (row, hint_slot_of, hint.get("best")))
                self._handle_scheduling_failure(
                    fwk, state, qp, Status.unschedulable("no feasible node"),
                    d, pod_cycle)
                self.smetrics.observe_attempt(
                    "unschedulable", fwk.profile_name, self.now_fn() - t0)

    def run_until_settled(self, max_cycles: int = 100000, flush: bool = True,
                          idle_wait: float = 0.005, max_no_progress: int = 200) -> int:
        # the shared batched settle loop (Scheduler.run_batched_until_settled),
        # incl. the idle-wait backoff for flapping pods
        return self.run_batched_until_settled(
            max_cycles=max_cycles, flush=flush, idle_wait=idle_wait,
            max_no_progress=max_no_progress)
